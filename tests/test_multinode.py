"""Out-of-process multi-node cluster tests.

Reference analog: ``python/ray/tests/test_multi_node*.py`` driven by
``cluster_utils.Cluster`` (``python/ray/cluster_utils.py:99``) — real
per-node daemons on one machine.  Here each external node is a real
``node_agent`` subprocess with its own shm store; objects genuinely cannot
be mmap'd across nodes, so these tests exercise the transfer path
(``object_manager.h:206`` analog), remote worker spawn (``worker_pool.h:156``
analog), and node-death recovery.
"""

import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy as NA,
)


@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=2)
    yield c
    c.shutdown()


@ray.remote
def _whoami():
    import ray_tpu

    return ray_tpu.get_runtime_context().node_id


@ray.remote
def _make_array(n):
    return np.arange(n, dtype=np.int64)


@ray.remote
def _total(x):
    return int(x.sum())


def test_task_runs_on_external_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    out = ray.get(
        _whoami.options(scheduling_strategy=NA(node_id=n1)).remote())
    assert out == n1


def test_object_transfer_head_to_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    big = np.arange(3_000_000, dtype=np.int64)
    ref = ray.put(big)  # lives in the head store
    s = ray.get(
        _total.options(scheduling_strategy=NA(node_id=n1)).remote(ref))
    assert s == int(big.sum())


def test_object_transfer_node_to_head_and_cross_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    n2 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1)).remote(5_000_000)
    got = ray.get(ref)  # node1 store -> head
    expect = int(np.arange(5_000_000, dtype=np.int64).sum())
    assert int(got.sum()) == expect
    # node1 store -> node2 consumer (through the head relay)
    s = ray.get(
        _total.options(scheduling_strategy=NA(node_id=n2)).remote(ref))
    assert s == expect


def test_actor_on_external_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)

    @ray.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def inc(self):
            self.x += 1
            return self.x

        def where(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().node_id

    a = Counter.options(scheduling_strategy=NA(node_id=n1)).remote()
    assert ray.get([a.inc.remote() for _ in range(3)]) == [1, 2, 3]
    assert ray.get(a.where.remote()) == n1


def test_agent_death_retries_elsewhere(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    big = np.arange(1_000_000, dtype=np.int64)
    ref = ray.put(big)

    @ray.remote(max_retries=3)
    def slow_total(x):
        time.sleep(2.0)
        return int(x.sum())

    f = slow_total.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(ref)
    time.sleep(0.8)
    cluster.kill_agent(n1)  # SIGKILL: no graceful shutdown
    assert ray.get(f, timeout=60) == int(big.sum())
    # the node is marked dead
    dead = [n for n in cluster.rt.list_nodes() if n["node_id"] == n1]
    assert dead and not dead[0]["alive"]


def test_node_local_objects_lost_on_agent_death(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1)).remote(2_000_000)
    ray.wait([ref], num_returns=1, timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    # The segment is gone with the node's store; without lineage
    # reconstruction this surfaces as ObjectLostError.  (Lineage recovery
    # turns this into a re-execution — covered in test_lineage.)
    try:
        got = ray.get(ref, timeout=30)
        assert int(got.sum()) == int(
            np.arange(2_000_000, dtype=np.int64).sum())
    except ray.exceptions.ObjectLostError:
        pass
