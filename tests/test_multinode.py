"""Out-of-process multi-node cluster tests.

Reference analog: ``python/ray/tests/test_multi_node*.py`` driven by
``cluster_utils.Cluster`` (``python/ray/cluster_utils.py:99``) — real
per-node daemons on one machine.  Here each external node is a real
``node_agent`` subprocess with its own shm store; objects genuinely cannot
be mmap'd across nodes, so these tests exercise the transfer path
(``object_manager.h:206`` analog), remote worker spawn (``worker_pool.h:156``
analog), and node-death recovery.
"""

import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy as NA,
)


@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=2)
    yield c
    c.shutdown()


@ray.remote
def _whoami():
    import ray_tpu

    return ray_tpu.get_runtime_context().node_id


@ray.remote
def _make_array(n):
    return np.arange(n, dtype=np.int64)


@ray.remote
def _total(x):
    return int(x.sum())


def test_task_runs_on_external_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    out = ray.get(
        _whoami.options(scheduling_strategy=NA(node_id=n1)).remote())
    assert out == n1


def test_object_transfer_head_to_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    big = np.arange(3_000_000, dtype=np.int64)
    ref = ray.put(big)  # lives in the head store
    s = ray.get(
        _total.options(scheduling_strategy=NA(node_id=n1)).remote(ref))
    assert s == int(big.sum())


def test_object_transfer_node_to_head_and_cross_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    n2 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1)).remote(5_000_000)
    got = ray.get(ref)  # node1 store -> head
    expect = int(np.arange(5_000_000, dtype=np.int64).sum())
    assert int(got.sum()) == expect
    # node1 store -> node2 consumer (through the head relay)
    s = ray.get(
        _total.options(scheduling_strategy=NA(node_id=n2)).remote(ref))
    assert s == expect


def test_actor_on_external_node(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)

    @ray.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def inc(self):
            self.x += 1
            return self.x

        def where(self):
            import ray_tpu

            return ray_tpu.get_runtime_context().node_id

    a = Counter.options(scheduling_strategy=NA(node_id=n1)).remote()
    assert ray.get([a.inc.remote() for _ in range(3)]) == [1, 2, 3]
    assert ray.get(a.where.remote()) == n1


def test_agent_death_retries_elsewhere(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    big = np.arange(1_000_000, dtype=np.int64)
    ref = ray.put(big)

    @ray.remote(max_retries=3)
    def slow_total(x):
        time.sleep(2.0)
        return int(x.sum())

    f = slow_total.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(ref)
    time.sleep(0.8)
    cluster.kill_agent(n1)  # SIGKILL: no graceful shutdown
    assert ray.get(f, timeout=60) == int(big.sum())
    # the node is marked dead
    dead = [n for n in cluster.rt.list_nodes() if n["node_id"] == n1]
    assert dead and not dead[0]["alive"]


def test_lineage_recovers_object_lost_with_node(cluster):
    """Kill the node holding a task's output: ray.get must transparently
    rebuild it by re-executing the creating task on a surviving node
    (reference: ObjectRecoveryManager, object_recovery_manager.h:41)."""
    n1 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(2_000_000)
    ray.wait([ref], num_returns=1, timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    got = ray.get(ref, timeout=60)
    assert int(got.sum()) == int(np.arange(2_000_000, dtype=np.int64).sum())
    # and it really was a re-execution, not a cached copy
    states = [e["state"] for e in cluster.rt.task_events]
    assert "RECONSTRUCTING" in states


def test_lineage_recovery_feeds_dependent_task(cluster):
    """A consumer task whose arg's segment died mid-flight gets the arg
    rebuilt via the owner's lineage (reference: pull-through-owner +
    recovery)."""
    n1 = cluster.add_node(num_cpus=2, external=True)
    n2 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(1_000_000)
    ray.wait([ref], num_returns=1, timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    s = ray.get(
        _total.options(scheduling_strategy=NA(node_id=n2)).remote(ref),
        timeout=60)
    assert s == int(np.arange(1_000_000, dtype=np.int64).sum())


def test_put_objects_are_not_recoverable(cluster):
    """ray.put has no lineage: losing its store surfaces ObjectLostError
    (reference semantics: only task returns reconstruct)."""
    n1 = cluster.add_node(num_cpus=2, external=True)

    @ray.remote
    def make_put():
        return ray.put(np.arange(1_000_000))  # > inline cutoff: shm-homed

    inner = ray.get(make_put.options(
        scheduling_strategy=NA(node_id=n1)).remote(), timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    with pytest.raises(ray.exceptions.ObjectLostError):
        ray.get(inner, timeout=30)


def test_direct_transfer_bypasses_head(cluster):
    """Cross-node object consumption pulls chunks straight from the home
    node's object server; the head brokers locations only.  Both the
    agent-relay counter and the worker-getparts counter must stay cold
    (reference: ObjectManager::Pull, object_manager.h:206)."""
    n1 = cluster.add_node(num_cpus=2, external=True)
    n2 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1)).remote(4_000_000)  # 32 MB
    ray.wait([ref], num_returns=1, timeout=60)
    base_relay = cluster.rt.relayed_segments
    base_broker = cluster.rt.brokered_parts

    # node2 worker consumes node1's object: direct agent->worker pull
    expect = int(np.arange(4_000_000, dtype=np.int64).sum())
    s = ray.get(
        _total.options(scheduling_strategy=NA(node_id=n2)).remote(ref),
        timeout=120)
    assert s == expect
    # driver consumes it too: direct agent->driver pull
    got = ray.get(ref, timeout=60)
    assert int(got.sum()) == expect

    assert cluster.rt.relayed_segments == base_relay, \
        "head relayed segment payloads"
    assert cluster.rt.brokered_parts == base_broker, \
        "worker fell back to head-brokered getparts"


def test_direct_transfer_throughput(cluster):
    """Mechanics check at real size: a ~128 MB segment crosses nodes in
    1 MB chunks without the head touching payload bytes.  (Throughput is
    asserted only loosely — CI boxes vary wildly.)"""
    n1 = cluster.add_node(num_cpus=2, external=True)
    ref = _make_array.options(
        scheduling_strategy=NA(node_id=n1)).remote(16_000_000)  # 128 MB
    ray.wait([ref], num_returns=1, timeout=120)
    base_relay = cluster.rt.relayed_segments
    t0 = time.time()
    got = ray.get(ref, timeout=120)
    dt = time.time() - t0
    assert got.shape[0] == 16_000_000
    assert cluster.rt.relayed_segments == base_relay
    assert dt < 60, f"128MB pull took {dt:.1f}s"
