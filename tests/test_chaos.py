"""Chaos-injection harness tests.

The acceptance battery from ROADMAP item 4: a cluster under injected
worker/agent kills keeps every ``ray.get`` correct (reconstruction +
retries absorbing the faults), ``RAY_TPU_CHAOS`` env rules kill spawned
processes deterministically at named syncpoints (mid-striped-pull worker
death), agent death mid-lease interacts with lease revocation, and the
whole battery re-runs under ``RAY_TPU_LOCKCHECK=1`` with zero cycles.

Reference analog: ``python/ray/_private/test_utils.py`` kill_raylet /
NodeKillerActor + the chaos_test release suites.
"""

import os
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu._private import recovery
from ray_tpu.chaos import ChaosController
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy as NA,
)


@ray.remote
def _stage1(i):
    return np.full(260_000, i, dtype=np.int64)  # ~2 MB: shm-homed


@ray.remote
def _stage2(a):
    time.sleep(0.05)
    return int(a[0]) * 10


# ------------------------------------------------------------ unit-level --

def test_controller_at_syncpoint_fires_nth():
    fired = []
    ctl = ChaosController.__new__(ChaosController)  # no runtime needed
    ctl._rt = None
    import threading

    ctl._lock = threading.Lock()
    ctl._timers = []
    ctl._net = None
    ctl._sync_actions = {}
    ctl._pending = []
    ctl._pending_ev = threading.Event()
    ctl._stopped = False
    ctl._runner = threading.Thread(target=ctl._run_loop, daemon=True)
    ctl._runner.start()
    recovery.set_chaos_hook(ctl._fire)
    try:
        ctl.at_syncpoint("probe", fired.append, "hit", n=3)
        for _ in range(2):
            recovery.syncpoint("probe")
        time.sleep(0.1)
        assert fired == []
        recovery.syncpoint("probe")
        deadline = time.time() + 2
        while not fired and time.time() < deadline:
            time.sleep(0.01)
        assert fired == ["hit"]
    finally:
        ctl.stop()
    assert not recovery.chaos_armed()


def test_env_rule_parse_ignores_garbage():
    rules = recovery.parse_chaos_rules(
        "worker:pull_chunk:3, bogus, agent:agent_msg:nope, driver:x:1")
    assert rules == [("worker", "pull_chunk", 3), ("driver", "x", 1)]


def test_syncpoint_is_noop_unarmed():
    assert not recovery.chaos_armed()
    recovery.syncpoint("anything")  # must not raise, must cost ~nothing


def test_chaos_fixture_kill_worker_mid_task_retries(ray_start_regular,
                                                    chaos_controller):
    """The pytest-fixture form of the harness: a mid-task worker kill
    is absorbed by the system-failure retry budget."""

    @ray.remote(max_retries=3)
    def slow(i):
        time.sleep(0.3)
        return i

    refs = [slow.remote(i) for i in range(4)]
    time.sleep(0.15)
    assert chaos_controller.kill_worker(mid_task=True) is not None
    assert ray.get(refs, timeout=60) == list(range(4))
    assert chaos_controller.stats()["chaos_kills"] == 1


# ------------------------------------------------------------ acceptance --

def test_chaos_acceptance_fanout_survives_worker_and_agent_kill():
    """THE acceptance scenario: 2-agent cluster, 40-task fan-out with a
    dependency chain, one mid-run worker kill AND one agent kill —
    every ray.get returns the correct value, reconstructions >= 1, and
    no ObjectLostError ever reaches the driver."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=0)
    chaos = None
    try:
        n1 = c.add_node(num_cpus=2, external=True)
        n2 = c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt)

        # Stage 1: 20 producers pinned across both nodes so the agent
        # kill is guaranteed to take some results with it.
        s1 = [_stage1.options(scheduling_strategy=NA(
            node_id=(n1 if i % 2 else n2), soft=True)).remote(i)
            for i in range(20)]
        ray.wait(s1, num_returns=len(s1), timeout=60)

        # Stage 2 (the dependency chain) starts; mid-run, kill a busy
        # worker AND the n2 agent — stage-2 tasks retry (system-failure
        # budget) and their lost stage-1 args reconstruct from lineage.
        s2 = [_stage2.remote(r) for r in s1]
        time.sleep(0.15)
        assert chaos.kill_worker(mid_task=True) is not None
        assert chaos.kill_agent(n2) == n2

        out = ray.get(s2, timeout=120)
        assert out == [i * 10 for i in range(20)]
        stats = c.rt.transfer_stats()
        assert stats["reconstructions"] >= 1, stats
        assert stats["chaos_kills"] == 2
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


def test_chaos_acceptance_recovery_off_reproduces_loss():
    """Same shape with recovery=off: the agent kill surfaces the legacy
    ObjectLostError and every recovery counter stays zero."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=0, _system_config={"recovery": False})
    try:
        n1 = c.add_node(num_cpus=2, external=True)
        n2 = c.add_node(num_cpus=2, external=True)
        s1 = [_stage1.options(scheduling_strategy=NA(
            node_id=n2, soft=True)).remote(i) for i in range(8)]
        ray.wait(s1, num_returns=len(s1), timeout=60)
        c.kill_agent(n2)  # not via the controller: counters must stay 0
        time.sleep(0.5)
        # The legacy failure shape: the loss surfaces — either directly
        # (driver-side pull) or as the consumer task's failure cause
        # (executor-side arg fetch).
        with pytest.raises((ray.exceptions.ObjectLostError,
                            ray.exceptions.TaskError)) as ei:
            ray.get([_stage2.remote(r) for r in s1], timeout=60)
        err = ei.value
        assert isinstance(err, ray.exceptions.ObjectLostError) or \
            isinstance(getattr(err, "cause", None),
                       ray.exceptions.ObjectLostError) or \
            "ObjectLostError" in str(err)
        stats = c.rt.transfer_stats()
        for k in ("reconstructions", "reconstruction_failures",
                  "actor_restarts", "chaos_kills"):
            assert stats[k] == 0, (k, stats[k])
    finally:
        c.shutdown()


# ------------------------------------------------- env-rule chaos kills --

def test_env_rule_kills_worker_mid_striped_pull():
    """A worker armed with ``worker:pull_chunk:2`` dies mid-stream while
    pulling a cross-node argument; the task retries on a fresh worker
    (the one-shot lockfile keeps the rule from re-firing) and the get
    succeeds.  This is the deterministic kill-mid-pull the wall-clock
    schedules can't hit reliably."""
    from ray_tpu.cluster_utils import Cluster

    chaos_dir = tempfile.mkdtemp()
    c = Cluster(head_num_cpus=2)
    try:
        n1 = c.add_node(num_cpus=2, external=True)
        n2 = c.add_node(
            num_cpus=2, external=True,
            env_overrides={"RAY_TPU_CHAOS": "worker:pull_chunk:2",
                           "RAY_TPU_CHAOS_DIR": chaos_dir})
        big = _stage1.options(
            scheduling_strategy=NA(node_id=n1, soft=False)).remote(7)
        ray.wait([big], num_returns=1, timeout=30)

        @ray.remote(max_retries=3)
        def consume(a):
            return int(a[0])

        # The n2 consumer pulls an ~2 MB segment (>= 2 chunks) from n1
        # and dies at chunk 2 of the stream.
        out = ray.get(consume.options(
            scheduling_strategy=NA(node_id=n2, soft=False)).remote(big),
            timeout=90)
        assert out == 7
        # The rule really fired: its one-shot lockfile was claimed by
        # the worker that died for it (a chaos test whose kill silently
        # missed proves nothing).
        claim = os.path.join(
            chaos_dir,
            f"ray_tpu_chaos_{c.rt.session_id}_worker_pull_chunk_2")
        assert os.path.exists(claim), "chaos env rule never fired"
    finally:
        c.shutdown()


def test_chaos_kill_agent_mid_lease_revocation_interplay():
    """Kill an agent whose workers are LEASED to a peer holder mid-push:
    the head revokes the leases (lease_revocations counts) and the
    holder's retries land the work elsewhere — completion, not loss."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=0)
    chaos = None
    try:
        n1 = c.add_node(num_cpus=1, external=True)
        n2 = c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt)
        kf = tempfile.mktemp()

        @ray.remote
        def coordinator(kill_file):
            @ray.remote
            def slow(i):
                time.sleep(0.25)
                return i * 3

            refs = [slow.remote(i) for i in range(16)]
            open(kill_file + ".ready", "w").write("x")
            return ray.get(refs)

        fut = coordinator.options(
            scheduling_strategy=NA(node_id=n1, soft=False),
            num_cpus=1).remote(kf)
        deadline = time.time() + 60
        while not os.path.exists(kf + ".ready") \
                and time.time() < deadline:
            time.sleep(0.05)
        assert os.path.exists(kf + ".ready")
        time.sleep(0.3)  # leases granted on n2, pushes in flight
        assert chaos.kill_agent(n2) == n2
        assert ray.get(fut, timeout=120) == [i * 3 for i in range(16)]
        stats = c.rt.transfer_stats()
        assert stats["lease_revocations"] >= 1, stats
        assert stats["chaos_kills"] >= 1
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


# --------------------------------------------------- lockcheck battery --

@pytest.mark.slow  # duplicate-coverage subprocess drill: the kill/
#                   restart/reconstruction machinery runs tier-1 in the
#                   acceptance tests above (and the failover battery),
#                   and the lock-order pins it checks have sub-second
#                   tier-1 representatives in tests/test_lockcheck.py;
#                   this re-run with the checker installed rides the
#                   slow lane next to the failover lockcheck battery
def test_chaos_battery_under_lockcheck_zero_cycles():
    """The chaos battery's single-host shape re-run with the lockdep
    checker installed: worker kill + actor restart + reconstruction
    machinery must introduce no lock-order cycles (the lineage-table
    leaf is additionally pinned in tests/test_lockcheck.py)."""
    code = textwrap.dedent("""
        import os, time
        import ray_tpu as ray
        from ray_tpu.devtools import lockcheck
        from ray_tpu.chaos import ChaosController
        assert lockcheck.enabled()
        rt = ray.init(num_cpus=2, num_tpus=0)
        chaos = ChaosController(rt)

        @ray.remote(max_retries=3)
        def f(i):
            time.sleep(0.02)
            return i + 1

        @ray.remote(max_restarts=1, max_task_retries=-1)
        class C:
            def __init__(self):
                self.n = 0
            def inc(self):
                self.n += 1
                return self.n
            def __ray_save__(self):
                return self.n
            def __ray_restore__(self, n):
                self.n = n

        c = C.remote()
        assert ray.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]
        refs = [f.remote(i) for i in range(24)]
        time.sleep(0.1)
        chaos.kill_worker(mid_task=True, actor=False)
        chaos.kill_worker(mid_task=False, actor=True)
        assert ray.get(refs, timeout=60) == list(range(1, 25))
        assert ray.get(c.inc.remote(), timeout=30) == 4  # restored
        stats = rt.transfer_stats()
        assert stats["chaos_kills"] >= 2
        assert stats["actor_restarts"] >= 1
        chaos.stop()
        ray.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        print("CHAOS_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    assert "CHAOS_LOCKCHECK_OK" in proc.stdout
