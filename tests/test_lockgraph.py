"""Whole-program lock-graph analyzer tests: the seeded-mutation battery
(each concurrency-contract-breaking edit to a COPY of the real tree
produces exactly the expected RTL6xx finding), the static-superset
cross-check against the runtime lockcheck's observed edges, the shared
leaf registry, and the CLI contract.

The fixture-level EXPECT coverage for RTL600-604 lives in
test_devtools_lint.py (the shared harness); this file owns the
whole-tree properties."""

import json
import os
import re
import shutil
import subprocess
import sys
import textwrap

import ray_tpu
from ray_tpu.devtools import lockcheck, lockgraph

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))


# -- registry agreement -----------------------------------------------------

def test_readme_lock_order_table_matches_generated_doc():
    """The README's LOCK ORDER table must equal `lockgraph --doc`
    byte-for-byte (regenerate with
    `python -m ray_tpu.devtools.lockgraph --doc` after changing any
    lock creation site or annotation) — the same no-drift contract the
    wire-protocol verb table carries."""
    readme = os.path.join(os.path.dirname(PKG_DIR), "README.md")
    with open(readme, "r", encoding="utf-8") as f:
        content = f.read()
    assert lockgraph.lock_order_doc() in content, (
        "README.md's LOCK ORDER table is stale — regenerate it with "
        "`python -m ray_tpu.devtools.lockgraph --doc`")


def test_leaf_registry_is_shared_with_runtime_lockcheck():
    """lockcheck consumes lockgraph's leaf sites verbatim — one source
    of truth, so the static and dynamic checkers cannot disagree about
    which locks are leaves."""
    static = lockgraph.leaf_sites()
    assert static, "tree has annotated leaves"
    assert lockcheck.leaf_registry(refresh=True) == static
    for site, name in static.items():
        path, line = site.rsplit(":", 1)
        assert os.path.isabs(path) and int(line) > 0, site
        # Every registered site line really carries the annotation.
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
        window = "".join(lines[max(0, int(line) - 2):int(line)])
        assert "lock-order: leaf" in window, (site, name)


def test_leaf_violations_use_the_static_registry(monkeypatch):
    """The dynamic leaf check flags an observed edge LEAVING a
    registered leaf site (the runtime counterpart of RTL602)."""
    lockcheck.install(raise_on_cycle=False)
    try:
        lockcheck.clear()
        import threading
        leaf = threading.Lock()
        other = threading.Lock()
        with leaf:
            with other:
                pass
        # Register the leaf's creation site as if it were annotated.
        (leaf_site,) = [frm for frm in lockcheck.edges()]
        monkeypatch.setattr(lockcheck, "_leaf_registry_cache",
                            {leaf_site: "test._leaf"})
        bad = lockcheck.leaf_violations()
        assert len(bad) == 1 and "test._leaf" in bad[0], bad
        exported = lockcheck.export_graph()
        assert exported["leaf_violations"] == bad
        assert [leaf_site, sorted(lockcheck.edges()[leaf_site])[0]] \
            in exported["edges"]
    finally:
        lockcheck.uninstall()


# -- static superset of observed runtime edges ------------------------------

def test_static_graph_is_superset_of_runtime_observed_edges():
    """Soundness cross-check: every lock-nesting edge the runtime
    lockcheck observes during a real init/task/actor/put workload —
    between creation sites the static analyzer knows — must already be
    in the static graph.  A missing edge means lockgraph's call-graph
    resolution lost a path the scheduler actually executed."""
    code = textwrap.dedent("""
        import json
        import ray_tpu
        from ray_tpu.devtools import lockcheck
        assert lockcheck.enabled()
        ray_tpu.init(num_cpus=2, num_tpus=0)

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get([f.remote(i) for i in range(4)]) == [1, 2, 3, 4]

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]
        ref = ray_tpu.put(list(range(50000)))
        assert len(ray_tpu.get(ref)) == 50000
        ray_tpu.shutdown()
        print("EDGES_JSON=" + json.dumps(lockcheck.export_graph()["edges"]))
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    match = re.search(r"EDGES_JSON=(\[.*\])", proc.stdout)
    assert match, proc.stdout[-2000:]
    observed = [tuple(e) for e in json.loads(match.group(1))]
    assert observed, "workload recorded no lock nestings at all"

    analysis = lockgraph.Analysis([PKG_DIR])
    known = set(analysis.known_sites())
    static_edges = analysis.site_edges()
    # Only edges between sites the static analyzer models are in scope:
    # Event/Queue-internal locks attribute to ray_tpu lines but are not
    # lock creation sites, and self-edges (two instances of one class)
    # are the runtime checker's own ABBA domain.
    in_scope = [(frm, to) for frm, to in observed
                if frm in known and to in known and frm != to]
    assert in_scope, (
        "no observed edge mapped to known static sites — the site "
        f"vocabularies diverged: observed={observed[:10]}")
    missing = [e for e in in_scope if e not in static_edges]
    assert not missing, (
        "runtime lockcheck observed lock-nesting edges the static "
        f"graph lacks (analyzer unsoundness): {missing}")


# -- seeded mutations -------------------------------------------------------

def _mutate(pkg: str, rel: str, old: str, new: str):
    path = os.path.join(pkg, rel)
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    assert old in src, f"mutation anchor vanished from {rel}: {old!r}"
    with open(path, "w", encoding="utf-8") as f:
        f.write(src.replace(old, new, 1))
    return path, src


def test_seeded_mutations_each_produce_the_expected_finding(tmp_path):
    """The acceptance battery: introducing a cross-path cycle, growing a
    declared leaf an edge, moving an Event.set inside a leaf body, and
    burying a pickle two calls deep under the runtime lock each produce
    exactly the expected RTL6xx class on an otherwise-clean copy of the
    shipped tree."""
    pkg = str(tmp_path / "ray_tpu")
    shutil.copytree(PKG_DIR, pkg,
                    ignore=shutil.ignore_patterns("__pycache__"))
    assert lockgraph.check_paths([pkg]) == [], \
        "the copied tree must be clean before any mutation"

    def run():
        return lockgraph.check_paths([pkg])

    # 1. Cross-acquire two worker-side leaves on different call paths ->
    #    RTL601 cycle (plus RTL602: both ends are declared leaves).
    path_a, orig_a = _mutate(
        pkg, "_private/worker_main.py",
        "            self.pending[req_id] = (slot, msg, time.monotonic())\n",
        "            self.pending[req_id] = (slot, msg, time.monotonic())\n"
        "            with self._xfer_lock:\n"
        "                pass\n")
    path_b, orig_b = _mutate(
        pkg, "_private/worker_main.py",
        "        with self._xfer_lock:\n"
        "            delta = {}\n",
        "        with self._xfer_lock:\n"
        "            with self.pending_lock:\n"
        "                pass\n"
        "            delta = {}\n")
    findings = run()
    assert any(f.rule == "RTL601" and "pending_lock" in f.message
               and "_xfer_lock" in f.message for f in findings), findings
    assert any(f.rule == "RTL602" for f in findings), findings
    # Same file mutated twice: restore in REVERSE order (orig_b still
    # contains mutation a; orig_a is pristine).
    with open(path_b, "w", encoding="utf-8") as f:
        f.write(orig_b)
    with open(path_a, "w", encoding="utf-8") as f:
        f.write(orig_a)

    # 2. Grow the dispatch-dirty leaf an outgoing edge -> RTL602 naming
    #    the leaf and its annotation site.
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        "            else:\n"
        "                self._dispatch_dirty.update(keys)\n",
        "            else:\n"
        "                self._dispatch_dirty.update(keys)\n"
        "            with self._dirty_lock:\n"
        "                pass\n")
    findings = run()
    assert any(f.rule == "RTL602" and "_dispatch_dirty_lock" in f.message
               and "_dirty_lock" in f.message for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 3. Move the dispatch Event.set INSIDE the leaf body -> RTL603 (the
    #    convention every PR pinned by hand: signal after release).
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        "                self._dispatch_dirty.update(keys)\n"
        "        self._dispatch_event.set()\n",
        "                self._dispatch_dirty.update(keys)\n"
        "            self._dispatch_event.set()\n")
    findings = run()
    assert any(f.rule == "RTL603" and "_dispatch_dirty_lock" in f.message
               for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 4. Bury a pickle two calls deep under the runtime lock -> RTL604
    #    anchored at the IO site, path named in the message (lexical
    #    RTL402 cannot see this).
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        '    def _mark_dirty(self, worker: "WorkerHandle"):\n',
        "    def _lg_mut_outer(self):\n"
        "        self._lg_mut_inner()\n"
        "\n"
        "    def _lg_mut_inner(self):\n"
        "        serialization.dumps_inline([1])\n"
        "\n"
        '    def _mark_dirty(self, worker: "WorkerHandle"):\n')
    path2, orig2 = _mutate(
        pkg, "_private/runtime.py",
        "                with self.lock:\n"
        "                    self._dispatch_locked(keys)\n",
        "                with self.lock:\n"
        "                    self._lg_mut_outer()\n"
        "                    self._dispatch_locked(keys)\n")
    findings = run()
    assert any(f.rule == "RTL604" and "dumps_inline" in f.message
               and "_lg_mut_outer" in f.message for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)
    with open(path2, "w", encoding="utf-8") as f:
        f.write(orig2)

    assert run() == [], "restores must return the copy to clean"


def test_reasonless_lockgraph_suppression_is_flagged(tmp_path):
    bad = tmp_path / "bad_noqa.py"
    bad.write_text(
        "import threading\n\n\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()  # lock-order: leaf\n"
        "        self._b_lock = threading.Lock()\n\n"
        "    def f(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:  # noqa: RTL602\n"
        "                pass\n")
    findings = lockgraph.check_paths([str(bad)])
    assert [f.rule for f in findings] == ["RTL600"]
    # With a reason, the suppression stands.
    bad.write_text(bad.read_text().replace(
        "# noqa: RTL602", "# noqa: RTL602 -- handoff proven by test_x"))
    assert lockgraph.check_paths([str(bad)]) == []


# -- CLI contract -----------------------------------------------------------

def test_cli_exits_nonzero_on_bad_fixture_with_rule_and_line():
    bad = os.path.join(os.path.dirname(__file__), "lint_fixtures",
                       "bad_lockgraph.py")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lockgraph", bad],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "RTL601" in proc.stdout
    assert re.search(r"bad_lockgraph\.py:\d+:\d+", proc.stdout)


def test_cli_doc_renders_lock_order_table():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lockgraph", "--doc"],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert "| lock | kind | created at |" in proc.stdout
    for needle in ("runtime.Runtime.lock",
                   "runtime.Runtime._dispatch_dirty_lock", "leaf",
                   "io-guard"):
        assert needle in proc.stdout, needle


def test_cli_dump_lists_locks_edges_and_spawns():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lockgraph", "--dump",
         PKG_DIR],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert "== locks" in proc.stdout
    assert "== edges" in proc.stdout
    assert "== spawn edges" in proc.stdout
    assert "runtime.Runtime.lock" in proc.stdout


def test_main_select_filters_rules(tmp_path, capsys):
    bad = tmp_path / "bad_select.py"
    bad.write_text(
        "import threading\n\n\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()  # lock-order: leaf\n"
        "        self._b_lock = threading.Lock()\n\n"
        "    def f(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                pass\n")
    assert lockgraph.main([str(bad)]) == 1
    assert "RTL602" in capsys.readouterr().out
    assert lockgraph.main(["--select=RTL601", str(bad)]) == 0
    assert capsys.readouterr().out.strip() == ""


def test_main_exit_codes(capsys):
    assert lockgraph.main([]) == 2
    capsys.readouterr()
    assert lockgraph.main(["no_such_dir/"]) == 2
    assert "no such path" in capsys.readouterr().err
    assert lockgraph.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in lockgraph.RULES:
        assert rule_id in out
    assert lockgraph.main(["--select=RTL9", PKG_DIR]) == 2
    assert "matches no rule" in capsys.readouterr().err
