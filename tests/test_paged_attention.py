"""Paged (block-table) decode attention kernel vs the oracles.

Convention from test_ops.py: every kernel is pinned against an XLA/host
reference, pallas running in interpret mode on the CPU backend — the
same code path that compiles for TPU.  The randomized battery covers
arbitrary (shuffled, non-contiguous) block tables, ragged last blocks,
padding table entries past the context, trailing-window masking, and
the ``window=1`` exact-gather identity the serving engine's bitwise
pin rides on.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.ops.attention import mha_reference
from ray_tpu.ops.paged_attention import (
    paged_attention, paged_attention_reference)


def _random_paged(rng, B, h, d, bs, num_blocks, max_ctx):
    """Random cache + per-seq block tables (shuffled physical ids,
    ragged lengths, arbitrary padding entries past the last page)."""
    q = jnp.asarray(rng.normal(size=(B, h, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(num_blocks, bs, h, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(num_blocks, bs, h, d)), jnp.float32)
    cls = rng.integers(1, max_ctx + 1, size=B).astype(np.int32)
    width = -(-int(cls.max()) // bs)
    perm = rng.permutation(num_blocks)
    assert B * width <= num_blocks, "test sizing: disjoint tables"
    bt = perm[: B * width].reshape(B, width).astype(np.int32)
    # Overwrite the dead tail of each row with arbitrary (valid) ids:
    # the kernel must never read meaning into entries past the context.
    for b in range(B):
        pages = -(-int(cls[b]) // bs)
        bt[b, pages:] = rng.integers(0, num_blocks, size=width - pages)
    return q, kc, vc, bt, cls


def _gathered(kc, vc, bt, cls, b, bs):
    n = int(cls[b])
    pages = bt[b, : -(-n // bs)]
    k = np.asarray(kc)[pages].reshape(-1, *kc.shape[2:])[:n]
    v = np.asarray(vc)[pages].reshape(-1, *vc.shape[2:])[:n]
    return jnp.asarray(k), jnp.asarray(v), n


@pytest.mark.parametrize("h,d,bs", [(1, 32, 8), (4, 32, 8), (2, 64, 16)])
def test_paged_attention_matches_mha_reference(h, d, bs):
    """Randomized block tables (incl. ragged last blocks): the paged
    kernel must match the contiguous-gather mha_reference oracle."""
    rng = np.random.default_rng(42)
    for trial in range(3):
        q, kc, vc, bt, cls = _random_paged(
            rng, B=3, h=h, d=d, bs=bs, num_blocks=24, max_ctx=5 * bs - 3)
        out = paged_attention(q, kc, vc, bt, cls, interpret=True)
        for b in range(q.shape[0]):
            k, v, n = _gathered(kc, vc, bt, cls, b, bs)
            # One decode query at position n-1 attending to the whole
            # context == causal attention with q_offset = n-1.
            ref = mha_reference(q[b][None, None], k[None], v[None],
                                causal=True, q_offset=n - 1)
            assert float(jnp.max(jnp.abs(out[b] - ref[0, 0]))) < 1e-5, \
                (trial, b)


def test_paged_attention_matches_xla_reference_and_window():
    rng = np.random.default_rng(7)
    q, kc, vc, bt, cls = _random_paged(
        rng, B=4, h=2, d=16, bs=8, num_blocks=32, max_ctx=29)
    for window in (0, 1, 5, 13):
        out = paged_attention(q, kc, vc, bt, cls, window=window,
                              interpret=True)
        ref = paged_attention_reference(q, kc, vc, bt, cls,
                                        window=window)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5, window


def test_paged_attention_window1_is_bitwise_gather():
    """window=1: softmax over a single position is exactly 1.0, so the
    output is BITWISE the stored v row — the identity the paged decode
    mode's greedy-chain pin is built on."""
    rng = np.random.default_rng(3)
    q, kc, vc, bt, cls = _random_paged(
        rng, B=5, h=1, d=32, bs=8, num_blocks=48, max_ctx=40)
    out = np.asarray(paged_attention(q, kc, vc, bt, cls, window=1,
                                     interpret=True))
    for b in range(out.shape[0]):
        n = int(cls[b])
        blk = int(bt[b, (n - 1) // 8])
        last = np.asarray(vc)[blk, (n - 1) % 8]
        assert (out[b] == last).all(), b


def test_paged_attention_ragged_single_token_context():
    """context_len=1 with a one-entry table: the smallest legal shape
    (a request admitted with a single prompt token)."""
    rng = np.random.default_rng(11)
    kc = jnp.asarray(rng.normal(size=(4, 8, 1, 16)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(4, 8, 1, 16)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(1, 1, 16)), jnp.float32)
    bt = np.asarray([[2]], np.int32)
    cls = np.asarray([1], np.int32)
    out = paged_attention(q, kc, vc, bt, cls, interpret=True)
    # Softmax over one position: exactly the first row of block 2.
    assert (np.asarray(out)[0] == np.asarray(vc)[2, 0]).all()


def test_paged_attention_interpret_default_off_tpu():
    """interpret=None resolves to interpret mode off-TPU (the repo
    convention: the same kernel path is tested on CPU)."""
    assert jax.default_backend() != "tpu"
    rng = np.random.default_rng(1)
    q, kc, vc, bt, cls = _random_paged(
        rng, B=2, h=1, d=16, bs=8, num_blocks=16, max_ctx=20)
    out = paged_attention(q, kc, vc, bt, cls)  # no explicit interpret
    ref = paged_attention_reference(q, kc, vc, bt, cls)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5
