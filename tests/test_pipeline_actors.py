"""Distributed pipeline-parallel training tests.

Pins the ISSUE 18 acceptance criteria: the distributed 1F1B schedule
over stage actors matches ``parallel.pipeline.pipeline_apply`` (and the
single-host fallback) BITWISE on integer-valued float32 training; a
killed mid-pipeline stage restores from its ``__ray_save__`` checkpoint
with bounded loss-step replay and zero object loss at the driver; the
``distributed_training`` master switch off runs the byte-identical
single-host path with every new counter zero.
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.train.pipeline_actors import (
    PipelineTrainer, _split_microbatches, train_stats,
)


# Module-level so cloudpickled actor ctors resolve them by reference.
def _stage_fn(sp, x):
    import jax

    def layer(carry, w):
        return (carry @ w), None

    y, _ = jax.lax.scan(layer, x, sp["w"])
    return y


def _loss_fn(y, t):
    import jax.numpy as jnp

    # Mean over elements; with integer-valued data every term is an
    # exact small rational (denominator a power of two) -> bitwise-
    # reproducible across summation orders.
    return jnp.sum(y - t) / y.size


def _int_data(seed=0, D=4, B=8, L=4):
    rng = np.random.default_rng(seed)
    w = rng.integers(-2, 3, size=(L, D, D)).astype(np.float32)
    x = rng.integers(-2, 3, size=(B, D)).astype(np.float32)
    t = rng.integers(-2, 3, size=(B, D)).astype(np.float32)
    return w, x, t


def _sgd_trainer(w, num_microbatches=4, **kw):
    import optax

    return PipelineTrainer(
        _stage_fn, _loss_fn, [{"w": w[:2]}, {"w": w[2:]}],
        optimizer=optax.sgd(1.0), num_microbatches=num_microbatches, **kw)


def test_1f1b_schedule_shape_and_stash_bound():
    """Warmup is min(pp-1-s, M) forwards; each B(i) follows F(i); the
    live activation stash never exceeds pp entries."""
    w, _, _ = _int_data()
    tr = _sgd_trainer(w, num_microbatches=6, distributed=False)
    tr._pp = 4  # schedule shape is pure arithmetic over (pp, M, s)
    for s in range(4):
        seq = tr._stage_sched(s)
        warmup = 0
        for kind, _ in seq:
            if kind != "F":
                break
            warmup += 1
        # Leading forward run = warmup forwards plus the first steady-
        # state forward (1F1B pairs start with F).
        assert warmup == min(min(4 - 1 - s, 6) + 1, 6)
        assert len(seq) == 2 * 6
        live, high = 0, 0
        done_f, done_b = set(), set()
        for kind, i in seq:
            if kind == "F":
                done_f.add(i)
                live += 1
            else:
                assert i in done_f, "backward before its forward"
                done_b.add(i)
                live -= 1
            high = max(high, live)
        assert done_f == done_b == set(range(6))
        assert high <= 4, f"stage {s}: {high} live stashes > pp"


def test_distributed_1f1b_bitwise_vs_pipeline_apply(ray_start_regular):
    """The acceptance pin: distributed 1F1B loss and per-stage grads are
    bitwise-equal to ``pipeline_apply`` on one host (pp=2 mesh) and to
    the single-host fallback, for integer-valued float32 weights."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import MeshConfig, make_mesh
    from ray_tpu.parallel.pipeline import pipeline_apply

    w, x, t = _int_data()
    M = 4

    tr = _sgd_trainer(w, num_microbatches=M)
    assert tr.distributed
    before = tr.get_stage_params()
    metrics = tr.step(x, t)
    after = tr.get_stage_params()
    # sgd(lr=1.0): the applied update IS the mean micro-batch gradient.
    dist_grads = [b["w"] - a["w"] for b, a in zip(before, after)]
    tr.shutdown()

    # Reference 1: pipeline_apply (in-XLA GPipe over the pp mesh axis).
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])
    stacked = {"w": w.reshape(2, 2, *w.shape[1:])}

    def ref_loss(sp):
        y = pipeline_apply(_stage_fn, sp, jnp.asarray(x), mesh=mesh,
                           num_microbatches=M)
        return _loss_fn(y, jnp.asarray(t))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(stacked)
    assert np.float32(metrics["loss"]) == np.float32(ref_l)
    for s in range(2):
        np.testing.assert_array_equal(dist_grads[s],
                                      np.asarray(ref_g["w"][s]))

    # Reference 2: the single-host fallback (master-switch-off path).
    tr2 = _sgd_trainer(w, num_microbatches=M, distributed=False)
    m2 = tr2.step(x, t)
    assert np.float32(m2["loss"]) == np.float32(ref_l)
    for a, b in zip(after, tr2.get_stage_params()):
        np.testing.assert_array_equal(a["w"], b["w"])

    # Counters flowed worker -> head: (pp-1) * M activations forward
    # plus (pp-1) * M grads backward.
    time.sleep(1.2)
    st = ray_start_regular.transfer_stats()
    assert st["microbatch_pushes"] >= 2 * M
    assert st["stage_restarts"] == 0
    assert st["learner_queue_stalls"] == 0


def test_transfer_stats_has_training_counters(ray_start_regular):
    st = ray_start_regular.transfer_stats()
    for k in ("microbatch_pushes", "stage_restarts",
              "learner_queue_stalls"):
        assert st[k] == 0


def test_switch_off_is_single_host_with_zero_counters():
    """Master switch off: PipelineTrainer falls back to the single-host
    path, the knobs ride _system_config -> _worker_config_env into
    spawned workers, and every new counter stays zero (pinned)."""
    rt = ray.init(num_cpus=4, _system_config={
        "distributed_training": False,
        "pipeline_microbatches": 6,
        "impala_queue_depth": 0,
    })
    try:
        @ray.remote
        def probe():
            import os

            return (os.environ.get("RAY_TPU_DISTRIBUTED_TRAINING"),
                    os.environ.get("RAY_TPU_PIPELINE_MICROBATCHES"),
                    os.environ.get("RAY_TPU_IMPALA_QUEUE_DEPTH"))

        assert ray.get(probe.remote(), timeout=60) == ("0", "6", "0")

        w, x, t = _int_data()
        tr = _sgd_trainer(w, num_microbatches=0)  # 0 -> config knob (6)
        assert not tr.distributed
        assert tr.num_microbatches == 6
        # 6 microbatches don't divide batch 8 -> use 4 explicitly.
        tr = _sgd_trainer(w)
        tr.step(x, t)
        time.sleep(1.0)
        st = rt.transfer_stats()
        assert st["microbatch_pushes"] == 0
        assert st["stage_restarts"] == 0
        assert st["learner_queue_stalls"] == 0
    finally:
        ray.shutdown()


@pytest.mark.slow
def test_inflight_replay_after_stage_kill(ray_start_regular):
    """Kill the last stage between steps: the actor restores from its
    ``__ray_save__`` checkpoint, in-flight calls replay in order, and
    the training trajectory is bitwise-identical to an uninterrupted
    distributed run."""
    w, x, t = _int_data()
    tr = _sgd_trainer(w)
    losses = [tr.step(x, t)["loss"] for _ in range(2)]
    pids = tr.stage_pids()
    time.sleep(0.5)  # let the post-call checkpoint message land
    os.kill(pids[1], 9)
    losses += [tr.step(x, t)["loss"] for _ in range(2)]
    final = tr.get_stage_params()
    tr.shutdown()

    tr2 = _sgd_trainer(w)
    ref = [tr2.step(x, t)["loss"] for _ in range(4)]
    assert [np.float32(v) for v in losses] == [np.float32(v) for v in ref]
    for a, b in zip(final, tr2.get_stage_params()):
        np.testing.assert_array_equal(a["w"], b["w"])
    tr2.shutdown()

    time.sleep(1.2)
    assert ray_start_regular.transfer_stats()["stage_restarts"] >= 1


@pytest.mark.slow
def test_chaos_mid_epoch_kill_bounded_replay(ray_start_regular):
    """Chaos drill: kill a mid-pipeline stage WHILE a step is running,
    mid-epoch.  The epoch completes (bounded re-drive, idempotent
    apply_grads), no ObjectLostError reaches the driver, and the
    trajectory matches an uninterrupted distributed run bitwise."""
    w, x, t = _int_data()
    tr = _sgd_trainer(w)
    losses = [tr.step(x, t)["loss"] for _ in range(2)]
    pids = tr.stage_pids()
    time.sleep(0.5)

    def killer():
        time.sleep(0.15)
        os.kill(pids[1], 9)

    th = threading.Thread(target=killer)
    th.start()
    # The kill lands while this step's schedule is in flight.
    losses.append(tr.step(x, t)["loss"])
    th.join()
    losses.append(tr.step(x, t)["loss"])
    stats = tr.stage_stats()
    assert [s["applied_step"] for s in stats] == [3, 3]
    assert all(s["stash"] == 0 for s in stats)
    final = tr.get_stage_params()
    tr.shutdown()

    tr2 = _sgd_trainer(w)
    ref = [tr2.step(x, t)["loss"] for _ in range(4)]
    assert [np.float32(v) for v in losses] == [np.float32(v) for v in ref]
    for a, b in zip(final, tr2.get_stage_params()):
        np.testing.assert_array_equal(a["w"], b["w"])
    tr2.shutdown()

    time.sleep(1.2)
    st = ray_start_regular.transfer_stats()
    assert st["stage_restarts"] >= 1


def test_fill_drain_schedule_matches_1f1b(ray_start_regular):
    """The bench baseline computes the same step: fill/drain wave
    barriers produce bitwise-identical grads to 1F1B."""
    w, x, t = _int_data(seed=3)
    tr = _sgd_trainer(w)
    m1 = tr.step(x, t, schedule="fill_drain")
    p_fd = tr.get_stage_params()
    tr.shutdown()
    tr2 = _sgd_trainer(w)
    m2 = tr2.step(x, t, schedule="1f1b")
    assert np.float32(m1["loss"]) == np.float32(m2["loss"])
    for a, b in zip(p_fd, tr2.get_stage_params()):
        np.testing.assert_array_equal(a["w"], b["w"])
    tr2.shutdown()


def test_split_microbatches_rejects_ragged():
    with pytest.raises(ValueError):
        _split_microbatches(np.zeros((7, 3)), 2)


def test_llama_pipeline_stage_helpers():
    """models.llama pipeline helpers: stage splitting covers every
    layer once; stage_fn composition equals the monolithic forward."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama as L

    cfg = L.LlamaConfig.tiny()
    params = L.init_params(jax.random.PRNGKey(0), cfg)
    sps = L.pipeline_stage_params(params, 2)
    assert "embed" in sps[0] and "lm_head" in sps[1]
    assert "embed" not in sps[1] and "lm_head" not in sps[0]
    stage_fn = L.make_pipeline_stage_fn(cfg)
    tok = jnp.asarray(
        (np.arange(2 * 8).reshape(2, 8) % cfg.vocab_size).astype(np.int32))
    y = tok
    for sp in sps:
        y = stage_fn(sp, y)
    ref_logits, _ = L.forward(params, tok, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-5)
    targets = (tok + 1) % cfg.vocab_size
    loss = L.make_pipeline_loss_fn(cfg)(y, targets)
    _, ref_metrics = L.loss_fn(params, {"inputs": tok, "targets": targets},
                               cfg)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(ref_metrics["loss"]),
                               rtol=2e-5, atol=2e-5)
