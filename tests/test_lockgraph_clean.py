"""Clean-tree gate for the static lock-graph analyzer: the shipped tree
must carry zero un-suppressed RTL6xx findings (every suppression with a
'-- reason' tail), inside a tier-1-friendly time budget — the lockgraph
twin of test_lint_clean.py, wired through the same merged
`python -m ray_tpu.devtools.check` engine."""

import os
import time

import ray_tpu
from ray_tpu.devtools import lockgraph

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def test_tree_is_lockgraph_clean_within_budget():
    """`python -m ray_tpu.devtools.lockgraph ray_tpu/ tests/` must exit
    0 on the shipped tree, and the whole-program analysis (parse, call
    graph, fixpoint, region walk) must stay inside its 10 s budget so
    the gate is cheap enough to keep in tier-1."""
    start = time.monotonic()
    findings = lockgraph.check_paths([PKG_DIR, TESTS_DIR])
    elapsed = time.monotonic() - start
    assert findings == [], (
        "lockgraph found un-suppressed RTL6xx findings (fix them, or "
        "suppress with '# noqa: <RULE-ID> -- reason'):\n"
        + "\n".join(repr(f) for f in findings))
    assert elapsed < 10.0, (
        f"lockgraph took {elapsed:.1f}s over ray_tpu/ + tests/ — the "
        f"tier-1 gate budget is 10s")


def test_tree_has_lock_annotations_and_edges():
    """Guard the analysis against silently degrading into a no-op: the
    real tree must keep producing a substantial lock inventory, leaf
    registry, and edge set (a parser regression that drops every lock
    would otherwise still 'sweep clean')."""
    analysis = lockgraph.Analysis([PKG_DIR])
    assert len(analysis.locks) >= 30, len(analysis.locks)
    assert len(analysis.leaf_sites()) >= 10, analysis.leaf_sites()
    assert len(analysis.edges) >= 10, len(analysis.edges)
    kinds = {ld.kind for ld in analysis.locks.values()}
    assert "leaf" in kinds and "io-guard" in kinds
