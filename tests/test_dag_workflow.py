"""DAG API + Workflow tests.

Reference patterns: ``python/ray/dag/tests`` (bind/execute graphs) and
``python/ray/workflow/tests`` (durable resume skips completed steps).
"""

import os
import time

import pytest

import ray_tpu as ray
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def ray8(tmp_path):
    rt = ray.init(num_cpus=8)
    workflow.init(str(tmp_path / "wf"))
    yield rt
    ray.shutdown()


@ray.remote
def add(a, b):
    return a + b


@ray.remote
def double(x):
    return x * 2


def test_function_dag_execute(ray8):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), double.bind(3))
    # (5*2) + (3*2)
    assert ray.get(dag.execute(5), timeout=60) == 16


def test_dag_node_runs_once_per_execute(ray8):
    marker = f"/tmp/rtpu_dag_{os.getpid()}"
    if os.path.exists(marker):
        os.remove(marker)

    @ray.remote
    def effect():
        with open(marker, "a") as f:
            f.write("x")
        return 1

    shared = effect.bind()
    dag = add.bind(shared, shared)  # diamond: shared executes ONCE
    assert ray.get(dag.execute(), timeout=60) == 2
    assert open(marker).read() == "x"
    os.remove(marker)


def test_actor_dag(ray8):
    @ray.remote
    class Acc:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    node = Acc.bind(10)
    dag = node.add.bind(node.add.bind(5))  # 10+5=15, then +15=30
    assert ray.get(dag.execute(), timeout=60) == 30


def test_workflow_run_and_output(ray8):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 1)
    out = workflow.run(dag, workflow_id="w1", input_value=4)
    assert out == 9
    assert workflow.get_status("w1") == "SUCCESSFUL"
    assert workflow.get_output("w1") == 9
    assert ("w1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(ray8):
    marker = f"/tmp/rtpu_wf_{os.getpid()}"
    for suffix in ("a", "b"):
        if os.path.exists(marker + suffix):
            os.remove(marker + suffix)

    @ray.remote
    def step_a():
        with open(marker + "a", "a") as f:
            f.write("x")
        return 10

    @ray.remote
    def step_b(v):
        with open(marker + "b", "a") as f:
            f.write("x")
        if len(open(marker + "b").read()) == 1:
            raise RuntimeError("transient failure")  # first attempt dies
        return v + 1

    dag = step_b.bind(step_a.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"
    out = workflow.resume("w2")
    assert out == 11
    # step_a checkpointed on attempt 1 and was NOT re-executed by resume
    assert open(marker + "a").read() == "x"
    assert open(marker + "b").read() == "xx"
    os.remove(marker + "a")
    os.remove(marker + "b")


def test_workflow_rerun_same_id_loads_checkpoints(ray8):
    marker = f"/tmp/rtpu_wf2_{os.getpid()}"
    if os.path.exists(marker):
        os.remove(marker)

    @ray.remote
    def once():
        with open(marker, "a") as f:
            f.write("x")
        return 7

    dag = double.bind(once.bind())
    assert workflow.run(dag, workflow_id="w3") == 14
    # run again with the SAME id: everything loads from checkpoints
    assert workflow.run(dag, workflow_id="w3") == 14
    assert open(marker).read() == "x"
    os.remove(marker)


def test_workflow_run_async_and_delete(ray8):
    with InputNode() as inp:
        dag = double.bind(inp)
    fut = workflow.run_async(dag, workflow_id="w4", input_value=21)
    assert fut.result(timeout=60) == 42
    workflow.delete("w4")
    assert workflow.get_status("w4") == "NOT_FOUND"
