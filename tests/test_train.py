"""Train-layer tests (reference pattern: python/ray/train/tests/
test_backend.py, test_data_parallel_trainer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.air import Checkpoint, ScalingConfig, RunConfig, FailureConfig
from ray_tpu.air import session as air_session
from ray_tpu.train import DataParallelTrainer, JaxConfig


@pytest.fixture
def ray4():
    rt = ray.init(num_cpus=6)
    yield rt
    ray.shutdown()


def test_checkpoint_morphing(tmp_path):
    data = {"params": {"w": np.arange(6.0).reshape(2, 3)}, "step": 7}
    ck = Checkpoint.from_dict(data)
    d = ck.to_directory(str(tmp_path / "ck"))
    back = Checkpoint.from_directory(d).to_dict()
    assert back["step"] == 7
    assert np.allclose(back["params"]["w"], data["params"]["w"])
    again = Checkpoint.from_bytes(ck.to_bytes()).to_dict()
    assert again["step"] == 7


def test_checkpoint_jax_arrays():
    ck = Checkpoint.from_dict({"w": jnp.ones((2, 2))})
    out = Checkpoint.from_bytes(ck.to_bytes()).to_dict()
    assert np.allclose(out["w"], 1.0)


def _sgd_loop(config):
    """Tiny numpy regression loop using the session API."""
    rng = np.random.default_rng(0)
    w = np.zeros(4)
    ckpt = air_session.get_checkpoint()
    start = 0
    if ckpt is not None:
        st = ckpt.to_dict()
        w, start = st["w"], st["step"]
    x = rng.normal(size=(64, 4))
    y = x @ np.array([1.0, -2.0, 3.0, 0.5])
    for step in range(start, config["steps"]):
        g = 2 * x.T @ (x @ w - y) / len(x)
        w -= config["lr"] * g
        loss = float(np.mean((x @ w - y) ** 2))
        air_session.report(
            {"loss": loss, "step": step,
             "rank": air_session.get_world_rank()},
            checkpoint=Checkpoint.from_dict({"w": w, "step": step + 1}))


def test_data_parallel_trainer_single_worker(ray4):
    trainer = DataParallelTrainer(
        _sgd_loop, train_loop_config={"steps": 5, "lr": 0.05},
        backend_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.metrics["loss"] < 5.0
    assert len(result.metrics_history) == 5
    st = result.checkpoint.to_dict()
    assert st["step"] == 5


def test_data_parallel_trainer_two_workers(ray4):
    trainer = DataParallelTrainer(
        _sgd_loop, train_loop_config={"steps": 3, "lr": 0.05},
        backend_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics["rank"] == 0
    assert len(result.metrics_history) == 3


def test_resume_from_checkpoint(ray4):
    trainer = DataParallelTrainer(
        _sgd_loop, train_loop_config={"steps": 3, "lr": 0.05},
        backend_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1))
    r1 = trainer.fit()
    trainer2 = DataParallelTrainer(
        _sgd_loop, train_loop_config={"steps": 6, "lr": 0.05},
        backend_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1),
        resume_from_checkpoint=r1.checkpoint)
    r2 = trainer2.fit()
    # resumed at step 3, ran 3 more
    assert len(r2.metrics_history) == 3
    assert r2.checkpoint.to_dict()["step"] == 6
    assert r2.metrics["loss"] < r1.metrics["loss"]


def _failing_loop(config):
    import os
    rank = air_session.get_world_rank()
    ckpt = air_session.get_checkpoint()
    attempt = ckpt.to_dict()["attempt"] if ckpt else 0
    if attempt == 0 and rank == 0 and not os.environ.get("_RT_NO_CRASH"):
        air_session.report(
            {"phase": "precrash"},
            checkpoint=Checkpoint.from_dict({"attempt": 1}))
        os._exit(1)  # simulate worker death mid-training
    air_session.report({"phase": "done", "attempt": attempt},
                       checkpoint=Checkpoint.from_dict({"attempt": attempt}))


def test_failure_config_group_restart(ray4):
    """Reference: FailureConfig(max_failures) + group restart
    (backend_executor.py:522)."""
    trainer = DataParallelTrainer(
        _failing_loop, train_loop_config={},
        backend_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["phase"] == "done"
    assert result.metrics["attempt"] == 1  # restarted from the checkpoint


def _jax_distributed_loop(config):
    """Real multi-process SPMD: every worker joins one jax.distributed
    cluster; psum over the global (2-process CPU) mesh."""
    import jax
    import jax.numpy as jnp
    n = jax.process_count()
    rank = jax.process_index()
    total = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
        jnp.ones((jax.local_device_count(), 1)))
    air_session.report({"procs": n, "rank": rank,
                        "local_devices": jax.local_device_count(),
                        "global_devices": jax.device_count(),
                        "psum": float(total[0][0])})


@pytest.mark.slow
def test_jax_distributed_backend_two_processes(ray4):
    """The NCCL-seam replacement (SURVEY.md §2.3): jax.distributed
    rendezvous run by _JaxBackend.on_start across 2 worker processes."""
    trainer = DataParallelTrainer(
        _jax_distributed_loop, train_loop_config={},
        backend_config=JaxConfig(distributed=True),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    m = result.metrics
    assert m["procs"] == 2
    # each worker inherits the virtual-device XLA flag; the global mesh is
    # the union of both processes' devices and psum crosses the boundary
    assert m["global_devices"] == 2 * m["local_devices"]
    assert m["psum"] == m["global_devices"]
