"""Cluster launcher (reference: ray up/down/exec, autoscaler/_private/
commands.py) with the subprocess provider — real head process + real
node-agent subprocesses over TCP."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cluster_cfg(tmp_path, monkeypatch):
    import ray_tpu.autoscaler.launcher as launcher

    monkeypatch.setattr(launcher, "STATE_DIR", str(tmp_path / "state"))
    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(textwrap.dedent(f"""
        cluster_name: t-{os.getpid()}
        provider:
          type: subprocess
        head:
          num_cpus: 2
        worker_types:
          cpu-2:
            resources: {{CPU: 2}}
            min_workers: 1
            max_workers: 2
    """))
    yield str(cfg), launcher
    try:
        launcher.down(str(cfg))
    except Exception:
        pass


def test_up_exec_down(cluster_cfg):
    cfg_path, launcher = cluster_cfg
    state = launcher.up(cfg_path)
    assert state["address"].startswith("tcp://")
    assert len(state["nodes"]) == 1

    # exec: a driver script connecting through the env the launcher sets,
    # seeing BOTH nodes (head + subprocess agent).
    script = os.path.join(os.path.dirname(cfg_path), "probe.py")
    with open(script, "w") as f:
        f.write(textwrap.dedent("""
            import json
            import os
            import sys

            from ray_tpu._private.client import client_connect

            rt = client_connect(os.environ["RAY_TPU_ADDRESS"],
                                bytes.fromhex(
                                    os.environ["RAY_TPU_CLIENT_AUTHKEY"]))
            info = rt.request(lambda rid: ("cluster_info", rid))
            print(json.dumps({"nodes": len(info["nodes"]),
                              "cpus": info["resources"].get("CPU")}))
            rt.disconnect()
        """))
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               RAY_TPU_ADDRESS=state["address"],
               RAY_TPU_CLIENT_AUTHKEY=state["authkey"])
    deadline_tries = 20
    for _ in range(deadline_tries):  # agent registration is async
        out = subprocess.run([sys.executable, script], env=env,
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        info = json.loads(out.stdout.strip().splitlines()[-1])
        if info["nodes"] >= 2:
            break
        import time
        time.sleep(0.5)
    assert info["nodes"] == 2, info
    assert info["cpus"] == 4.0  # head 2 + worker node 2

    # exec_cmd wires the same env through a shell.
    rc = launcher.exec_cmd(cfg_path,
                           f"{sys.executable} {script} > /dev/null")
    assert rc == 0

    # idempotent up
    state2 = launcher.up(cfg_path)
    assert state2["address"] == state["address"]

    launcher.down(cfg_path)
    import time

    def head_dead(pid):
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().split()[2] == "Z"  # zombie child
        except OSError:
            return True  # reaped / gone

    deadline = time.time() + 10
    while time.time() < deadline and not head_dead(state["head_pid"]):
        time.sleep(0.3)
    assert head_dead(state["head_pid"])
