"""Fault-tolerance subsystem tests: lineage-based reconstruction
(head-owned and worker-owned objects, recursive arg rebuilds, depleted
retries, byte-budget eviction), the system-vs-application retry split
(``retry_exceptions=``), restartable actors with ``__ray_save__``/
``__ray_restore__`` checkpoint hooks and ``max_task_retries`` replay,
and the ``recovery=off`` switch (legacy ObjectLostError, every new
counter zero).

Reference analogs: ``python/ray/tests/test_reconstruction*.py``,
``test_actor_failures.py`` (checkpointing), ``test_task_retries``.
"""

import os
import pickle
import tempfile
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu._private import recovery
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy as NA,
)

RECOVERY_COUNTERS = ("reconstructions", "reconstruction_failures",
                     "actor_restarts", "chaos_kills")


@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=2)
    yield c
    c.shutdown()


@ray.remote
def _make(n):
    return np.arange(n, dtype=np.int64)


@ray.remote
def _double(x):
    return x * 2


# ------------------------------------------- structured ObjectLostError --

def test_object_lost_error_structured_fields_and_pickle():
    e = ray.exceptions.ObjectLostError(
        object_id="ab" * 16, owner="driver", home="feed", phase="pull")
    assert e.object_id == "ab" * 16
    assert e.phase == "pull"
    assert e.reconstructable
    # One constructor everywhere => one message shape.
    assert "phase=pull" in str(e) and "home=feed" in str(e)
    e2 = pickle.loads(pickle.dumps(e))
    assert (e2.object_id, e2.owner, e2.home, e2.phase) == \
        (e.object_id, e.owner, e.home, e.phase)
    assert isinstance(e2, ray.exceptions.ObjectLostError)


def test_freed_and_owner_died_are_not_reconstructable():
    assert not ray.exceptions.ObjectFreedError.reconstructable
    assert not ray.exceptions.OwnerDiedError.reconstructable
    # Subclasses keep the structured fields through pickling too.
    e = pickle.loads(pickle.dumps(
        ray.exceptions.OwnerDiedError(object_id="cd" * 16, phase="export")))
    assert isinstance(e, ray.exceptions.OwnerDiedError)
    assert not e.reconstructable and e.object_id == "cd" * 16


# --------------------------------------------------- lineage table unit --

def _spec(i, num_returns=1, arg=b"", max_retries=3):
    from ray_tpu._private.ids import new_task_id

    return {"task_id": new_task_id().binary(), "num_returns": num_returns,
            "name": f"t{i}", "args": [("inline", arg)], "kwargs": {},
            "max_retries": max_retries}


def test_lineage_table_budget_evicts_oldest_first():
    t = recovery.LineageTable(budget_bytes=4 * recovery._SPEC_BASE_COST)
    specs = [_spec(i) for i in range(8)]
    for s in specs:
        t.record(s)
    stats = t.stats()
    assert stats["evicted"] > 0
    assert stats["bytes"] <= 4 * recovery._SPEC_BASE_COST
    # Oldest entries evicted; newest survive.
    assert specs[0]["task_id"][:12] not in t
    assert specs[-1]["task_id"][:12] in t


def test_lineage_table_releases_on_last_return_object():
    from ray_tpu._private.ids import TaskID

    t = recovery.LineageTable(budget_bytes=0)  # unbounded
    s = _spec(0, num_returns=2)
    t.record(s)
    tid = TaskID(s["task_id"])
    assert t.release(tid.object_id(0).binary()) is None  # one still alive
    entry = t.release(tid.object_id(1).binary())
    assert entry is not None and entry["spec"] is s
    assert s["task_id"][:12] not in t and t.stats()["bytes"] == 0


def test_lineage_table_attempt_budget_depletes():
    t = recovery.LineageTable(budget_bytes=0)
    s = _spec(0, max_retries=2)
    t.record(s)
    prefix = s["task_id"][:12]
    assert t.note_attempt(prefix)
    assert t.note_attempt(prefix)
    assert not t.note_attempt(prefix)  # depleted: recovery must refuse


def test_head_lineage_budget_rides_system_config():
    rt = ray.init(num_cpus=2,
                  _system_config={"lineage_bytes_budget": 4096})
    try:
        assert rt.lineage.budget == 4096
        refs = [_double.remote(i) for i in range(40)]
        ray.get(refs)
        assert rt.lineage.stats()["evicted"] > 0
        assert rt.lineage.stats()["bytes"] <= 4096
    finally:
        ray.shutdown()


# ------------------------------------------------ retry semantics split --

def test_retry_exceptions_opt_in_counts_executions(ray_start_regular):
    path = tempfile.mktemp()

    @ray.remote(max_retries=3, retry_exceptions=[ValueError])
    def flaky(p):
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        if n < 2:
            raise ValueError("transient")
        return n

    assert ray.get(flaky.remote(path)) == 2
    # EXACTLY first-failure + retries: 3 executions, no more no less.
    assert int(open(path).read()) == 3


def test_app_errors_do_not_retry_without_opt_in(ray_start_regular):
    path = tempfile.mktemp()

    @ray.remote(max_retries=3)
    def fails(p):
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        raise ValueError("app bug")

    with pytest.raises(ray.exceptions.TaskError):
        ray.get(fails.remote(path))
    # max_retries is a SYSTEM-failure budget: the app error ran once.
    assert int(open(path).read()) == 1


def test_retry_exceptions_type_filter(ray_start_regular):
    path = tempfile.mktemp()

    @ray.remote(max_retries=3, retry_exceptions=[KeyError])
    def fails(p):
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        raise ValueError("not retryable")

    with pytest.raises(ray.exceptions.TaskError):
        ray.get(fails.remote(path))
    assert int(open(path).read()) == 1


def test_retry_exceptions_bare_class_shorthand(ray_start_regular):
    path = tempfile.mktemp()

    @ray.remote(max_retries=2, retry_exceptions=ValueError)
    def flaky(p):
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        if n < 1:
            raise ValueError("transient")
        return n

    assert ray.get(flaky.remote(path)) == 1
    assert int(open(path).read()) == 2
    with pytest.raises(TypeError):
        flaky.options(retry_exceptions="ValueError")._build_spec(
            ray_start_regular, (path,), {})
    with pytest.raises(TypeError):
        # Strings INSIDE the list must be rejected too — they could
        # never match, silently disabling the opt-in.
        flaky.options(retry_exceptions=["ValueError"])._build_spec(
            ray_start_regular, (path,), {})


def test_retry_exceptions_budget_depletes(ray_start_regular):
    path = tempfile.mktemp()

    @ray.remote(max_retries=2, retry_exceptions=True)
    def always(p):
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, "w").write(str(n + 1))
        raise RuntimeError("always")

    with pytest.raises(ray.exceptions.TaskError):
        ray.get(always.remote(path))
    assert int(open(path).read()) == 3  # 1 + 2 retries


# --------------------------------------------- head-owned reconstruction --

def test_reconstruction_counts_and_reconstructing_event(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    ref = _make.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(2_000_000)
    ray.wait([ref], num_returns=1, timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    got = ray.get(ref, timeout=60)
    assert int(got.sum()) == int(np.arange(2_000_000, dtype=np.int64).sum())
    stats = cluster.rt.transfer_stats()
    assert stats["reconstructions"] >= 1
    states = [e["state"] for e in cluster.rt.task_events]
    assert "RECONSTRUCTING" in states


def test_recursive_arg_reconstruction(cluster):
    """Consumer output AND its argument both died with the node: the
    owner rebuilds the argument first, then the consumer (recursive
    recovery walk, cycle-safe)."""
    n1 = cluster.add_node(num_cpus=2, external=True)
    x = _make.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(1_500_000)
    y = _double.options(
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(x)
    ray.wait([y], num_returns=1, timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    got = ray.get(y, timeout=90)
    assert int(got[:5].sum()) == 2 * int(np.arange(5).sum())
    assert cluster.rt.transfer_stats()["reconstructions"] >= 2


def test_depleted_retries_surfaces_structured_object_lost(cluster):
    n1 = cluster.add_node(num_cpus=2, external=True)
    ref = _make.options(
        max_retries=0,
        scheduling_strategy=NA(node_id=n1, soft=True)).remote(1_500_000)
    ray.wait([ref], num_returns=1, timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    with pytest.raises(ray.exceptions.ObjectLostError) as ei:
        ray.get(ref, timeout=30)
    # The refusal carries the structured identity, and counts.
    assert ei.value.object_id == ref.id().hex()
    assert cluster.rt.transfer_stats()["reconstruction_failures"] >= 1
    assert cluster.rt.transfer_stats()["reconstructions"] == 0


# ------------------------------------------- worker-owned (direct path) --

def test_worker_owned_direct_path_reconstruction():
    """THIS is what the head's lineage cannot cover: a worker's
    direct-submitted tasks never reach the head, so the worker's own
    DirectCaller lineage must rebuild their lost returns (owner-side
    recovery, Ownership NSDI'21)."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=0)
    try:
        n1 = c.add_node(num_cpus=1, external=True)
        c.add_node(num_cpus=2, external=True)
        kf = tempfile.mktemp()

        @ray.remote
        def coordinator(kill_file):
            @ray.remote
            def make(i):
                return np.full(300_000, i, dtype=np.int64)

            refs = [make.remote(i) for i in range(8)]
            # wait (NOT get): results stay un-materialized segments
            ray.wait(refs, num_returns=len(refs), timeout=60)
            open(kill_file + ".ready", "w").write("x")
            while not os.path.exists(kill_file + ".done"):
                time.sleep(0.1)
            time.sleep(0.5)
            return [int(ray.get(r)[0]) for r in refs]

        fut = coordinator.options(
            scheduling_strategy=NA(node_id=n1, soft=False),
            num_cpus=1).remote(kf)
        deadline = time.time() + 60
        while not os.path.exists(kf + ".ready") \
                and time.time() < deadline:
            time.sleep(0.1)
        assert os.path.exists(kf + ".ready"), "coordinator never started"
        # n1 is full (the coordinator) => every subtask ran on n2; kill
        # it and every result segment is gone.
        killed = [n for n in c.rt.list_nodes()
                  if n["node_id"] != n1 and not n["labels"].get("head")]
        c.kill_agent(killed[0]["node_id"])
        time.sleep(0.3)
        open(kf + ".done", "w").write("x")
        assert ray.get(fut, timeout=120) == list(range(8))
        time.sleep(1.0)  # xfer_stats delta flush
        assert c.rt.transfer_stats()["reconstructions"] >= 8
    finally:
        c.shutdown()


# ------------------------------------------------- restartable actors --

@ray.remote(max_restarts=2, max_task_retries=-1)
class _CheckpointedCounter:
    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1
        return self.n

    def pid(self):
        return os.getpid()

    def __ray_save__(self):
        return self.n

    def __ray_restore__(self, n):
        self.n = n


def test_actor_restart_with_checkpoint_hooks(ray_start_regular):
    rt = ray_start_regular
    c = _CheckpointedCounter.remote()
    for _ in range(3):
        ray.get(c.inc.remote())
    pid = ray.get(c.pid.remote())
    time.sleep(0.3)  # conflated actor_checkpoint message lands
    os.kill(pid, 9)
    v = ray.get(c.inc.remote(), timeout=30)
    assert v == 4, f"state not restored (got {v})"
    assert ray.get(c.pid.remote()) != pid
    stats = rt.transfer_stats()
    assert stats["actor_restarts"] >= 1


def test_actor_restart_without_hooks_resets_state(ray_start_regular):
    @ray.remote(max_restarts=1, max_task_retries=-1)
    class Plain:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    c = Plain.remote()
    for _ in range(3):
        ray.get(c.inc.remote())
    os.kill(ray.get(c.pid.remote()), 9)
    assert ray.get(c.inc.remote(), timeout=30) == 1  # fresh __init__


def test_actor_inflight_replay_per_max_task_retries(ray_start_regular):
    path = tempfile.mktemp()

    @ray.remote(max_restarts=1, max_task_retries=2)
    class Slow:
        def work(self, p):
            n = int(open(p).read()) if os.path.exists(p) else 0
            open(p, "w").write(str(n + 1))
            time.sleep(1.0)
            return "done"

        def pid(self):
            return os.getpid()

    c = Slow.remote()
    pid = ray.get(c.pid.remote())
    fut = c.work.remote(path)
    time.sleep(0.4)  # mid-execution
    os.kill(pid, 9)
    # The in-flight call REPLAYS on the restarted actor (at-least-once).
    assert ray.get(fut, timeout=30) == "done"
    assert int(open(path).read()) == 2


def test_actor_inflight_fails_without_task_retries(ray_start_regular):
    @ray.remote(max_restarts=1)
    class Slow:
        def work(self):
            time.sleep(1.0)
            return "done"

        def pid(self):
            return os.getpid()

    c = Slow.remote()
    pid = ray.get(c.pid.remote())
    fut = c.work.remote()
    time.sleep(0.4)
    os.kill(pid, 9)
    with pytest.raises(ray.exceptions.ActorDiedError):
        ray.get(fut, timeout=30)
    # ...but the actor itself restarted and serves new calls.
    assert ray.get(c.pid.remote(), timeout=30) != pid


# ----------------------------------------------------- off switch + env --

def test_recovery_off_is_legacy_loss_with_zero_counters():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=2, _system_config={"recovery": False})
    try:
        n1 = c.add_node(num_cpus=2, external=True)

        @ray.remote
        def probe():
            return (os.environ.get("RAY_TPU_RECOVERY"),
                    os.environ.get("RAY_TPU_LINEAGE_BYTES_BUDGET"),
                    os.environ.get("RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S"))

        # Knob plumbing reaches agent-spawned workers too.
        env = ray.get(probe.options(
            scheduling_strategy=NA(node_id=n1)).remote(), timeout=30)
        assert env[0] == "0" and env[1] and env[2] is not None

        ref = _make.options(
            scheduling_strategy=NA(node_id=n1, soft=True)).remote(
                2_000_000)
        ray.wait([ref], num_returns=1, timeout=30)
        cluster_stats = c.rt.transfer_stats()
        c.kill_agent(n1)
        time.sleep(0.5)
        with pytest.raises(ray.exceptions.ObjectLostError):
            ray.get(ref, timeout=30)
        stats = c.rt.transfer_stats()
        for k in RECOVERY_COUNTERS:
            assert stats[k] == 0, (k, stats[k])
            assert cluster_stats[k] == 0
    finally:
        c.shutdown()


def test_put_only_objects_stay_unrecoverable_and_count(cluster):
    """ray.put has no lineage — recovery refuses (the documented
    refusal case), counted as a reconstruction failure."""
    n1 = cluster.add_node(num_cpus=2, external=True)

    @ray.remote
    def make_put():
        return ray.put(np.arange(1_000_000))

    inner = ray.get(make_put.options(
        scheduling_strategy=NA(node_id=n1)).remote(), timeout=30)
    cluster.kill_agent(n1)
    time.sleep(0.5)
    with pytest.raises(ray.exceptions.ObjectLostError):
        ray.get(inner, timeout=30)
    assert cluster.rt.transfer_stats()["reconstruction_failures"] >= 1
