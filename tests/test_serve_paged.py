"""Serving memory plane: paged KV admission, shared-prefix reuse, and
speculative decoding over the continuous batcher.

The battery pins the ISSUE acceptance contract: block-gated admission
PARKS on exhaustion (never errors) and packs skewed-length batches past
the dense slot cap at equal simulated HBM; prefix sharing and
copy-on-write divergence keep decoded chains bitwise-identical to the
uncached host reference; exact-match speculative acceptance retires >1
token/step with greedy output bitwise-unchanged; and with every knob
off the engine is the byte-identical PR 8 dense batcher with every new
counter zero (the knob-off pin)."""

import threading
import time

import pytest

import ray_tpu as ray
from ray_tpu import serve
from ray_tpu.serve.continuous import _ContinuousBatcher
from ray_tpu.serve.kv_cache import (
    BlockAllocator, PagedKVEngine, PrefixCache, RequestTooLarge)


def _drive(batcher, requests, timeout=60):
    """Submit every request from its own thread; results/errors by id."""
    results, errors = {}, {}

    def client(req):
        try:
            results[req["id"]] = batcher.submit(req)
        except BaseException as e:  # noqa: BLE001 — recorded for asserts
            errors[req["id"]] = e

    threads = [threading.Thread(target=client, args=(r,))
               for r in requests]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    return results, errors


def _paced_step(step_s):
    """Step fn over paged slots: request["tokens"] iterations each, one
    fixed sleep per step (occupancy-independent device-step model)."""

    def stepfn(slots):
        time.sleep(step_s)
        for s in slots:
            if s.state is None:
                s.state = {"n": 0, "need": s.request["tokens"]}
            s.state["n"] += 1
            if s.state["n"] >= s.state["need"]:
                s.finish(s.state["n"])

    return stepfn


def _sizing_engine(num_blocks, block_size, **kw):
    """Engine sized off request["tokens"] alone (no prompt)."""
    kw.setdefault("prefix_caching", False)
    return PagedKVEngine(num_blocks, block_size,
                         tokens_for=lambda r: ((), r["tokens"]), **kw)


# -- allocator / prefix-cache units -----------------------------------------

def test_block_allocator_refcounts_and_all_or_nothing():
    a = BlockAllocator(4, 8)
    assert a.alloc(5) is None and a.available == 4  # all-or-nothing
    blks = a.alloc(3)
    assert len(blks) == 3 and a.used == 3
    a.incref(blks[0])
    a.free(blks)                  # blks[0] survives its shared ref
    assert a.used == 1 and a.ref(blks[0]) == 1
    a.free([blks[0]])
    assert a.used == 0
    with pytest.raises(ValueError, match="double free"):
        a.free([blks[0]])
    with pytest.raises(ValueError, match="incref of free"):
        a.incref(blks[1])


def test_prefix_cache_block_boundary_reuse_and_reclaim():
    a = BlockAllocator(16, 8)
    c = PrefixCache(a)
    prompt = tuple(range(20))          # 3 blocks, last one partial
    chain = a.alloc(3)
    c.insert(prompt, chain)            # keys: len 8, 16, 20
    # A longer prompt sharing the 16-token boundary reuses 2 blocks.
    got, n = c.lookup(tuple(range(16)) + (99, 98))
    assert n == 16 and got == chain[:2]
    assert all(a.ref(b) > 1 for b in got)
    a.free(got)
    # A sub-block prefix (< block_size) has no boundary entry.
    assert c.lookup((0, 1, 2)) == ([], 0)
    # Reclaim drops LRU entries until the need is met.
    a.free(chain)                      # cache refs keep blocks alive
    used_before = a.used
    assert used_before > 0
    c.reclaim(a.available + used_before)
    assert a.used == 0 and len(c) == 0


# -- admission: parking and fast-fail ---------------------------------------

def test_allocator_exhaustion_parks_admission_then_completes():
    """6 requests whose budgets each take the WHOLE pool serialize
    through admission: parks (not errors), FIFO completion, pool fully
    freed at the end."""
    eng = _sizing_engine(4, 4)                  # 16-token pool
    b = _ContinuousBatcher(_paced_step(0.001), None, 8, 0.0,
                           continuous=True, kv=eng)
    reqs = [{"id": i, "tokens": 16} for i in range(6)]
    results, errors = _drive(b, reqs)
    assert not errors and len(results) == 6
    s = b.stats()
    assert s["mode"] == "continuous+paged"
    # Park EPISODES, not boundary re-checks: the 5 waiting requests
    # park once each, not once per scheduler boundary they waited out.
    assert 1 <= s["admission_parks"] <= len(reqs)
    assert s["retired"] == 6 and s["step_errors"] == 0
    assert s["kv_blocks_used"] == 0             # alloc-on-admit/free-on-retire


def test_oversized_request_fails_fast_and_queue_keeps_flowing():
    """A budget larger than the TOTAL pool can never fit: it must raise
    RequestTooLarge to ITS caller while the requests queued behind it
    still complete (parking it would wedge the FIFO head forever)."""
    eng = _sizing_engine(4, 4)
    b = _ContinuousBatcher(_paced_step(0.001), None, 8, 0.0,
                           continuous=True, kv=eng)
    reqs = [{"id": 0, "tokens": 8}, {"id": 1, "tokens": 999},
            {"id": 2, "tokens": 8}]
    results, errors = _drive(b, reqs)
    assert set(results) == {0, 2} and set(errors) == {1}
    assert isinstance(errors[1], RequestTooLarge)
    assert b.stats()["admission_rejects"] == 1


def test_malformed_request_dooms_slot_not_scheduler():
    """A request the sizing hook cannot even size (poison pill) must
    fail ITS caller — not kill the scheduler thread with the bad slot
    still at the queue head, where every respawned scheduler would die
    on it again."""
    eng = _sizing_engine(4, 4)          # tokens_for does len+arith -> TypeError
    b = _ContinuousBatcher(_paced_step(0.001), None, 8, 0.0,
                           continuous=True, kv=eng)
    reqs = [{"id": 0, "tokens": 8}, {"id": 1, "tokens": None},
            {"id": 2, "tokens": 8}]
    results, errors = _drive(b, reqs)
    assert set(results) == {0, 2} and set(errors) == {1}
    assert isinstance(errors[1], TypeError)
    # The surviving scheduler keeps draining fresh submissions.
    assert b.submit({"id": 3, "tokens": 4}) == 4
    assert b.stats()["step_errors"] == 0


def test_paged_packs_past_dense_slot_cap():
    """Equal simulated HBM (128 tokens): the dense engine fits
    128/max_seq_len(16) = 8 slots; block-granular admission packs the
    same short (4-token) requests past that cap in one live batch."""
    eng = _sizing_engine(32, 4, max_slots=64)   # 128-token pool
    peak = {"live": 0}

    def stepfn(slots):
        peak["live"] = max(peak["live"], len(slots))
        time.sleep(0.002)
        for s in slots:
            s.state = (s.state or 0) + 1
            if s.state >= s.request["tokens"]:
                s.finish(s.state)

    b = _ContinuousBatcher(stepfn, None, 8, 0.0, continuous=True, kv=eng)
    reqs = [{"id": i, "tokens": 4} for i in range(48)]
    results, errors = _drive(b, reqs)
    assert not errors and len(results) == 48
    assert peak["live"] > 8, peak                # past the dense HBM cap
    assert b.stats()["batch_occupancy"] > 8


# -- the paged decoder: bitwise pins ----------------------------------------

def _decoder_batcher(dec):
    return _ContinuousBatcher(dec._paged_step, None, 8, 0.0,
                              continuous=True, kv=dec.serve_kv_engine)


def test_paged_decoder_prefix_reuse_cow_bitwise():
    """Shared system prompt across clients: prefix blocks are mapped
    (hits + shared blocks), divergence copies-on-write, and every chain
    is bitwise the host reference — identical to the UNCACHED run."""
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    sys_prompt = list(range(20))                 # spans 2 full blocks
    reqs = [{"id": i, "prompt": sys_prompt + [i], "tokens": 3 + i % 4}
            for i in range(8)]

    def run(prefix_caching):
        dec = MeshShardedDecoder(paged=True, kv_blocks=64,
                                 kv_block_size=8,
                                 prefix_caching=prefix_caching)
        b = _decoder_batcher(dec)
        results, errors = _drive(b, reqs)
        assert not errors
        return results, b.stats()

    cached, cs = run(True)
    uncached, us = run(False)
    assert cached == uncached                    # bitwise A/B
    ref = MeshShardedDecoder()
    for r in reqs:
        assert cached[r["id"]] == ref.reference_decode(r["prompt"],
                                                       r["tokens"])
    assert cs["prefix_hits"] > 0 and cs["prefix_blocks_shared"] > 0
    assert cs["cow_copies"] > 0                  # divergence after share
    assert us["prefix_hits"] == us["prefix_blocks_shared"] == 0


def test_speculative_battery_bitwise_greedy():
    """Exact-match acceptance: for every draft length k the decoded
    chains are bitwise the host reference; a mostly-agreeing draft
    accepts >0 proposals and retires >1 token/step, a garbage draft
    accepts ~none — output unchanged either way."""
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    reqs = [{"id": i, "prompt": [i], "tokens": 5 + i % 6}
            for i in range(8)]
    ref = MeshShardedDecoder()
    expected = {r["id"]: ref.reference_decode(r["prompt"], r["tokens"])
                for r in reqs}
    for k in (0, 1, 3, 7):
        dec = MeshShardedDecoder(paged=True, kv_blocks=64,
                                 kv_block_size=8, speculative_k=k)
        b = _decoder_batcher(dec)
        results, errors = _drive(b, reqs)
        assert not errors and results == expected, f"k={k}"
        s = b.stats()
        if k == 0:
            assert s["spec_proposed"] == s["spec_accepted"] == 0
        else:
            assert s["spec_proposed"] >= s["spec_accepted"] > 0, f"k={k}"
    assert s["tokens_per_step"] > 1.0            # k=7 retires multi-token
    # Garbage draft: rejects dominate, greedy output still bitwise.
    dec = MeshShardedDecoder(paged=True, kv_blocks=64, kv_block_size=8,
                             speculative_k=3)
    dec._wd_host = -dec._wd_host                 # anti-correlated draft
    b = _decoder_batcher(dec)
    results, errors = _drive(b, reqs)
    assert not errors and results == expected
    s = b.stats()
    assert s["spec_accepted"] < s["spec_proposed"]


def test_paged_instance_with_knob_off_falls_back_dense():
    """A paged=True decoder driven by a DENSE batcher (paged_kv knob
    off, the process default: the batching decorator ignores
    serve_kv_engine, so slots carry no kv plan) must fall back to the
    dense decode path — both prompt forms decode correctly and every
    engine counter stays zero."""
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    dec = MeshShardedDecoder(paged=True)
    ref = MeshShardedDecoder()
    assert dec({"prompt": 3, "tokens": 4}) == ref.reference_decode(3, 4)
    assert dec({"prompt": [2, 9], "tokens": 3}) \
        == ref.reference_decode([2, 9], 3)
    s = dec.serve_kv_engine.stats_locked()
    assert all(v == 0 for k, v in s.items()
               if k not in ("kv_blocks_total",)), s


# -- knob plumbing through serve + the knob-off pin -------------------------

def test_paged_serve_e2e_knobs_on():
    """_system_config{paged_kv, speculative_k} reaches replica workers
    (rides _worker_config_env): the stock MeshShardedDecoder deployment
    comes up paged+speculative, chains stay bitwise, and the controller
    rollup reports the memory-plane observables."""
    ray.init(num_cpus=4,
             _system_config={"paged_kv": True, "speculative_k": 2})
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        dep = serve.deployment(MeshShardedDecoder, name="paged",
                               max_concurrency=16)
        handle = serve.run(dep.bind(), name="paged")
        shared = list(range(16))                 # 2 shared blocks
        reqs = [{"prompt": shared + [i], "tokens": 1 + i % 5}
                for i in range(10)]
        outs = ray.get([handle.remote(r) for r in reqs], timeout=120)
        ref = MeshShardedDecoder()
        for r, out in zip(reqs, outs):
            assert out == ref.reference_decode(r["prompt"], r["tokens"])
        stats = serve.serving_stats("paged")
        assert stats["mode"] == "continuous+paged"
        assert stats["kv_blocks_total"] > 0
        assert 0.0 <= stats["kv_occupancy"] <= 1.0
        assert stats["prefix_hits"] > 0
        assert stats["spec_accepted"] > 0
        assert stats["tokens_per_step"] > 1.0
        assert stats["retired"] == 10
    finally:
        serve.shutdown()
        ray.shutdown()


def test_knob_off_dense_engine_zero_counters_pin():
    """All three switches off (the defaults): the stock deployment runs
    the PR 8 dense engine — mode has no paged flag and EVERY
    serving-memory counter in the rollup is zero."""
    ray.init(num_cpus=4)
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        dep = serve.deployment(MeshShardedDecoder, name="dense",
                               max_concurrency=16)
        handle = serve.run(dep.bind(), name="dense")
        outs = ray.get([handle.remote({"prompt": i, "tokens": 2})
                        for i in range(6)], timeout=120)
        ref = MeshShardedDecoder()
        for i, out in enumerate(outs):
            assert out == ref.reference_decode(i, 2)
        stats = serve.serving_stats("dense")
        assert stats["mode"] == "continuous"
        for key in ("kv_blocks_total", "kv_blocks_used", "prefix_hits",
                    "prefix_blocks_shared", "cow_copies",
                    "spec_proposed", "spec_accepted", "tokens_emitted",
                    "admission_parks", "admission_rejects"):
            assert stats[key] == 0, key
        assert stats["kv_occupancy"] == 0.0
        assert stats["tokens_per_step"] == 0.0
    finally:
        serve.shutdown()
        ray.shutdown()


# -- the perf A/B (bench-shaped; slow tier) ---------------------------------

@pytest.mark.slow
def test_acceptance_paged_1_5x_req_s_at_equal_hbm():
    """THE acceptance micro: skewed-length requests (most short, some
    at max_seq_len) at EQUAL simulated HBM (1024 tokens).  Dense: 8
    slots of max_seq_len=128.  Paged: 128 blocks of 8 tokens.  Paced
    steps; >= 1.5x req/s, best-of-3 per engine."""
    step_s = 0.004
    reqs = [{"id": i, "tokens": 128 if i % 16 == 0 else 16}
            for i in range(96)]

    def req_rate(paged):
        best, samples = 0.0, []
        for _ in range(3):
            kv = _sizing_engine(128, 8, max_slots=64) if paged else None
            b = _ContinuousBatcher(_paced_step(step_s), None, 8, 0.0,
                                   continuous=True, kv=kv)
            t0 = time.perf_counter()
            results, errors = _drive(b, reqs, timeout=120)
            dt = time.perf_counter() - t0
            assert not errors and len(results) == len(reqs)
            samples.append(round(len(reqs) / dt, 1))
            best = max(best, len(reqs) / dt)
        return best, samples

    paged, ps = req_rate(True)
    dense, ds = req_rate(False)
    assert paged >= 1.5 * dense, (
        f"paged {paged:.0f} req/s vs dense {dense:.0f} req/s "
        f"(samples: {ps} vs {ds})")
