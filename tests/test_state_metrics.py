"""State-observability API + user metrics tests.

Reference patterns: ``python/ray/tests/test_state_api.py`` (list_* over a
live cluster) and ``python/ray/tests/test_metrics_agent.py`` (user
Counter/Gauge/Histogram visibility).
"""

import time

import pytest

import ray_tpu as ray
from ray_tpu.util import metrics, state


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    metrics.reset()
    yield rt
    ray.shutdown()


def test_list_tasks_cross_worker_states(ray8):
    @ray.remote
    def quick(i):
        return i

    @ray.remote
    def slow():
        time.sleep(30)

    done = ray.get([quick.options(name="quick").remote(i)
                    for i in range(5)], timeout=60)
    assert done == list(range(5))
    running = slow.options(name="slow").remote()
    time.sleep(0.5)
    tasks = state.list_tasks()
    by_name = {}
    for t in tasks:
        by_name.setdefault(t["name"], []).append(t["state"])
    assert by_name["quick"].count("FINISHED") == 5
    assert "RUNNING" in by_name.get("slow", [])
    summary = state.summarize_tasks()
    assert summary.get("quick:FINISHED") == 5
    ray.cancel(running, force=True)


def test_list_actors_and_workers(ray8):
    @ray.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="my_actor").remote()
    ray.get(a.ping.remote(), timeout=30)
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" and x["name"] == "my_actor"
               for x in actors)
    workers = state.list_workers()
    assert any(w["alive"] and w["actor_id"] for w in workers)


def test_list_objects_and_nodes(ray8):
    import numpy as np

    ref = ray.put(np.zeros(2_000_000, dtype=np.uint8))  # shm-resident
    objs = state.list_objects()
    mine = [o for o in objs if o["object_id"] == ref.hex()]
    assert mine and mine[0]["state"] == "READY" and mine[0]["kind"] == "shm"
    assert mine[0]["size"] > 1_000_000
    nodes = state.list_nodes()
    assert nodes and nodes[0]["alive"]


def test_state_api_callable_from_worker(ray8):
    @ray.remote
    class Probe:
        def nodes(self):
            from ray_tpu.util import state as st

            return len(st.list_nodes())

    p = Probe.remote()
    assert ray.get(p.nodes.remote(), timeout=30) >= 1


def test_metrics_counter_cross_worker(ray8):
    from ray_tpu.util.metrics import Counter

    @ray.remote
    def work(i):
        c = Counter("tasks_done", tag_keys=("shard",))
        c.inc(1.0, {"shard": str(i % 2)})
        return i

    ray.get([work.remote(i) for i in range(10)], timeout=60)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        snap = metrics.snapshot()
        total = sum(v for k, v in snap.items()
                    if k.startswith("tasks_done"))
        if total == 10.0:
            break
        time.sleep(0.2)
    snap = metrics.snapshot()
    assert snap.get("tasks_done{shard=0}") == 5.0
    assert snap.get("tasks_done{shard=1}") == 5.0


def test_metrics_gauge_histogram(ray8):
    from ray_tpu.util.metrics import Gauge, Histogram

    g = Gauge("queue_depth")
    g.set(3.0)
    g.set(7.0)
    h = Histogram("latency", boundaries=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0, 0.7):
        h.observe(v)
    snap = metrics.snapshot()
    assert snap["queue_depth"] == 7.0
    hist = snap["latency"]
    assert hist["count"] == 4 and hist["buckets"] == [1, 2, 1]
    assert abs(hist["sum"] - 6.25) < 1e-9
