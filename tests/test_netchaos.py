"""Gray-failure acceptance battery (ISSUE 14).

Where ``test_chaos.py`` proves the cluster survives CLEAN failures
(kills, closed connections — a peer dies and its socket says so), this
battery proves it survives the failures that announce nothing: a
stalled-but-alive link mid-transfer, a one-way partition the head can
only notice as silence.  The failure-detection plane (deadlines on
every wire operation, transport retries + hedging, head-side heartbeat
suspicion) is what turns each of these from a forever-hang into a
bounded, structured recovery — and ``chaos.ChaosNet`` is what makes
them injectable.

Reference analog: GcsHealthCheckManager + per-RPC gRPC deadlines;
"Gray Failure: The Achilles' Heel of Cloud-Scale Systems" (HotOS'17).
"""

import os
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time
import types

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import chaos as chaos_mod
from ray_tpu._private import protocol
from ray_tpu.chaos import ChaosController, ChaosNet
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy as NA,
)

# Tiny windows so suspicion/deadline tests complete in seconds; every
# cluster test in this file shares them.
FAST_FD = {
    "net_stall_timeout_s": 0.8,
    "net_connect_timeout_s": 2.0,
    "net_retry_count": 1,
    "net_retry_backoff_base_ms": 20.0,
    "health_check_period_s": 0.25,
    "health_check_timeout_s": 1.0,
    "health_check_failure_threshold": 2,
    "health_check_initial_delay_s": 1.0,
}

NET_COUNTERS = ("suspected_nodes", "stall_timeouts", "net_retries",
                "hedged_fetches")


@ray.remote(max_retries=3)
def _make(i):
    return np.full(260_000, i, dtype=np.int64)  # ~2 MB: shm-homed


@ray.remote(max_retries=3)
def _consume(a):
    return int(a[0])


# ------------------------------------------------------------ unit-level --

def test_parse_net_rules_ignores_garbage():
    rules = chaos_mod.parse_net_rules(
        "worker:send:stall:1, bogus, agent:chunk_send:delay-2.5:3,"
        "agent:recv:delay-x:1, driver:*:drop:2, agent:send:explode:1")
    assert rules == [
        ("worker", "send", "stall", 0.0, 1),
        ("agent", "chunk_send", "delay", 2.5, 3),
        ("driver", "*", "drop", 0.0, 2),
    ]


def test_chaosnet_hook_verdicts_and_restore():
    """Drop/dup verdicts, per-conn scoping, countdown, and a stall that
    parks the calling thread until restore — no cluster needed."""
    net = ChaosNet()
    conn_a, conn_b = object(), object()
    net.add_rule("send", "drop", conn=conn_a)
    net.add_rule("send", "dup", conn=conn_b, after=2)
    assert net._hook("send", conn_a) == "drop"
    assert net._hook("send", conn_b) is None      # countdown not reached
    assert net._hook("send", conn_b) == "dup"     # 2nd op arms it
    assert net._hook("recv", conn_b) is None      # wrong point
    assert net.stats()["net_faults"] == 2

    net.add_rule("recv", "stall", conn=conn_a)
    parked = threading.Event()
    resumed = threading.Event()

    def reader():
        parked.set()
        net._hook("recv", conn_a)  # parks until restore
        resumed.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    assert parked.wait(2)
    time.sleep(0.1)
    assert not resumed.is_set()   # genuinely parked (socket-open stall)
    net.restore(conn_a)
    assert resumed.wait(2)
    # conn_b's rule survived the scoped restore.
    assert net.stats()["net_rules"] == 1


def test_env_net_rule_one_shot_claim(tmp_path):
    """Two ChaosNet instances racing the same claim file: exactly one
    fires (the kill rules' O_EXCL convention)."""
    claim = str(tmp_path / "claim")
    fired = 0
    for _ in range(2):
        net = ChaosNet()
        net.add_rule("send", "drop", claim=claim)
        if net._hook("send", None) == "drop":
            fired += 1
    assert fired == 1


def test_recv_deadline_trips_on_silent_peer():
    """A recv with an armed zero-progress deadline surfaces
    NetTimeoutError in ~the deadline, not forever — and NetTimeoutError
    is an OSError so every existing conn-EOF discovery site absorbs
    it."""
    from multiprocessing.connection import Pipe

    here, there = Pipe()
    try:
        t0 = time.monotonic()
        with pytest.raises(protocol.NetTimeoutError):
            protocol.recv_deadline(here, 0.3)
        assert time.monotonic() - t0 < 3.0
        assert issubclass(protocol.NetTimeoutError, OSError)
        # Cleared deadline: a late message still arrives (the conn is
        # not poisoned by the trip).
        protocol.send(there, ("late", 1))  # noqa: RTL501 -- synthetic verb on a local Pipe, never on the cluster wire
        assert protocol.recv(here) == ("late", 1)
    finally:
        here.close()
        there.close()


def test_shutdown_conn_wakes_a_parked_reader():
    """The watchdog retirement contract: close() alone does NOT wake a
    thread already blocked in read() on Linux — shutdown_conn must, so
    the stalled-channel watchdogs (direct dping, worker hc_ping) can
    push their parked readers into the death/reconnect path."""
    import socket as socketlib
    from multiprocessing.connection import Connection

    a, b = socketlib.socketpair()
    conn = Connection(a.detach())
    other = Connection(b.detach())
    woke = threading.Event()
    err: list = []

    def reader():
        try:
            protocol.recv(conn)
        except (EOFError, OSError) as e:
            err.append(e)
        woke.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not woke.is_set()          # genuinely parked
    protocol.shutdown_conn(conn)
    assert woke.wait(3), "shutdown_conn failed to wake the parked reader"
    assert err                        # EOF/OSError, never a value
    conn.close()
    other.close()


def test_dial_bounds_a_stalled_auth_handshake():
    """An accepted-but-silent listener (process hung right after
    accept) cannot hang the dialer: the auth handshake rides the same
    connect deadline."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    addr = srv.getsockname()
    try:
        t0 = time.monotonic()
        with pytest.raises((protocol.NetTimeoutError, OSError)):
            protocol.dial(addr, authkey=b"k", connect_timeout=0.4)
        assert time.monotonic() - t0 < 4.0
    finally:
        srv.close()


def test_suspicion_state_machine_unit():
    """Sub-second unit rep of the suspicion window (the wall-clock
    variants below are the slow lane): ALIVE -> SUSPECT (counted once)
    -> probe per period -> DEAD past the threshold; any message fully
    absolves."""
    from ray_tpu._private.runtime import Runtime

    head = types.SimpleNamespace(suspected_nodes=0)
    peer = types.SimpleNamespace(last_seen=100.0, hc_suspect=False,
                                 hc_misses=0, hc_probe_ts=0.0)
    timeout, period, threshold = 5.0, 1.0, 2
    step = Runtime._suspect_step_locked

    def tick(now):
        probes, dead = [], []
        step(head, peer, now, timeout, period, threshold, probes, dead)
        return bool(probes), bool(dead)

    assert tick(103.0) == (False, False)          # within the window
    assert tick(106.0) == (True, False)           # SUSPECT: first probe
    assert peer.hc_suspect and head.suspected_nodes == 1
    assert tick(106.5) == (False, False)          # probe window open
    assert tick(107.1) == (True, False)           # miss 2
    assert tick(108.2) == (False, True)           # past threshold: DEAD
    # A different peer that speaks again is fully absolved.
    peer2 = types.SimpleNamespace(last_seen=100.0, hc_suspect=False,
                                  hc_misses=0, hc_probe_ts=0.0)
    probes, dead = [], []
    step(head, peer2, 106.0, timeout, period, threshold, probes, dead)
    assert peer2.hc_suspect
    peer2.last_seen = 107.0                       # spoke again
    step(head, peer2, 107.5, timeout, period, threshold, probes, dead)
    assert not peer2.hc_suspect and peer2.hc_misses == 0
    assert head.suspected_nodes == 2              # counted once per episode


# ------------------------------------------------------- knob plumbing --

def test_net_knobs_ride_worker_env_both_spawn_paths():
    """_system_config failure-detection knobs reach spawned workers
    through _worker_config_env on BOTH spawn paths (head-local
    subprocess and agent-forked); RTL504 pins the plumbing statically,
    this pins it live."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=1, _system_config={
        "failure_detection": False,
        "net_stall_timeout_s": 7.5,
        "net_connect_timeout_s": 2.25,
        "net_retry_count": 9,
        "net_retry_backoff_base_ms": 12.5,
        "health_check_period_s": 1.75,
        "health_check_timeout_s": 6.5,
        "health_check_failure_threshold": 4,
        "health_check_initial_delay_s": 3.25,
    })
    try:
        nid = c.add_node(num_cpus=1, external=True)

        @ray.remote
        def probe():
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg

            return (cfg.failure_detection, cfg.net_stall_timeout_s,
                    cfg.net_connect_timeout_s, cfg.net_retry_count,
                    cfg.net_retry_backoff_base_ms,
                    cfg.health_check_period_s,
                    cfg.health_check_timeout_s,
                    cfg.health_check_failure_threshold,
                    cfg.health_check_initial_delay_s)

        expected = (False, 7.5, 2.25, 9, 12.5, 1.75, 6.5, 4, 3.25)
        head_hex = c.rt.head_node.node_id.hex()
        assert ray.get(probe.options(scheduling_strategy=NA(
            node_id=head_hex, soft=False)).remote(), timeout=60) \
            == expected
        assert ray.get(probe.options(scheduling_strategy=NA(
            node_id=nid, soft=False)).remote(), timeout=60) == expected
    finally:
        c.shutdown()


def test_failure_detection_off_pins_counters():
    """Off-switch control: the PR 9 chaos acceptance shape (clean agent
    kill, recovery on) completes with failure_detection=off — and every
    failure-detection counter stays pinned at zero (the legacy blocking
    plane sends no heartbeat, arms no deadline, runs no suspicion
    thread)."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=2,
                _system_config={"failure_detection": False})
    chaos = None
    try:
        n1 = c.add_node(num_cpus=2, external=True)
        n2 = c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt)
        s1 = [_make.options(scheduling_strategy=NA(
            node_id=n2, soft=True)).remote(i) for i in range(8)]
        ray.wait(s1, num_returns=len(s1), timeout=60)
        # Kill BEFORE the consumers submit: n2-homed args are
        # guaranteed lost, so completion proves lineage reconstruction
        # engaged (soft pins keep the re-executions placeable).
        assert chaos.kill_agent(n2) == n2
        time.sleep(0.3)
        s2 = [_consume.options(scheduling_strategy=NA(
            node_id=n1, soft=True)).remote(r) for r in s1]
        assert ray.get(s2, timeout=120) == list(range(8))
        stats = c.rt.transfer_stats()
        assert stats["reconstructions"] >= 1, stats
        for k in NET_COUNTERS:
            assert stats[k] == 0, (k, stats[k])
        # No suspicion thread either — the switch means OFF, not idle.
        assert not any(t.name == "ray_tpu-suspicion"
                       for t in threading.enumerate())
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


# ------------------------------------------------------------ acceptance --

def _netchaos_fanout(n_tasks=40):
    """THE gray-failure acceptance scenario (shared with the lockcheck
    re-run): 2-agent cluster, ``n_tasks`` fan-out, with BOTH gray
    layers injected mid-run — the n2 data plane stalls mid-chunk (env
    net-chaos rule in the agent) and the n2 head link stalls (nothing
    EOFs, ever).  Every get must return the correct value, bounded;
    the deadline core counts stalls/retries/hedges; suspicion declares
    the node dead and lineage reconstructs what the relay can no
    longer reach.  Returns (values, stats, elapsed_s, agent_alive)."""
    from ray_tpu.cluster_utils import Cluster

    chaos_dir = tempfile.mkdtemp()
    c = Cluster(head_num_cpus=2, _system_config=dict(FAST_FD))
    chaos = None
    try:
        n1 = c.add_node(num_cpus=2, external=True)
        n2 = c.add_node(
            num_cpus=2, external=True,
            env_overrides={
                "RAY_TPU_CHAOS_NET": "agent:chunk_send:stall:2",
                "RAY_TPU_CHAOS_DIR": chaos_dir,
            })
        chaos = ChaosController(c.rt)

        half = n_tasks // 2
        # Soft pins: producers prefer (and land on) n2 while it is
        # healthy, and their lineage re-executions can place on n1 once
        # suspicion declares n2 dead (a hard pin would strand them).
        s1 = [_make.options(scheduling_strategy=NA(
            node_id=n2, soft=True)).remote(i) for i in range(half)]
        ray.wait(s1, num_returns=len(s1), timeout=60)

        # Consumers pinned cross-node: every arg pull crosses the link
        # that is about to go gray.  Mid-run, stall the n2 head link
        # too — no process dies, no socket closes.
        s2 = [_consume.options(scheduling_strategy=NA(
            node_id=n1, soft=True)).remote(r) for r in s1]
        time.sleep(0.2)
        assert chaos.stall_link(n2) == n2

        t0 = time.monotonic()
        out = ray.get(s2, timeout=120)
        elapsed = time.monotonic() - t0
        stats = c.rt.transfer_stats()
        proc = c._agents.get(n2)
        alive = proc is not None and proc.poll() is None
        return out, stats, elapsed, alive
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


def test_netchaos_acceptance_stalled_link_fanout():
    """A mid-run STALLED (not killed) agent: every get correct and
    bounded, zero hangs, stalls counted, the node suspected, and losses
    recovered through the existing lineage path."""
    out, stats, elapsed, agent_alive = _netchaos_fanout()
    assert out == list(range(20))
    # Bounded, not hanging: stall deadline trips + retries + hedge +
    # suspicion window + reconstruction all fit well inside the get
    # timeout; the explicit wall bound pins "bounded" against creep.
    assert elapsed < 90, elapsed
    assert stats["stall_timeouts"] >= 1, stats
    assert stats["suspected_nodes"] >= 1, stats
    assert stats["net_retries"] >= 1, stats
    assert stats["reconstructions"] >= 1, stats
    # Gray, not clean: the stalled agent process never exited.
    assert agent_alive


@pytest.mark.slow
def test_netchaos_oneway_partition_declares_dead_and_revokes():
    """One-way partition (the head goes deaf to a perfectly healthy
    agent): suspicion alone — silence, probes, threshold — declares
    the node dead and the PR 6 path revokes its leases, without ANY
    process having exited."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=0, _system_config=dict(FAST_FD))
    chaos = None
    try:
        n2 = c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt)

        # Park lease-holding work on the node so there are leases to
        # revoke when suspicion declares it dead.
        @ray.remote
        def slow(i):
            time.sleep(8)
            return i

        refs = [slow.options(scheduling_strategy=NA(
            node_id=n2, soft=False)).remote(i) for i in range(2)]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and c.rt.transfer_stats()["lease_grants"] == 0:
            time.sleep(0.1)

        assert chaos.partition(n2, direction="in") == n2
        deadline = time.monotonic() + 20
        dead = False
        while time.monotonic() < deadline:
            nodes = {n["node_id"]: n["alive"] for n in c.rt.list_nodes()}
            if nodes.get(n2) is False:
                dead = True
                break
            time.sleep(0.2)
        assert dead, "suspicion never declared the partitioned node dead"
        stats = c.rt.transfer_stats()
        assert stats["suspected_nodes"] >= 1, stats
        proc = c._agents.get(n2)
        assert proc is not None and proc.poll() is None, \
            "partition variant must not kill any process"
        del refs
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


@pytest.mark.slow
def test_drop_worker_connection_stall_variant_ab():
    """The A/B satellite: drop_worker_connection(stall=False) is the
    clean half-death (immediate EOF discovery), stall=True the gray one
    (socket open, head deaf) — one API; the gray drop is only
    discoverable by suspicion, counts a net_fault, and the fan-out
    still completes."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=0, _system_config=dict(FAST_FD))
    chaos = None
    try:
        c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt)

        @ray.remote(max_retries=3)
        def f(i):
            time.sleep(0.25)
            return i * 3

        refs = [f.remote(i) for i in range(16)]
        # Wait until a worker is demonstrably up (first result back)
        # before taking its conn away — dropping during spawn finds no
        # victim.
        ready, _ = ray.wait(refs, num_returns=1, timeout=60)
        assert ready
        assert chaos.drop_worker_connection(stall=True) is not None
        assert ray.get(refs, timeout=90) == [i * 3 for i in range(16)]
        stats = c.rt.transfer_stats()
        assert stats["suspected_nodes"] >= 1, stats
        assert chaos.stats()["net_faults"] >= 1
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


# ----------------------------------------------------- lockcheck re-run --

@pytest.mark.slow
def test_netchaos_battery_under_lockcheck():
    """The acceptance shape re-run under RAY_TPU_LOCKCHECK=1: the new
    suspicion loop, deadline retries, and net-chaos hook must introduce
    zero lock-order cycles (head/agent/workers all inherit the
    instrumentation)."""
    code = textwrap.dedent("""
        import os, tempfile, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        import numpy as np
        import ray_tpu as ray
        from ray_tpu.devtools import lockcheck
        from ray_tpu.chaos import ChaosController
        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy as NA,
        )

        cfg = {"net_stall_timeout_s": 0.8, "net_retry_count": 1,
               "net_retry_backoff_base_ms": 20.0,
               "health_check_period_s": 0.25,
               "health_check_timeout_s": 1.0,
               "health_check_failure_threshold": 2,
               "health_check_initial_delay_s": 1.0}
        chaos_dir = tempfile.mkdtemp()
        c = Cluster(head_num_cpus=2, _system_config=cfg)
        chaos = None
        try:
            n1 = c.add_node(num_cpus=2, external=True)
            n2 = c.add_node(num_cpus=2, external=True, env_overrides={
                "RAY_TPU_CHAOS_NET": "agent:chunk_send:stall:2",
                "RAY_TPU_CHAOS_DIR": chaos_dir})
            chaos = ChaosController(c.rt)

            @ray.remote(max_retries=3)
            def make(i):
                return np.full(260_000, i, dtype=np.int64)

            @ray.remote(max_retries=3)
            def consume(a):
                return int(a[0])

            s1 = [make.options(scheduling_strategy=NA(
                node_id=n2, soft=True)).remote(i) for i in range(8)]
            ray.wait(s1, num_returns=len(s1), timeout=60)
            s2 = [consume.options(scheduling_strategy=NA(
                node_id=n1, soft=True)).remote(r) for r in s1]
            time.sleep(0.2)
            assert chaos.stall_link(n2) == n2
            assert ray.get(s2, timeout=120) == list(range(8))
            stats = c.rt.transfer_stats()
            assert stats["stall_timeouts"] >= 1, stats
            assert stats["suspected_nodes"] >= 1, stats
        finally:
            if chaos is not None:
                chaos.stop()
            c.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        print("NETCHAOS_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    assert "NETCHAOS_LOCKCHECK_OK" in proc.stdout
