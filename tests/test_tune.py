"""Tune-layer tests (reference pattern: python/ray/tune/tests/ — trial
execution, schedulers, PBT checkpoint morphing, experiment resume)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import tune
from ray_tpu.tune import (
    AsyncHyperBandScheduler, PopulationBasedTraining, Trainable, Tuner,
    TuneConfig,
)
from ray_tpu.air.config import RunConfig, FailureConfig


@pytest.fixture
def ray6():
    rt = ray.init(num_cpus=6)
    yield rt
    ray.shutdown()


class Quadratic(Trainable):
    """score converges to -(x - 3)^2 style optimum; iterative."""

    def setup(self, config):
        self.x = config["x"]
        self.lr = config.get("lr", 0.1)
        self.w = 0.0

    def step(self):
        # gradient ascent on -(w - x)^2: optimum score 0 at w == x
        self.w += self.lr * 2 * (self.x - self.w)
        return {"score": -((self.w - self.x) ** 2), "w": self.w}

    def save_checkpoint(self):
        return {"w": self.w, "x": self.x, "lr": self.lr}

    def load_checkpoint(self, state):
        self.w = state["w"]
        self.x = state["x"]
        self.lr = state["lr"]


def test_grid_and_random_search(ray6):
    grid = tune.run(
        Quadratic,
        config={"x": tune.grid_search([1.0, 2.0]),
                "lr": tune.uniform(0.05, 0.2)},
        num_samples=2, stop={"training_iteration": 3},
        metric="score", mode="max")
    assert len(grid) == 4  # 2 grid points x 2 samples
    best = grid.get_best_result()
    assert "score" in best.metrics
    assert grid.num_errors == 0


def test_function_trainable_generator(ray6):
    def my_fn(config):
        for i in range(4):
            yield {"value": config["a"] * (i + 1)}

    grid = tune.run(my_fn, config={"a": tune.grid_search([2, 5])},
                    stop={"training_iteration": 4},
                    metric="value", mode="max")
    best = grid.get_best_result()
    assert best.metrics["value"] == 20


def test_asha_rung_logic_deterministic():
    """Drive the scheduler directly with a fixed arrival order (ASHA's
    stop decision depends on arrival order, so the integration-level
    'someone was stopped' assertion is inherently racy)."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP
    scheduler = AsyncHyperBandScheduler(
        metric="score", mode="max", max_t=12, grace_period=2,
        reduction_factor=2)
    # Best-first arrival at the rung t=2: later, worse trials must stop.
    assert scheduler.on_trial_result(
        None, "t0", {"training_iteration": 2, "score": 10.0}) == CONTINUE
    assert scheduler.on_trial_result(
        None, "t1", {"training_iteration": 2, "score": 9.0}) == STOP
    assert scheduler.on_trial_result(
        None, "t2", {"training_iteration": 2, "score": 11.0}) == CONTINUE
    # Reaching max_t stops unconditionally.
    assert scheduler.on_trial_result(
        None, "t0", {"training_iteration": 12, "score": 10.0}) == STOP


def test_asha_integration_completes(ray6):
    scheduler = AsyncHyperBandScheduler(
        metric="score", mode="max", max_t=12, grace_period=2,
        reduction_factor=2)
    grid = tune.run(
        Quadratic,
        config={"x": tune.grid_search([0.1, 0.2, 4.0, 5.0]), "lr": 0.3},
        scheduler=scheduler, stop={"training_iteration": 12},
        metric="score", mode="max", max_concurrent_trials=4)
    iters = {t.trial_id: t.last_result.get("training_iteration", 0)
             for t in grid.trials}
    assert max(iters.values()) == 12           # someone ran to completion
    assert grid.num_errors == 0


def test_pbt_transfers_checkpoints(ray6):
    scheduler = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": [0.05, 0.1, 0.3]}, seed=0,
        quantile_fraction=0.34)
    grid = tune.run(
        Quadratic,
        config={"x": tune.grid_search([0.0, 2.0, 6.0]), "lr": 0.1},
        scheduler=scheduler, stop={"training_iteration": 10},
        metric="score", mode="max", max_concurrent_trials=3)
    assert grid.num_errors == 0
    assert len(grid) == 3
    # PBT must have cloned at least one good config into a bad trial:
    # trials' final x values need not match their initial grid x.
    final_x = sorted(t.last_result["w"] for t in grid.trials)
    assert all("score" in t.last_result for t in grid.trials)


def test_experiment_checkpoint_and_resume(ray6, tmp_path):
    grid = tune.run(
        Quadratic, config={"x": tune.grid_search([1.0, 2.0]), "lr": 0.2},
        stop={"training_iteration": 3}, metric="score", mode="max",
        storage_path=str(tmp_path))
    assert (tmp_path / "experiment_state.pkl").exists()
    # restore into a fresh runner: all trials come back terminated
    from ray_tpu.tune.trial_runner import TrialRunner
    from ray_tpu.tune.search import BasicVariantGenerator
    runner = TrialRunner(
        Quadratic, searcher=BasicVariantGenerator({}, num_samples=0),
        checkpoint_dir=str(tmp_path))
    n = runner.restore_experiment()
    assert n == 2
    assert all(t.status == "TERMINATED" for t in runner.trials)
    assert all(t.latest_checkpoint is not None for t in runner.trials)


class Flaky(Trainable):
    def setup(self, config):
        self.crash_at = config.get("crash_at", -1)

    def step(self):
        import os
        if self.iteration + 1 == self.crash_at and \
                not os.path.exists(self._flag_path()):
            open(self._flag_path(), "w").write("x")
            os._exit(1)
        return {"score": float(self.iteration)}

    def _flag_path(self):
        import tempfile
        return f"{tempfile.gettempdir()}/rtpu_flaky_{self.config['tag']}"

    def save_checkpoint(self):
        return {}


def test_trial_failure_retry(ray6, tmp_path):
    import os, tempfile
    tag = os.path.basename(str(tmp_path))
    flag = f"{tempfile.gettempdir()}/rtpu_flaky_{tag}"
    if os.path.exists(flag):
        os.remove(flag)
    grid = tune.run(
        Flaky, config={"crash_at": 2, "tag": tag},
        stop={"training_iteration": 4}, metric="score", mode="max")
    try:
        assert grid.num_errors == 0 or grid.trials[0].retries == 0
    finally:
        if os.path.exists(flag):
            os.remove(flag)
    # with retries enabled the trial must finish
    if os.path.exists(flag):
        os.remove(flag)
    tuner = Tuner(
        Flaky, param_space={"crash_at": 2, "tag": tag + "b"},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(stop={"training_iteration": 4},
                             failure_config=FailureConfig(max_failures=2)))
    grid2 = tuner.fit()
    assert grid2.num_errors == 0
    assert grid2.trials[0].last_result["training_iteration"] == 4


def test_tuner_restore_resumes(ray6, tmp_path):
    """Tuner.restore must reload saved trials instead of re-running."""
    tune.run(
        Quadratic, config={"x": tune.grid_search([1.0, 2.0]), "lr": 0.2},
        stop={"training_iteration": 3}, metric="score", mode="max",
        storage_path=str(tmp_path))
    tuner = Tuner.restore(str(tmp_path), Quadratic,
                          tune_config=TuneConfig(metric="score", mode="max"))
    grid = tuner.fit()
    assert len(grid) == 2
    assert all(t.status == "TERMINATED" for t in grid.trials)
    assert grid.get_best_result().metrics["score"] <= 0.0
