"""Actor tests (reference model: python/ray/tests/test_actor.py,
test_actor_failures.py)."""

import os
import time

import pytest

import ray_tpu as ray


def test_basic_actor(ray_start_regular):
    @ray.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(10)
    assert ray.get(c.inc.remote(), timeout=30) == 11
    assert ray.get([c.inc.remote() for _ in range(5)]) == [12, 13, 14, 15, 16]


def test_actor_method_ordering(ray_start_regular):
    @ray.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return list(self.items)

    a = Appender.remote()
    refs = [a.add.remote(i) for i in range(20)]
    assert ray.get(refs[-1], timeout=30) == list(range(20))


def test_actor_error(ray_start_regular):
    @ray.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method error")

        def ok(self):
            return 1

    b = Bad.remote()
    with pytest.raises(ray.exceptions.TaskError):
        ray.get(b.fail.remote(), timeout=30)
    # actor survives method errors
    assert ray.get(b.ok.remote(), timeout=30) == 1


def test_actor_constructor_error(ray_start_regular):
    @ray.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("ctor boom")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(b.m.remote(), timeout=30)


def test_actor_death_and_restart(ray_start_regular):
    @ray.remote(max_restarts=1)
    class Flaky:
        def crash(self):
            os._exit(1)

        def ping(self):
            return "pong"

    f = Flaky.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(f.crash.remote(), timeout=30)
    deadline = time.monotonic() + 60  # generous: 1-cpu CI boxes crawl
    last = None
    while time.monotonic() < deadline:
        try:
            assert ray.get(f.ping.remote(), timeout=10) == "pong"
            break
        except ray.exceptions.RayTpuError as e:
            last = e
            time.sleep(0.2)
    else:
        from ray_tpu._private import api_internal

        rt = api_internal.get_runtime()
        actor = next(iter(rt.actors.values()), None)
        pytest.fail(
            f"actor did not restart: last={type(last).__name__}({last}); "
            f"actor_status={actor and actor.status} "
            f"restarts_left={actor and actor.restarts_left}")


def test_actor_no_restart_stays_dead(ray_start_regular):
    @ray.remote
    class Once:
        def crash(self):
            os._exit(1)

        def ping(self):
            return "pong"

    o = Once.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(o.crash.remote(), timeout=30)
    time.sleep(0.5)
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(o.ping.remote(), timeout=30)


def test_ray_kill(ray_start_regular):
    @ray.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray.get(v.ping.remote(), timeout=30) == "pong"
    ray.kill(v)
    time.sleep(0.5)
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(v.ping.remote(), timeout=30)


def test_named_actor(ray_start_regular):
    @ray.remote
    class Registry:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    r = Registry.options(name="reg").remote()
    ray.get(r.set.remote("a", 1), timeout=30)
    r2 = ray.get_actor("reg")
    assert ray.get(r2.get.remote("a"), timeout=30) == 1
    with pytest.raises(ValueError):
        ray.get_actor("missing")


def test_get_if_exists(ray_start_regular):
    @ray.remote
    class Singleton:
        def whoami(self):
            return id(self)

    a = Singleton.options(name="s", get_if_exists=True).remote()
    b = Singleton.options(name="s", get_if_exists=True).remote()
    ia = ray.get(a.whoami.remote(), timeout=30)
    ib = ray.get(b.whoami.remote(), timeout=30)
    assert ia == ib


def test_actor_handle_in_task(ray_start_regular):
    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self, k):
            self.n += k
            return self.n

    @ray.remote
    def bump(counter, k):
        return ray.get(counter.inc.remote(k))

    c = Counter.remote()
    assert ray.get(bump.remote(c, 5), timeout=60) == 5
    assert ray.get(bump.remote(c, 2), timeout=60) == 7


def test_actor_creates_actor(ray_start_regular):
    @ray.remote
    class Child:
        def val(self):
            return 7

    @ray.remote
    class Parent:
        def spawn(self):
            child = Child.remote()
            return ray.get(child.val.remote())

    p = Parent.remote()
    assert ray.get(p.spawn.remote(), timeout=60) == 7


def test_async_actor(ray_start_regular):
    @ray.remote
    class AsyncActor:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.remote()
    assert ray.get(a.work.remote(21), timeout=30) == 42


def test_max_concurrency(ray_start_regular):
    @ray.remote(max_concurrency=4)
    class Slow:
        def work(self):
            time.sleep(0.3)
            return 1

    s = Slow.remote()
    # Warm up first: actor creation is async, so without this the timed
    # window includes worker-process boot + __init__ and the assertion
    # flakes under machine load (seed failed ~2/5 runs).
    ray.get(s.work.remote(), timeout=30)
    t0 = time.monotonic()
    ray.get([s.work.remote() for _ in range(4)], timeout=30)
    elapsed = time.monotonic() - t0
    # 4 concurrent 0.3s calls should take ~0.3s, not 1.2s
    assert elapsed < 1.0, elapsed


def test_method_num_returns(ray_start_regular):
    @ray.remote
    class M:
        @ray.method(num_returns=2)
        def two(self):
            return 1, 2

    m = M.remote()
    a, b = m.two.remote()
    assert ray.get([a, b], timeout=30) == [1, 2]
