"""Framework linter tests: every rule's good/bad fixture pair, exact rule
IDs and line numbers, suppression syntax, and the CLI contract.

The EXPECT harness covers ALL THREE analyzers: per-file lint findings,
whole-program protocheck findings (a proto fixture names its companion
modules with `# protocheck-with: other.py`, so the two-module cases —
sender/handler arity drift, knob plumbing — analyze as one program with
findings attributed per file), and lockgraph's interprocedural RTL6xx
verdicts over the same file set."""

import os
import re
import subprocess
import sys

from ray_tpu.devtools import lint, lockgraph, protocheck

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "lint_fixtures")
_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([A-Z0-9]+)")
_WITH_RE = re.compile(r"#\s*protocheck-with:\s*([\w.,\s]+)")


def _expected_findings(path):
    """{(line, rule)} declared by `# EXPECT: RTLxxx` markers in a file."""
    out = set()
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            for rule in _EXPECT_RE.findall(line):
                out.add((lineno, rule))
    return out


def _companions(path):
    """Fixture files this one analyzes WITH (the whole-program cases)."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in list(f)[:10]:
            m = _WITH_RE.search(line)
            if m:
                out.extend(
                    os.path.join(FIXTURE_DIR, c.strip())
                    for c in m.group(1).split(",") if c.strip())
    return out


def _fixture_findings(path):
    """{(line, rule)} from all three analyzers, attributed to this
    file."""
    companions = _companions(path)
    got = {(f.line, f.rule) for f in lint.lint_file(path)}
    got |= {(f.line, f.rule)
            for f in protocheck.check_paths([path] + companions)
            if f.path == path}
    got |= {(f.line, f.rule)
            for f in lockgraph.check_paths([path] + companions)
            if f.path == path}
    return got


def _fixture_files():
    return sorted(
        os.path.join(FIXTURE_DIR, name)
        for name in os.listdir(FIXTURE_DIR)
        if name.endswith(".py"))


def test_fixtures_exist_in_good_bad_pairs():
    names = {os.path.basename(p) for p in _fixture_files()}
    bad = {n[len("bad_"):] for n in names if n.startswith("bad_")}
    good = {n[len("good_"):] for n in names if n.startswith("good_")}
    assert bad and bad == good, (bad, good)


def test_every_rule_has_a_firing_fixture():
    covered = set()
    for path in _fixture_files():
        covered.update(rule for _, rule in _expected_findings(path))
    all_rules = (set(lint.RULES) | set(protocheck.RULES)
                 | set(lockgraph.RULES))
    assert covered == all_rules, (
        f"rules without a bad fixture: {all_rules - covered}")


def test_fixture_findings_match_exactly():
    """Findings == EXPECT markers, per file: bad lines fire with the right
    rule ID on the right line, and NOTHING else fires (good files pin the
    negative space)."""
    for path in _fixture_files():
        got = _fixture_findings(path)
        want = _expected_findings(path)
        assert got == want, (
            f"{os.path.basename(path)}: findings {sorted(got)} != "
            f"expected {sorted(want)}")


def test_good_fixtures_are_silent():
    for path in _fixture_files():
        if os.path.basename(path).startswith("good_"):
            assert _fixture_findings(path) == set(), path


def test_noqa_requires_rule_id():
    src = "def f(l):\n    l.my_lock.acquire()  # noqa\n"
    assert [f.rule for f in lint.lint_source(src)] == ["RTL401"]
    src = "def f(l):\n    l.my_lock.acquire()  # noqa: RTL401 -- handoff\n"
    assert lint.lint_source(src) == []
    # Suppressing a DIFFERENT rule does not silence this one.
    src = "def f(l):\n    l.my_lock.acquire()  # noqa: RTL301\n"
    assert [f.rule for f in lint.lint_source(src)] == ["RTL401"]
    # Rationale text without the '--' separator still suppresses.
    src = "def f(l):\n    l.my_lock.acquire()  # noqa: RTL401 handoff\n"
    assert lint.lint_source(src) == []


def test_syntax_error_reports_rtl000():
    findings = lint.lint_source("def broken(:\n", "x.py")
    assert [f.rule for f in findings] == ["RTL000"]


def test_cli_contract_via_python_dash_m():
    """The real `python -m ray_tpu.devtools.lint` entry: exit 1 with rule
    ID + file:line on a bad fixture (one subprocess keeps this cheap; the
    other CLI behaviors are covered in-process below)."""
    bad = os.path.join(FIXTURE_DIR, "bad_lock_acquire.py")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lint", bad],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "RTL401" in proc.stdout
    assert re.search(r"bad_lock_acquire\.py:\d+:\d+", proc.stdout)


def test_main_exits_nonzero_with_rule_and_location(capsys):
    bad = os.path.join(FIXTURE_DIR, "bad_bare_except.py")
    assert lint.main([bad]) == 1
    out = capsys.readouterr().out
    assert "RTL301" in out
    assert re.search(r"bad_bare_except\.py:\d+:\d+", out)


def test_main_exits_zero_on_clean_input(capsys):
    good = os.path.join(FIXTURE_DIR, "good_lock_acquire.py")
    assert lint.main([good]) == 0
    assert capsys.readouterr().out.strip() == ""


def test_main_rejects_missing_paths(capsys):
    # A typo'd path must not pass green without linting anything.
    assert lint.main(["no_such_dir/"]) == 2
    assert "no such path" in capsys.readouterr().err


def test_directory_walk_skips_fixture_corpus():
    # The documented `lint tests/` invocation must not drown in the
    # linter's own bad-fixture corpus...
    walk = lint._iter_py_files([os.path.dirname(FIXTURE_DIR)])
    assert not any(os.sep + "lint_fixtures" + os.sep in p for p in walk)
    # ...but naming a fixture file explicitly still lints it.
    bad = os.path.join(FIXTURE_DIR, "bad_bare_except.py")
    assert lint._iter_py_files([bad]) == [bad]


def test_explicit_file_without_py_extension_is_linted(tmp_path):
    script = tmp_path / "extensionless_tool"
    script.write_text("try:\n    pass\nexcept:\n    pass\n")
    findings = lint.lint_paths([str(script)])
    assert [f.rule for f in findings] == ["RTL301"]


def test_main_list_rules(capsys):
    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in lint.RULES:
        assert rule_id in out


def test_main_doc_renders_rule_table(capsys):
    assert lint.main(["--doc"]) == 0
    out = capsys.readouterr().out
    assert "| rule | what it catches |" in out
    for rule_id in lint.RULES:
        assert rule_id in out


def test_main_select_runs_rules_individually(capsys):
    bad = os.path.join(FIXTURE_DIR, "bad_lock_acquire.py")
    # The file fires RTL401; selecting it keeps the finding...
    assert lint.main(["--select=RTL401", bad]) == 1
    assert "RTL401" in capsys.readouterr().out
    # ...selecting a different rule silences the run (exit 0)...
    assert lint.main(["--select=RTL301", bad]) == 0
    assert capsys.readouterr().out.strip() == ""
    # ...and a family prefix selects the whole family.
    assert lint.main(["--select=RTL4", bad]) == 1
    assert "RTL401" in capsys.readouterr().out
    # A selector matching NO rule is an error, not a silent green run.
    assert lint.main(["--select=RTL9", bad]) == 2
    assert "matches no rule" in capsys.readouterr().err
