"""Worker log capture + streaming (reference: log_monitor.py tailing
worker files to the driver; `ray logs` surface)."""
import time

import pytest

import ray_tpu as ray
from ray_tpu.util.state import get_worker_log


@pytest.fixture
def init2():
    ray.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray.shutdown()


def _wait_lines(needle, timeout=15):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for entry in get_worker_log():
            if any(needle in ln for ln in entry["lines"]):
                return entry
        time.sleep(0.4)
    return None


def test_task_prints_are_captured(init2, capfd):
    @ray.remote
    def noisy():
        print("hello-from-worker-42")
        return 1

    assert ray.get(noisy.remote()) == 1
    entry = _wait_lines("hello-from-worker-42")
    assert entry is not None, get_worker_log()
    assert entry["worker_id"]
    # log_to_driver re-prints with a worker prefix on driver stderr.
    err = capfd.readouterr().err
    assert "hello-from-worker-42" in err
    assert "(worker=" in err


def test_remote_node_logs_ship_to_head():
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(head_num_cpus=1)
    try:
        node_id = cluster.add_node(num_cpus=2, external=True)

        @ray.remote
        def remote_noisy():
            print("hello-from-remote-node")
            return 2

        ref = remote_noisy.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id, soft=False)).remote()
        assert ray.get(ref, timeout=60) == 2
        entry = _wait_lines("hello-from-remote-node")
        assert entry is not None
    finally:
        cluster.shutdown()
