"""Elastic-pod battery: preemption-aware node drain, notice sources,
spot scale-down through the drain protocol, and the sustained-traffic
chaos drill.

Reference pattern: the DrainNode protocol tests + chaos release jobs —
a planned departure (scale-down, spot warning window) must lose nothing
(leases revoked, restartable actors checkpointed to a surviving store,
small sole-copy objects migrated), while a no-warning kill falls back
to PR 9's lineage reconstruction.  The off-switch (``elastic_drain=
False``) must reproduce the legacy hard-remove behavior with every new
counter zero.
"""

import time

import pytest

import ray_tpu as ray
from ray_tpu import serve
from ray_tpu.autoscaler import FakeSliceProvider, StandardAutoscaler
from ray_tpu.chaos import ChaosController
from ray_tpu.cluster_utils import Cluster

ELASTIC_KEYS = ("preemptions", "drains_completed", "drain_timeouts",
                "objects_migrated")


def _wait_for(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def _one_head_node(rt):
    return sum(1 for n in rt.list_nodes() if n["alive"]) == 1


def test_drain_migrates_objects_and_node_removal_loses_nothing():
    """drain_node on a node holding sole-copy shm results: the objects
    are pulled and re-homed on the head's surviving store, the released
    agent exits cleanly, and every get after the node is gone is served
    from the migrated copy — zero reconstructions."""
    c = Cluster(head_num_cpus=1)
    try:
        nid = c.add_node(num_cpus=2, resources={"slice": 1}, external=True)

        @ray.remote(resources={"slice": 0.1})
        def produce(i):
            import numpy as np

            return np.full(300_000, i)  # ~2.4 MB -> the node's shm store

        refs = [produce.remote(i) for i in range(4)]
        ray.wait(refs, num_returns=4, timeout=60, fetch_local=False)
        rt = c.rt
        assert rt.drain_node(nid, 20.0, "test") is True
        st = rt.transfer_stats()
        assert st["drains_completed"] == 1
        assert st["drain_timeouts"] == 0
        assert st["objects_migrated"] >= 4
        # The drain_node release makes the agent exit on its own — no
        # terminate, no kill.
        assert _wait_for(lambda: _one_head_node(rt)), rt.list_nodes()
        vals = ray.get(refs, timeout=60)
        assert [int(v[0]) for v in vals] == [0, 1, 2, 3]
        assert rt.transfer_stats()["reconstructions"] == 0
    finally:
        c.shutdown()


def test_drain_migrates_spilled_sole_copies():
    """A node under store pressure SPILLS results to its local disk —
    which dies with the node exactly like its shm pages.  Drain
    migrates spilled sole-copies under the size cap too (the object
    server attaches them by absolute path like any segment)."""
    c = Cluster(head_num_cpus=1)
    try:
        # 4 MB store cap on the node: four ~2.4 MB results cannot all
        # stay resident — at least two spill to the node's disk.
        nid = c.add_node(num_cpus=2, resources={"slice": 1},
                         external=True,
                         env_overrides={"RAY_TPU_STORE_BYTES":
                                        str(4 * 1024 * 1024)})

        @ray.remote(resources={"slice": 0.1})
        def produce(i):
            import numpy as np

            return np.full(300_000, i)

        refs = [produce.remote(i) for i in range(4)]
        ray.wait(refs, num_returns=4, timeout=60, fetch_local=False)
        rt = c.rt
        with rt.lock:
            spilled = sum(1 for st in rt.objects.values()
                          if st.descr is not None
                          and st.descr[0] == "spilled")
        assert spilled >= 1, "store cap never forced a spill"
        assert rt.drain_node(nid, 20.0, "test") is True
        st = rt.transfer_stats()
        assert st["objects_migrated"] >= 4  # resident AND spilled moved
        assert _wait_for(lambda: _one_head_node(rt)), rt.list_nodes()
        vals = ray.get(refs, timeout=60)
        assert [int(v[0]) for v in vals] == [0, 1, 2, 3]
        assert rt.transfer_stats()["reconstructions"] == 0
    finally:
        c.shutdown()


def test_drain_force_checkpoints_actor_to_surviving_store():
    """A restartable actor on the draining node gets a forced
    __ray_save__ whose state is re-homed on the HEAD's store (a
    checkpoint homed on the dying node would be dropped at restart,
    PR 9); after the node dies the actor restarts on fresh capacity
    with the drained state intact."""
    c = Cluster(head_num_cpus=1)
    try:
        nid = c.add_node(num_cpus=2, resources={"slice": 1}, external=True)

        @ray.remote(max_restarts=-1, resources={"slice": 0.5})
        class Ck:
            def __init__(self):
                import numpy as np

                self.n = 0
                # Big enough that the forced checkpoint must ship as
                # PARTS (the store path, not inline) — pinning the
                # re-homing, not just the hook.
                self.buf = np.arange(300_000)

            def bump(self):
                self.n += 1
                return self.n

            def get(self):
                return self.n

            def __ray_save__(self):
                return (self.n, self.buf)

            def __ray_restore__(self, state):
                self.n, self.buf = state

        a = Ck.remote()
        assert ray.get(a.bump.remote(), timeout=60) == 1
        assert ray.get(a.bump.remote(), timeout=60) == 2
        rt = c.rt
        assert rt.drain_node(nid, 20.0, "test") is True
        with rt.lock:
            (actor,) = list(rt.actors.values())
            ck = actor.checkpoint
        # Forced checkpoint retained, homed on the head's (surviving)
        # store — not the draining node's.
        assert ck is not None and ck[0] == "shm" and ck[3] == rt.store_id
        assert _wait_for(lambda: _one_head_node(rt)), rt.list_nodes()
        # Fresh capacity: the actor restarts there and restores the
        # state saved AT DRAIN TIME (n == 2), not a fresh __init__.
        c.add_node(num_cpus=2, resources={"slice": 1}, external=True)
        assert ray.get(a.get.remote(), timeout=90) == 2
        st = rt.transfer_stats()
        assert st["drains_completed"] == 1
        assert st["actor_restarts"] == 1
    finally:
        c.shutdown()


def test_preempt_notice_graceful_self_drain():
    """The warning-window path end to end: chaos ``preempt`` (SIGUSR1)
    -> agent preempt_notice -> head drain -> drain_node release ->
    clean agent exit.  Zero object loss, zero reconstructions."""
    c = Cluster(head_num_cpus=1)
    try:
        c.add_node(num_cpus=2, resources={"slice": 1}, external=True)

        @ray.remote(resources={"slice": 0.1})
        def produce(i):
            import numpy as np

            return np.full(300_000, i)

        refs = [produce.remote(i) for i in range(3)]
        ray.wait(refs, num_returns=3, timeout=60, fetch_local=False)
        rt = c.rt
        with ChaosController(rt) as chaos:
            assert chaos.preempt_node(notice=True) is not None
            assert _wait_for(
                lambda: rt.transfer_stats()["drains_completed"] >= 1)
            st = rt.transfer_stats()
            assert st["preemptions"] == 1
            assert st["objects_migrated"] >= 3
            assert st["chaos_kills"] == 1
            assert _wait_for(lambda: _one_head_node(rt))
            vals = ray.get(refs, timeout=60)
            assert [int(v[0]) for v in vals] == [0, 1, 2]
            assert rt.transfer_stats()["reconstructions"] == 0
    finally:
        c.shutdown()


def test_no_notice_preemption_recovers_via_lineage():
    """The no-warning variant (SIGKILL): the same objects are LOST with
    the node and come back through PR 9 lineage reconstruction on a
    surviving slice — correct gets, bounded rebuild, no drain counters."""
    c = Cluster(head_num_cpus=1)
    try:
        nid1 = c.add_node(num_cpus=2, resources={"slice": 1},
                          external=True)

        @ray.remote(resources={"slice": 0.1})
        def produce(i):
            import numpy as np

            return np.full(300_000, i)

        refs = [produce.remote(i) for i in range(3)]
        ray.wait(refs, num_returns=3, timeout=60, fetch_local=False)
        # The surviving slice the producers re-execute on.
        c.add_node(num_cpus=2, resources={"slice": 1}, external=True)
        rt = c.rt
        with ChaosController(rt) as chaos:
            assert chaos.preempt_node(node_id=nid1, notice=False) == nid1
            vals = ray.get(refs, timeout=120)
            assert [int(v[0]) for v in vals] == [0, 1, 2]
            st = rt.transfer_stats()
            assert 1 <= st["reconstructions"] <= 3
            for k in ELASTIC_KEYS:
                assert st[k] == 0, (k, st[k])
    finally:
        c.shutdown()


def test_scale_down_routes_through_drain():
    """Idle scale-down goes through the drain protocol before
    terminate_node — counter-pinned on both sides (head transfer_stats
    and StandardAutoscaler.stats())."""
    c = Cluster(head_num_cpus=2)
    try:
        provider = FakeSliceProvider(c, {
            "spot-v5e": {"resources": {"CPU": 2, "slice": 1},
                         "max_workers": 2, "spot": True},
        })
        scaler = StandardAutoscaler(c.rt, provider, idle_timeout_s=1.0)

        @ray.remote(resources={"slice": 0.5})
        def f(i):
            return i * 3

        refs = [f.remote(i) for i in range(2)]
        time.sleep(0.2)
        launched = scaler.update()["launched"]
        assert launched
        assert ray.get(refs, timeout=120) == [0, 3]
        del refs
        gone = []
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline and len(gone) < len(launched):
            gone += scaler.update()["terminated"]
            time.sleep(0.3)
        assert len(gone) == len(launched), gone
        # The drain runs off-thread (the tick stays reactive): the
        # counters land at its conclusion, just after the report.
        assert _wait_for(lambda: c.rt.transfer_stats()
                         ["drains_completed"] >= len(gone), 30)
        assert c.rt.transfer_stats()["drain_timeouts"] == 0
        assert _wait_for(lambda: scaler.stats()
                         ["drains_completed"] >= len(gone), 10)
        sc = scaler.stats()
        assert sc["drains_requested"] >= len(gone)
        assert sc["autoscaler_errors"] == 0
    finally:
        c.shutdown()


def test_elastic_drain_off_is_legacy_hard_remove():
    """The off-switch: scale-down is a bare terminate_node, drain_node
    refuses, a preemption notice is never solicited (the head withholds
    drain_caps) — and every elastic counter stays zero."""
    c = Cluster(head_num_cpus=2,
                _system_config={"elastic_drain": False})
    try:
        provider = FakeSliceProvider(c, {
            "v5e": {"resources": {"CPU": 2, "slice": 1},
                    "max_workers": 1},
        })
        scaler = StandardAutoscaler(c.rt, provider, idle_timeout_s=0.5)

        @ray.remote(resources={"slice": 0.5})
        def f():
            return "ok"

        ref = f.remote()
        time.sleep(0.2)
        (nid,) = scaler.update()["launched"]
        assert ray.get(ref, timeout=120) == "ok"
        assert c.rt.drain_node(nid) is False  # switched off: refuses
        gone = []
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not gone:
            gone = scaler.update()["terminated"]
            time.sleep(0.3)
        assert gone == [nid]
        assert _wait_for(lambda: _one_head_node(c.rt))
        st = c.rt.transfer_stats()
        for k in ELASTIC_KEYS:
            assert st[k] == 0, (k, st[k])
        assert scaler.stats()["drains_requested"] == 0
    finally:
        c.shutdown()


def test_elastic_knobs_ride_worker_env():
    """_system_config elastic knobs reach spawned workers through
    _worker_config_env (both spawn paths share it; RTL504 pins the
    plumbing statically, this pins it live)."""
    ray.init(num_cpus=1, _system_config={
        "elastic_drain": False, "drain_deadline_s": 3.5,
        "drain_migrate_max_bytes": 123456,
        "spot_fallback_threshold": 7})
    try:
        @ray.remote
        def probe():
            import os

            return (os.environ.get("RAY_TPU_ELASTIC_DRAIN"),
                    os.environ.get("RAY_TPU_DRAIN_DEADLINE_S"),
                    os.environ.get("RAY_TPU_DRAIN_MIGRATE_MAX_BYTES"),
                    os.environ.get("RAY_TPU_SPOT_FALLBACK_THRESHOLD"))

        assert ray.get(probe.remote(), timeout=60) == (
            "0", "3.5", "123456", "7")
    finally:
        ray.shutdown()


def _elastic_drill(graceful: bool, duration_s: float,
                   p99_bound_s: float):
    """THE drill: sustained serve + task traffic while the autoscaler
    adds spot slices and chaos preempts one mid-run.  Returns the head
    stats and the serve p99 for the caller's variant-specific asserts.
    Every serve response and every task get is checked for exact
    correctness inline."""
    c = Cluster(head_num_cpus=2)
    scaler = None
    try:
        rt = c.rt
        provider = FakeSliceProvider(c, {
            "spot-v5e": {"resources": {"CPU": 2, "slice": 1},
                         "max_workers": 3, "spot": True},
        })
        scaler = StandardAutoscaler(rt, provider, idle_timeout_s=20.0,
                                    update_interval_s=0.4)
        scaler.start()

        # Preemption-tolerant replica: restart + in-flight replay (the
        # elastic ray_actor_options plumb) — a preempted replica is a
        # latency blip, not an error.
        @serve.deployment(num_replicas=1, num_cpus=0.5,
                          ray_actor_options={"max_restarts": -1,
                                             "max_task_retries": -1,
                                             "resources": {"slice": 0.25}})
        class Echo:
            def __call__(self, body):
                return {"double": body["x"] * 2}

        @ray.remote(resources={"slice": 0.25}, max_retries=6)
        def work(i):
            import numpy as np

            return np.full(200_000, i)  # node-store-homed result

        # The replica itself needs a slice: serve demand drives the
        # FIRST node launch through the autoscaler (no manual add).
        handle = serve.run(Echo.bind())
        with ChaosController(rt) as chaos:
            lat = []
            task_refs = {}
            t_end = time.monotonic() + duration_s
            preempt_at = t_end - duration_s / 2
            preempted = False
            i = 0
            while time.monotonic() < t_end or not preempted:
                i += 1
                task_refs[i] = work.remote(i)
                t0 = time.monotonic()
                out = ray.get(handle.remote({"x": i}), timeout=90)
                lat.append(time.monotonic() - t0)
                assert out == {"double": 2 * i}
                if not preempted and time.monotonic() >= preempt_at:
                    preempted = chaos.preempt_node(
                        notice=graceful) is not None
                time.sleep(0.03)
            assert preempted, "chaos never found a node to preempt"
            # Every task get exactly correct — graceful drains migrated
            # the preempted node's results, hard kills rebuild them via
            # lineage; either way no wrong answers, no losses.
            for k, ref in task_refs.items():
                v = ray.get(ref, timeout=120)
                assert int(v[0]) == k, (k, int(v[0]))
            lat.sort()
            p99 = lat[max(0, int(len(lat) * 0.99) - 1)]
            assert p99 < p99_bound_s, f"p99 {p99:.2f}s over bound"
            assert scaler.stats()["autoscaler_errors"] == 0
            return rt.transfer_stats(), p99, len(task_refs)
    finally:
        try:
            if scaler is not None:
                scaler.stop()
            serve.shutdown()
        finally:
            c.shutdown()


def test_elastic_drill_graceful_notice():
    """Acceptance: sustained serve + task traffic, autoscaler-driven
    node adds, one graceful preemption — every get correct, zero object
    loss (reconstructions == 0), drain counter-pinned, p99 bounded."""
    st, _p99, _n = _elastic_drill(graceful=True, duration_s=4.0,
                                  p99_bound_s=30.0)
    assert st["preemptions"] >= 1
    assert st["drains_completed"] >= 1
    assert st["reconstructions"] == 0
    assert st["chaos_kills"] >= 1


def test_elastic_drill_no_notice():
    """Acceptance, hard half: the same drill with a no-warning SIGKILL
    — gets stay correct via lineage, reconstructions bounded by the
    task count, no drain counters move."""
    st, _p99, n_tasks = _elastic_drill(graceful=False, duration_s=4.0,
                                       p99_bound_s=30.0)
    assert st["chaos_kills"] >= 1
    assert st["drains_completed"] == 0 and st["preemptions"] == 0
    # Bounded: only the killed node's unconsumed results rebuild (each
    # at most once more per retry budget — in practice once).
    assert st["reconstructions"] <= 2 * n_tasks


@pytest.mark.slow
def test_elastic_drill_sustained():
    """The long variant: more traffic, the same invariants, and the
    spot accounting visible after the churn."""
    st, p99, _n = _elastic_drill(graceful=True, duration_s=10.0,
                                 p99_bound_s=30.0)
    assert st["preemptions"] >= 1
    assert st["drains_completed"] >= 1
    assert st["reconstructions"] == 0
    assert p99 < 30.0
