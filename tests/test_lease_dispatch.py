"""Decentralized dispatch: bulk lease grants, spillback, revocation,
renewal, and the head-off-the-submit-path acceptance criterion
(reference: raylet lease-based hybrid scheduling + spillback,
local_task_manager.h:58; ownership of task metadata at the submitting
worker — Ownership, NSDI'21)."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

import ray_tpu as ray
from ray_tpu._private import api_internal

NEW_COUNTERS = ("lease_grants", "leased_submits", "spillbacks",
                "lease_revocations", "head_brokered_submits")


def _settled_stats(rt, timeout=6.0):
    """transfer_stats once the periodic worker deltas stop changing."""
    stats = rt.transfer_stats()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        time.sleep(0.35)
        nxt = rt.transfer_stats()
        if nxt == stats:
            return nxt
        stats = nxt
    return stats


def _wait_counter(rt, key, min_val, timeout=8.0):
    """Poll until a transfer_stats counter reaches min_val (worker
    deltas ride the 0.25s flusher)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        stats = rt.transfer_stats()
        if stats[key] >= min_val:
            return stats
        time.sleep(0.1)
    return rt.transfer_stats()


@ray.remote
def _noop():
    return None


@ray.remote
def _nap(t):
    time.sleep(t)
    return os.getpid()


@ray.remote
class _Client:
    def burst(self, n):
        import ray_tpu as ray

        return len(ray.get([_noop.remote() for _ in range(n)]))

    def slow_burst(self, n, t):
        import ray_tpu as ray

        return len(set(ray.get([_nap.remote(t) for _ in range(n)])))

    def lease_slots_seen(self, n):
        """Run a burst, then report the slot caps and peak inflight of
        the leases THIS process held (the holder-side view of the
        max_tasks_in_flight_per_worker cap)."""
        import ray_tpu as ray
        from ray_tpu._private.worker_main import get_worker_runtime

        rt = get_worker_runtime()
        peaks = {}

        def sample():
            while not done[0]:
                with rt.direct.lock:
                    for pool in rt.direct.pools.values():
                        for lease in pool["leases"]:
                            key = id(lease)
                            peaks[key] = (
                                lease.slots,
                                max(peaks.get(key, (0, 0))[1],
                                    len(lease.inflight)))
                time.sleep(0.002)

        done = [False]
        t = threading.Thread(target=sample, daemon=True)
        t.start()
        ray.get([_nap.remote(0.02) for _ in range(n)])
        done[0] = True
        t.join(timeout=5)
        return list(peaks.values())


def test_acceptance_head_brokered_stays_flat_under_fanin():
    """The acceptance criterion: a 500-task multi-client fan-in rides
    the lease plane — leased_submits carries the traffic while
    head_brokered_submits stays ~flat (bounded by lease-grant/renewal
    and starvation events, NOT task count)."""
    ray.init(num_cpus=16)
    rt = api_internal.get_runtime()
    try:
        clients = [_Client.remote() for _ in range(4)]
        # Warm-up: workers spawn, first leases get granted.
        assert ray.get([c.burst.remote(5) for c in clients]) == [5] * 4
        s0 = _settled_stats(rt)
        assert ray.get([c.burst.remote(125) for c in clients]) == [125] * 4
        s1 = _settled_stats(rt)
        leased = s1["leased_submits"] - s0["leased_submits"]
        brokered = (s1["head_brokered_submits"]
                    - s0["head_brokered_submits"])
        # The fan-in is 500 tasks; the lease plane must carry the bulk
        # and the head must see at most a starvation-bounded trickle.
        assert leased + brokered >= 500, (leased, brokered)
        assert leased >= 400, (leased, brokered)
        assert brokered <= 100, (leased, brokered)
        assert s1["lease_grants"] >= 1
    finally:
        ray.shutdown()


def test_decentralized_off_zero_counters_and_knob_env_plumbing():
    """The off switch, in one cluster boot: (a) a multi-client fan-in
    runs entirely head-brokered with every decentralized-dispatch
    counter pinned at zero; (b) the PR-5 contract for the new knobs —
    _system_config overrides reach spawned workers through the
    RAY_TPU_* env namespace (both spawn paths share
    _worker_config_env), so a worker's GLOBAL_CONFIG agrees with the
    driver's switch."""
    ray.init(num_cpus=8, _system_config={
        "decentralized_dispatch": False,
        "lease_slots": 3,
        "lease_ttl_s": 7.5,
        "lease_renew_tasks": 17,
        "lease_spillback_depth": 9,
    })
    rt = api_internal.get_runtime()
    try:
        assert rt.config.decentralized_dispatch is False
        clients = [_Client.remote() for _ in range(3)]
        assert ray.get([c.burst.remote(40) for c in clients]) == [40] * 3
        stats = _settled_stats(rt)
        zeros = {k: stats[k] for k in NEW_COUNTERS}
        assert all(v == 0 for v in zeros.values()), zeros

        @ray.remote
        def probe():
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg

            return (cfg.decentralized_dispatch, cfg.lease_slots,
                    cfg.lease_ttl_s, cfg.lease_renew_tasks,
                    cfg.lease_spillback_depth)

        assert ray.get(probe.remote(), timeout=60) == \
            (False, 3, 7.5, 17, 9)
    finally:
        ray.shutdown()


@pytest.mark.slow  # the slots bound keeps its tier-1 representative in
                   # the renewal unit test below (stub-host, sub-second);
                   # this adds only the in-cluster sampling geometry
def test_holder_never_exceeds_granted_slots():
    """Lease pipelining vs the max_tasks_in_flight_per_worker cap: the
    head grants min(lease_slots, max_tasks_in_flight_per_worker) slots
    and the holder never pipelines past them — renewal keeps a lease
    alive, it never widens it."""
    ray.init(num_cpus=8, _system_config={"lease_slots": 64})
    rt = api_internal.get_runtime()
    try:
        cap = rt.config.max_tasks_in_flight_per_worker
        c = _Client.remote()
        seen = ray.get(c.lease_slots_seen.remote(60), timeout=120)
        assert seen, "burst never held a lease"
        for slots, peak_inflight in seen:
            assert slots <= cap, (slots, cap)
            assert peak_inflight <= slots, (peak_inflight, slots)
    finally:
        ray.shutdown()


def test_unsolicited_grant_piggybacks_on_brokered_burst():
    """A burst of direct-eligible specs arriving at the head marks the
    sender lease-starved: the head piggybacks a bulk lease_grant on the
    exchange (counted in lease_grants) so the next burst rides the
    direct plane.  Redundant-grant guard: a sender that already holds a
    lease gets no offer."""
    ray.init(num_cpus=8)
    rt = api_internal.get_runtime()
    try:
        ray.get(_noop.remote())  # spawn at least one live worker
        with rt.lock:
            lessee = next(
                w for n in rt.nodes.values()
                for w in n.all_workers.values()
                if not w.dead and w.conn is not None)
        fake_burst = [{"name": "t", "resources": {"CPU": 1.0}}
                      for _ in range(8)]
        g0 = rt.lease_grants
        rt._maybe_offer_lease(lessee, fake_burst)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and rt.lease_grants == g0:
            time.sleep(0.05)
        assert rt.lease_grants > g0
        # The lessee now holds leases: a second burst is guarded.
        deadline = time.monotonic() + 5
        held = False
        while time.monotonic() < deadline and not held:
            with rt.lock:
                held = any(w.client_lease is lessee
                           for n in rt.nodes.values()
                           for w in n.all_workers.values())
            time.sleep(0.02)
        assert held
        g1 = rt.lease_grants
        rt._maybe_offer_lease(lessee, fake_burst)
        time.sleep(0.5)
        assert rt.lease_grants == g1
    finally:
        ray.shutdown()


def test_renewal_batches_one_message_per_n_pushes(monkeypatch):
    """Holder-side renewal amortization, pinned at the unit level: a
    granted lease is renewed with ONE lease_renew message per
    lease_renew_tasks pushes (not one per task), and the holder never
    pipelines past the granted slot count."""
    import queue as queue_mod

    from ray_tpu._private import direct as direct_mod
    from ray_tpu._private import protocol, serialization
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu._private.ids import new_task_id

    monkeypatch.setattr(GLOBAL_CONFIG, "decentralized_dispatch", True)
    monkeypatch.setattr(GLOBAL_CONFIG, "lease_ttl_s", 30.0)
    monkeypatch.setattr(GLOBAL_CONFIG, "lease_renew_tasks", 4)

    sent_head = []

    class FakeConn:
        def __init__(self):
            self._q = queue_mod.SimpleQueue()

        def send_bytes(self, b):
            pass

        def recv_bytes(self):
            return self._q.get()  # parks the reader thread

        def close(self):
            pass

    class Host:
        store_id = "stub"
        shm = None

        def head_request(self, build):
            return {"grants": [("w1", ("127.0.0.1", 1), None)],
                    "slots": 2, "ttl": 30.0, "hint": None}

        def head_send(self, msg):
            sent_head.append(msg)

        def dial(self, addr):
            return FakeConn()

        def get_payload(self, fid):
            return b"payload"

        def submit_via_head(self, spec):
            sent_head.append(("submit", 0, spec))

        def submit_via_head_many(self, specs):
            sent_head.append(("submit_batch", specs))

    caller = direct_mod.DirectCaller(Host())

    def spec():
        return {"task_id": new_task_id().binary(), "num_returns": 1,
                "name": "t", "args": [], "kwargs": {}, "func_id": "f",
                "resources": {"CPU": 1.0}}

    caller.submit_many([spec() for _ in range(12)])
    deadline = time.monotonic() + 5
    lease = None
    while time.monotonic() < deadline and lease is None:
        with caller.lock:
            for pool in caller.pools.values():
                if pool["leases"]:
                    lease = pool["leases"][0]
        time.sleep(0.01)
    assert lease is not None
    assert lease.slots == 2
    descr = (protocol.INLINE, serialization.dumps_inline(None))
    pushed_total = 0
    for _ in range(24):
        with caller.lock:
            rids = list(lease.inflight)
        if not rids:
            break
        assert len(rids) <= 2, rids  # granted slots bound the pipeline
        pushed_total += len(rids)
        caller._on_result_batch(
            lease, [(rid, True, [descr], {}) for rid in rids])
    assert pushed_total >= 12

    def flat(msgs):
        for m in msgs:
            if protocol.is_batch(m):
                yield from m[1]
            else:
                yield m

    renews = [m for m in flat(sent_head) if m[0] == "lease_renew"]
    assert renews, sent_head
    assert all(m[1] == ["w1"] for m in renews)
    # One renewal per lease_renew_tasks=4 pushes (not one per task).
    assert len(renews) <= 12 // 4, renews
    caller.shutdown()


@pytest.mark.slow  # ~16s; revocation-on-node-death now has a faster
# tier-1 rep in tests/test_chaos.py (kill-agent-mid-lease interplay),
# and the renewal/TTL units above stay tier-1
def test_lease_revocation_on_node_death_mid_push():
    """A node dies while a holder is pushing onto its leased workers:
    the head revokes the leases explicitly (lease_revocations counts
    them) and every pushed spec still completes — rerouted through the
    head or re-leased elsewhere, none lost."""
    ray.init(num_cpus=1)
    rt = api_internal.get_runtime()
    try:
        node2 = rt.add_node(num_cpus=8)
        c = _Client.remote()  # takes the head's only CPU slot
        # Long enough burst that the node dies mid-stream.
        fut = c.slow_burst.remote(24, 0.04)
        deadline = time.monotonic() + 20
        leased_on_node2 = False
        while time.monotonic() < deadline and not leased_on_node2:
            with rt.lock:
                leased_on_node2 = any(
                    w.client_lease is not None and not w.dead
                    for w in rt.nodes[node2].all_workers.values())
            time.sleep(0.01)
        assert leased_on_node2, "no lease ever landed on the added node"
        rt.remove_node(node2)
        # All 24 tasks must still produce results (>=1 distinct pid).
        assert ray.get(fut, timeout=120) >= 1
        stats = _wait_counter(rt, "lease_revocations", 1)
        assert stats["lease_revocations"] >= 1, stats
    finally:
        ray.shutdown()


def test_spillback_bounces_and_work_completes():
    """An oversubscribed leased worker bounces excess pushes
    (lease_spillback_depth); the holder re-lands them (other leases /
    hint-steered requests / head fallback) and the burst completes with
    spillbacks counted."""
    ray.init(num_cpus=8, _system_config={"lease_spillback_depth": 2})
    rt = api_internal.get_runtime()
    try:
        c = _Client.remote()
        assert ray.get(c.slow_burst.remote(32, 0.05), timeout=120) >= 1
        stats = _wait_counter(rt, "spillbacks", 1)
        assert stats["spillbacks"] >= 1, stats
        assert stats["leased_submits"] >= 1, stats
    finally:
        ray.shutdown()


@pytest.mark.slow  # spillback + counters keep their tier-1
                   # representative in the single-node test above; this
                   # adds only the two-node hint-landing geometry
def test_spillback_hint_steers_next_lease_to_second_node():
    """The bounced-back hint names the next-best node and the holder's
    next lease request honors it: with the head node saturated, the
    spilled work's replacement leases land on the second node."""
    ray.init(num_cpus=4, _system_config={"lease_spillback_depth": 2,
                                         "lease_slots": 4})
    rt = api_internal.get_runtime()
    try:
        node2 = rt.add_node(num_cpus=8)
        c = _Client.remote()
        fut = c.slow_burst.remote(48, 0.05)
        # Sample DURING the burst for a CLIENT lease on node2: the
        # head-fallback reroute after SPILL_MAX bounces places ordinary
        # head-dispatch leases (client_lease is None), so only the
        # hint-steered lease_req can produce this observation.
        leased_on_node2 = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not leased_on_node2:
            with rt.lock:
                leased_on_node2 = any(
                    w.client_lease is not None and not w.dead
                    for w in rt.nodes[node2].all_workers.values())
            time.sleep(0.01)
        assert ray.get(fut, timeout=120) >= 1
        stats = _settled_stats(rt)
        if stats["spillbacks"] < 1:
            pytest.skip("burst drained without oversubscription "
                        "(load-dependent); spillback covered above")
        # Replacement CLIENT leases were drawn from the hinted node.
        assert leased_on_node2, stats
    finally:
        ray.shutdown()


def test_lockcheck_battery_over_lease_plane():
    """The fan-in + spillback + revocation battery re-run under
    RAY_TPU_LOCKCHECK=1: zero lock-order cycles across the dispatcher
    thread, the dirty-shard marking, lease granting and the holder-side
    pools."""
    code = textwrap.dedent("""
        import time
        import ray_tpu as ray
        from ray_tpu.devtools import lockcheck
        from ray_tpu._private import api_internal

        assert lockcheck.enabled()
        ray.init(num_cpus=8,
                 _system_config={"lease_spillback_depth": 2})
        rt = api_internal.get_runtime()

        @ray.remote
        def nap(t):
            time.sleep(t)
            return None

        @ray.remote
        class Client:
            def burst(self, n, t):
                import ray_tpu as ray
                return len(ray.get([nap.remote(t) for _ in range(n)]))

        clients = [Client.remote() for _ in range(3)]
        assert ray.get([c.burst.remote(30, 0.01) for c in clients]) \\
            == [30, 30, 30]
        # Revocation path: kill a leased worker mid-burst.
        fut = clients[0].burst.remote(30, 0.05)
        deadline = time.monotonic() + 15
        victim = None
        while victim is None and time.monotonic() < deadline:
            with rt.lock:
                for node in rt.nodes.values():
                    for w in node.all_workers.values():
                        if w.client_lease is not None and not w.dead \\
                                and w.proc is not None:
                            victim = w
                            break
                    if victim:
                        break
            time.sleep(0.01)
        if victim is not None:
            victim.proc.terminate()
        assert ray.get(fut, timeout=120) == 30
        dirty_site = rt._dispatch_dirty_lock._site
        ray.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        # Per-shard dirty lock is a LEAF: nothing is acquired under it
        # (the dispatcher event is set OUTSIDE it by design).
        edges = lockcheck.edges()
        assert edges.get(dirty_site, set()) == set(), edges.get(dirty_site)
        print("LEASE_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "LEASE_LOCKCHECK_OK" in proc.stdout
