"""Tracing/timeline (reference: `ray timeline` scripts.py:1840 + task
events; handler latency stats per src/ray/common/event_stats.h)."""
import json
import time

import pytest

import ray_tpu as ray
from ray_tpu.util.tracing import chrome_trace, get_task_spans, handler_stats


@pytest.fixture
def init2():
    ray.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray.shutdown()


def test_timeline_captures_task_and_actor_spans(init2, tmp_path):
    @ray.remote
    def work(i):
        time.sleep(0.002)
        return i

    @ray.remote
    class A:
        def m(self):
            time.sleep(0.002)
            return 1

    a = A.remote()
    ray.get([work.remote(i) for i in range(40)])
    ray.get([a.m.remote() for _ in range(10)])
    # Spans flush on worker queue drain; give the periodic flusher a beat.
    deadline = time.time() + 5
    while time.time() < deadline:
        spans = get_task_spans()
        names = [s["name"] for s in spans]
        if names.count("work") >= 40 and names.count("actor.m") >= 10:
            break
        time.sleep(0.3)
    assert names.count("work") >= 40, names[:5]
    assert names.count("actor.m") >= 10
    for s in spans:
        assert s["end"] >= s["start"]
        assert s["worker_id"]

    out = ray.timeline(str(tmp_path / "trace.json"))
    events = json.load(open(out))
    xs = [e for e in events if e.get("ph") == "X"]
    assert len(xs) >= 50
    assert all({"name", "ts", "dur", "pid", "tid"} <= set(e) for e in xs)
    # Perfetto lane metadata present.
    assert any(e.get("ph") == "M" for e in events)


def test_handler_stats_expose_head_latency(init2):
    @ray.remote
    def f():
        return None

    ray.get([f.remote() for _ in range(20)])
    stats = handler_stats()
    tags = {s["handler"] for s in stats}
    assert tags, stats
    for s in stats:
        assert s["count"] > 0 and s["mean_us"] >= 0


def test_spans_visible_from_worker(init2):
    @ray.remote
    def f():
        return None

    @ray.remote
    def probe():
        from ray_tpu.util.tracing import get_task_spans
        return len(get_task_spans())

    ray.get([f.remote() for _ in range(10)])
    time.sleep(0.6)
    assert ray.get(probe.remote()) >= 1
