"""Push-based shuffle battery (``ray_tpu/data/shuffle.py`` +
``streaming_executor.ShuffleOperator``).

Covered here:
- push-vs-legacy byte-identical results for sort (asc/desc),
  random_shuffle, groupby aggregate and map_groups, including runs
  randomized around ``shuffle_partition_bytes_target`` (reducer counts
  decoupled from the block count);
- merge-on-arrival ordering pins: tie-heavy sorts with a tiny
  ``shuffle_merge_fanin`` (intermediate merges forced, arrival order
  exercised), the exact legacy random permutation reproduced block by
  block, group rows emitted in None-safe key order;
- None-key sorts complete on both engines with Nones ordered last
  (first when descending) — the ``(x is None, x)`` convention;
- off-switch pin: ``push_shuffle=off`` reproduces the legacy path
  byte-identically, every new counter zero, and the shuffle module is
  never even imported;
- knob env-plumbing probe: the three shuffle knobs follow
  ``_system_config`` into spawned workers;
- the battery shape re-run under ``RAY_TPU_LOCKCHECK=1`` with zero
  lock-order cycles;
- slow lane: the kill-one-node-AND-stall-another chaos drill
  (reconstructions >= 1, shuffle_hedges >= 1, zero ObjectLostError)
  and the paced-link perf A/B (push >= 2x legacy GB/s with the head
  control-plane counters flat).
"""

import os
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import data as rd

SHUFFLE_COUNTERS = ("shuffle_pushed_bytes", "shuffle_merges",
                    "shuffle_spills", "shuffle_hedges")

# Tiny failure-detection windows for the chaos drill (the
# test_netchaos.py convention).
FAST_FD = {
    "net_stall_timeout_s": 0.8,
    "net_connect_timeout_s": 2.0,
    "net_retry_count": 1,
    "net_retry_backoff_base_ms": 20.0,
    "health_check_period_s": 0.25,
    "health_check_timeout_s": 1.0,
    "health_check_failure_threshold": 2,
    "health_check_initial_delay_s": 1.0,
}


def _rows(n, seed):
    """Distinct float sort keys (no ties -> strict byte identity),
    integer-exact aggregation values."""
    rng = np.random.default_rng(seed)
    return [{"k": float(v), "g": i % 13, "v": i}
            for i, v in enumerate(rng.random(n))]


def _battery(ds):
    return {
        "sort_asc": ds.sort(key="k").take_all(),
        "sort_desc": ds.sort(key="k", descending=True).take_all(),
        "random": ds.random_shuffle(seed=3).take_all(),
        "agg": ds.groupby("g").aggregate(
            rd.Sum("v"), rd.Count(), rd.Min("v"), rd.Max("v")).take_all(),
        "map_groups": ds.groupby("g").map_groups(
            lambda rs: [{"g": rs[0]["g"],
                         "vs": [r["v"] for r in rs]}]).take_all(),
    }


def _run_battery(system_config, rows, parallelism=5):
    rt = ray.init(num_cpus=4, _system_config=system_config)
    try:
        res = _battery(rd.from_items(rows, parallelism=parallelism))
        stats = {k: v for k, v in rt.transfer_stats().items()
                 if k in SHUFFLE_COUNTERS}
        return res, stats
    finally:
        ray.shutdown()


# ------------------------------------------------ byte-identity pins ----

def test_push_vs_legacy_byte_identical():
    """The exact-equality contract: with push_shuffle on, every shuffle
    mode reproduces the legacy output bit-for-bit — same rows, same
    order, same block boundaries (R = n when no bytes target is set)."""
    rows = _rows(300, seed=0)
    on, on_stats = _run_battery({}, rows)
    off, off_stats = _run_battery({"push_shuffle": False}, rows)
    for mode in on:
        assert on[mode] == off[mode], mode
    assert on_stats["shuffle_pushed_bytes"] > 0, on_stats
    assert on_stats["shuffle_merges"] > 0, on_stats
    # Off-switch pin: every new counter zero.
    assert all(v == 0 for v in off_stats.values()), off_stats


def test_partition_bytes_target_randomized():
    """Randomized ``shuffle_partition_bytes_target`` decouples R from
    the block count; the flattened sort output and the combined group
    rows stay identical to legacy at EVERY target (global order does
    not depend on where block boundaries fall)."""
    rows = _rows(400, seed=1)
    legacy, _ = _run_battery({"push_shuffle": False}, rows)
    rng = np.random.default_rng(7)
    # ~30 KB of pickled rows: one target per regime — tiny (clamped to
    # 4x the block count), mid (a few reducers), huge (R=1) — each
    # jittered so block-boundary placement is genuinely randomized.
    targets = [int(rng.integers(300, 900)),
               int(rng.integers(4_000, 9_000)),
               int(rng.integers(40_000, 90_000))]
    seen_r = set()
    for tgt in targets:
        rt = ray.init(num_cpus=4, _system_config={
            "shuffle_partition_bytes_target": tgt})
        try:
            ds = rd.from_items(rows, parallelism=5)
            out = ds.sort(key="k")
            got = out.take_all()
            assert got == legacy["sort_asc"], tgt
            assert out._stats is not None and out._stats.shuffle
            seen_r.add(out._stats.shuffle["reducers"])
            # Group rows land on different reducers at different R, but
            # the combined (key-ordered) result set is invariant.
            agg = ds.groupby("g").aggregate(
                rd.Sum("v"), rd.Count(), rd.Min("v"), rd.Max("v")
            ).take_all()
            assert sorted(agg, key=lambda r: r["g"]) == \
                sorted(legacy["agg"], key=lambda r: r["g"]), tgt
        finally:
            ray.shutdown()
    # The randomized targets really exercised different reducer counts.
    assert len(seen_r) >= 2, (targets, seen_r)


def test_sort_none_keys_both_engines():
    """Satellite pin: None sort keys no longer TypeError — they order
    after every real key (before, when descending), identically on the
    push and legacy engines."""
    rows = _rows(120, seed=2)
    for i in range(0, 120, 10):
        rows[i] = dict(rows[i], k=None)
    outs = {}
    for name, cfg in (("push", {}), ("legacy", {"push_shuffle": False})):
        ray.init(num_cpus=4, _system_config=cfg)
        try:
            ds = rd.from_items(rows, parallelism=4)
            outs[name] = (ds.sort(key="k").take_all(),
                          ds.sort(key="k", descending=True).take_all())
        finally:
            ray.shutdown()
    assert outs["push"] == outs["legacy"]
    asc, desc = outs["push"]
    assert [r["k"] for r in asc[-12:]] == [None] * 12
    assert [r["k"] for r in desc[:12]] == [None] * 12
    real = [r["k"] for r in asc if r["k"] is not None]
    assert real == sorted(real)


# ------------------------------------------- merge-on-arrival pins ----

def test_merge_on_arrival_sort_ordering_tie_heavy():
    """Tie-heavy keys + fanin=2 (intermediate merges forced while later
    maps are still arriving): the output must equal a STABLE sort of
    the map-order concatenation — equal keys keep block order — for
    both directions.  This is the strict-merge-key guarantee: arrival
    order cannot perturb the result."""
    sizes = [7, 61, 3, 40, 19]  # uneven blocks: maps finish out of order
    rows, blocks = [], []
    v = 0
    for s in sizes:
        blk = [{"k": v % 5, "v": (v := v + 1)} for _ in range(s)]
        blocks.append(blk)
        rows.extend(blk)
    ray.init(num_cpus=4, _system_config={"shuffle_merge_fanin": 2})
    try:
        ds = rd.from_items(rows, parallelism=len(sizes))
        asc = ds.sort(key="k")
        got_asc = asc.take_all()
        got_desc = ds.sort(key="k", descending=True).take_all()
        assert got_asc == sorted(rows, key=lambda r: r["k"])
        assert got_desc == sorted(rows, key=lambda r: r["k"],
                                  reverse=True)
        # fanin=2 really forced intermediate merges on arrival (not
        # just the one finalize merge per reducer).
        assert asc._stats.shuffle["shuffle_merges"] >= 1, \
            asc._stats.shuffle
    finally:
        ray.shutdown()


def test_random_shuffle_reproduces_exact_legacy_permutation():
    """The push engine must land EXACTLY the legacy permutation: per
    reducer j, the rows map i's RNG(seed+i) assigned to j, concatenated
    in map order, then shuffled by RNG(seed+1000+j) — computed here
    from first principles, not by running the legacy engine."""
    seed, n = 11, 4
    rows = [{"v": i} for i in range(200)]
    per_block = [rows[i * 50:(i + 1) * 50] for i in range(n)]
    expected = []
    assignments = [np.random.default_rng(seed + i).integers(
        0, n, size=50) for i in range(n)]
    for j in range(n):
        part = [r for i in range(n)
                for r, a in zip(per_block[i], assignments[i]) if a == j]
        np.random.default_rng(seed + 1000 + j).shuffle(part)
        expected.extend(part)
    ray.init(num_cpus=4)
    try:
        got = rd.from_items(rows, parallelism=n).random_shuffle(
            seed=seed).take_all()
        assert got == expected
    finally:
        ray.shutdown()


def test_groupby_rows_emitted_in_key_order_per_block():
    """Each output block's group rows are emitted in None-safe key
    order and every group appears exactly once across blocks."""
    rows = [{"g": i % 9, "v": i} for i in range(180)]
    ray.init(num_cpus=4)
    try:
        out = rd.from_items(rows, parallelism=4).groupby("g") \
            .aggregate(rd.Sum("v"), rd.Count())
        blocks = [list(b) for b in
                  (ray.get(r) for r in out._executed_refs())]
        seen = []
        for blk in blocks:
            keys = [r["g"] for r in blk]
            assert keys == sorted(keys), keys
            seen.extend(keys)
        assert sorted(seen) == list(range(9))
        for r in (row for blk in blocks for row in blk):
            g = r["g"]
            assert r["sum(v)"] == sum(v for v in range(180) if v % 9 == g)
            assert r["count()"] == 20
    finally:
        ray.shutdown()


# ------------------------------------------------ switches and knobs ----

def test_push_shuffle_off_never_imports_shuffle_module():
    """Off-switch hygiene in a fresh process: the legacy path runs
    without ever importing ray_tpu.data.shuffle (so its counters cannot
    even exist to drift) and transfer_stats reports all-zero shuffle
    counters sourced from the head's own fields."""
    code = textwrap.dedent("""
        import sys
        import ray_tpu as ray
        from ray_tpu import data as rd

        rt = ray.init(num_cpus=4, _system_config={"push_shuffle": False})
        ds = rd.from_items([{"k": i % 7, "v": i} for i in range(60)],
                           parallelism=3)
        assert [r["k"] for r in ds.sort(key="k").take_all()] == \\
            sorted(i % 7 for i in range(60))
        ds.random_shuffle(seed=1).take_all()
        stats = rt.transfer_stats()
        for k in ("shuffle_pushed_bytes", "shuffle_merges",
                  "shuffle_spills", "shuffle_hedges"):
            assert stats[k] == 0, (k, stats[k])
        assert "ray_tpu.data.shuffle" not in sys.modules
        st = ds.sort(key="k").materialize()
        assert "Push shuffle" not in st.stats()
        ray.shutdown()
        print("OFF_SWITCH_OK")
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("RAY_TPU_PUSH_SHUFFLE", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=180,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-4000:])
    assert "OFF_SWITCH_OK" in proc.stdout


def test_shuffle_knobs_follow_system_config_into_workers():
    """The three knobs ride _system_config -> _worker_config_env -> the
    worker environment (the RTL504-enforced contract)."""
    ray.init(num_cpus=2, _system_config={
        "push_shuffle": False,
        "shuffle_partition_bytes_target": 123456,
        "shuffle_merge_fanin": 5,
    })
    try:
        @ray.remote
        def probe():
            import os

            return (os.environ.get("RAY_TPU_PUSH_SHUFFLE"),
                    os.environ.get(
                        "RAY_TPU_SHUFFLE_PARTITION_BYTES_TARGET"),
                    os.environ.get("RAY_TPU_SHUFFLE_MERGE_FANIN"))

        assert ray.get(probe.remote(), timeout=60) == \
            ("0", "123456", "5")
    finally:
        ray.shutdown()


def test_stats_surface_shuffle_summary():
    """Dataset.stats() grows the push-shuffle line; shuffle_summary()
    mirrors transfer_stats keys and reads all-zero on the legacy path."""
    from ray_tpu.data.execution import DatasetStats

    ray.init(num_cpus=4)
    try:
        ds = rd.from_items(_rows(80, seed=4), parallelism=4)
        out = ds.sort(key="k").materialize()
        assert "Push shuffle:" in out.stats()
        s = out._stats.shuffle_summary()
        assert s["reducers"] == 4 and s["maps"] == 4
        assert s["shuffle_pushed_bytes"] > 0
    finally:
        ray.shutdown()
    empty = DatasetStats().shuffle_summary()
    assert set(empty) == {"maps", "reducers", "shuffle_pushed_bytes",
                          "shuffle_merges", "shuffle_spills",
                          "shuffle_hedges"}
    assert all(v == 0 for v in empty.values())


# ------------------------------------------------- lockcheck battery ----

def test_shuffle_battery_lockcheck_clean():
    """The battery shape under RAY_TPU_LOCKCHECK=1 (head + workers all
    instrumented): zero lock-order cycles recorded in the driver."""
    code = textwrap.dedent("""
        import numpy as np
        import ray_tpu as ray
        from ray_tpu import data as rd
        from ray_tpu.devtools import lockcheck

        ray.init(num_cpus=4, _system_config={"shuffle_merge_fanin": 2})
        rng = np.random.default_rng(0)
        rows = [{"k": float(v), "g": i % 7, "v": i}
                for i, v in enumerate(rng.random(150))]
        ds = rd.from_items(rows, parallelism=5)
        assert [r["k"] for r in ds.sort(key="k").take_all()] == \\
            sorted(r["k"] for r in rows)
        ds.random_shuffle(seed=2).take_all()
        ds.groupby("g").aggregate(rd.Sum("v")).take_all()
        ray.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        lockcheck.assert_acyclic()
        print("SHUFFLE_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-4000:])
    assert "SHUFFLE_LOCKCHECK_OK" in proc.stdout


# ------------------------------------------------------- slow lane ----

@pytest.mark.slow
def test_shuffle_chaos_drill_kill_one_node_stall_another():
    """THE shuffle chaos acceptance: 3-agent cluster, input blocks homed
    on the doomed nodes, then — the moment the map wave is submitted —
    one node's agent is KILLED and another's head link goes gray
    (ChaosNet stall, nothing EOFs).  The shuffle must complete with
    correct, fully-sorted results: lost input blocks reconstruct
    through lineage (reconstructions >= 1), unreachable reducer stores
    force map-side hedges and/or reducer rebuilds (shuffle_hedges >= 1),
    and no ObjectLostError ever reaches the consumer."""
    from ray_tpu.chaos import ChaosController
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.dataset import Dataset
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    @ray.remote(max_retries=3)
    def mk_block(i):
        # > max_inline_object_size per block, so blocks are shm-homed
        # on their producer node (the kill genuinely loses them) rather
        # than riding the task result inline through the head.
        rng = np.random.default_rng(1000 + i)
        return [{"k": float(v), "p": bytes(6000)}
                for v in rng.random(300)]

    c = Cluster(head_num_cpus=2, _system_config=dict(FAST_FD))
    chaos = None
    try:
        n1 = c.add_node(num_cpus=2, external=True)
        n2 = c.add_node(num_cpus=2, external=True)
        n3 = c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt)

        # Producers soft-pinned to the two doomed nodes: the kill takes
        # input blocks with it, so re-run maps MUST reconstruct them.
        homes = [n1, n2, n1, n2, n3, n1]
        blocks = [mk_block.options(scheduling_strategy=NA(
            node_id=homes[i], soft=True)).remote(i)
            for i in range(len(homes))]
        ray.wait(blocks, num_returns=len(blocks), timeout=60)

        fired = []

        def wreck():
            fired.append(chaos.kill_agent(n1))
            fired.append(chaos.stall_link(n2))

        chaos.at_syncpoint("shuffle:maps_submitted", wreck, n=1)

        out = Dataset(blocks).sort(key="k")
        rows = out.take_all()  # any ObjectLostError would surface here

        expected = sorted(
            float(v) for i in range(len(homes))
            for v in np.random.default_rng(1000 + i).random(300))
        assert [r["k"] for r in rows] == expected
        assert len(fired) == 2 and fired[0] == n1 and fired[1] == n2, \
            fired
        stats = c.rt.transfer_stats()
        assert stats["reconstructions"] >= 1, stats
        assert stats["shuffle_hedges"] >= 1, stats
    finally:
        if chaos is not None:
            chaos.stop()
        c.shutdown()


@pytest.mark.slow
def test_shuffle_perf_paced_link_2x():
    """Acceptance micro (the bench.py shuffle_gbps row's shape): with
    the pull-serve plane paced (the per-node object server every legacy
    partition byte queues behind — and that push bypasses by writing
    partitions straight into the consumer store), the push-based sort
    moves >= 2x the legacy GB/s, with ZERO partition payload through
    the head — head_brokered_submits and brokered_put_parts flat in
    both modes."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    row = bench.shuffle_bench(rounds=1)
    for mode in ("sort_push", "sort_legacy"):
        assert row[mode]["completed"], row
        assert row[mode]["head_brokered_submits"] == 0, row
        assert row[mode]["brokered_put_parts"] == 0, row
    assert row["sort_push"]["gbps"] >= 2 * row["sort_legacy"]["gbps"], row
