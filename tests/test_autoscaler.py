"""Autoscaler tests against the in-process slice provider.

Reference pattern: ``python/ray/tests/test_autoscaler_fake_multinode.py``
— scale-up from queued infeasible demand and idle scale-down run with no
cloud, against FakeMultiNodeProvider (node_provider.py:237); here each
launched node is a REAL node_agent subprocess.
"""

import time

import pytest

import ray_tpu as ray
from ray_tpu.autoscaler import FakeSliceProvider, StandardAutoscaler
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(head_num_cpus=2)
    yield c
    c.shutdown()


def test_scale_up_for_infeasible_tpu_tasks_and_scale_down(cluster):
    provider = FakeSliceProvider(cluster, {
        "v5e-4": {"resources": {"CPU": 4, "TPU": 4}, "max_workers": 2},
    })
    scaler = StandardAutoscaler(cluster.rt, provider, idle_timeout_s=3.0)

    @ray.remote(num_tpus=4)
    def on_slice():
        import os

        return os.environ.get("TPU_VISIBLE_CHIPS", "")

    # Infeasible now: the head has no TPU resource at all.
    refs = [on_slice.remote() for _ in range(2)]
    time.sleep(0.2)
    report = scaler.update()
    # slice-atomic: both 4-chip tasks fit one v5e-4 node sequentially, but
    # the packer sees 2 concurrent shapes of TPU:4 -> 2 slices (cap 2)
    assert len(report["launched"]) == 2, report
    chips = ray.get(refs, timeout=120)
    assert all(c == "0,1,2,3" for c in chips)

    # idle: after the timeout both slices terminate (never the head)
    deadline = time.monotonic() + 30
    gone = []
    while time.monotonic() < deadline:
        gone += scaler.update()["terminated"]
        if len(gone) == 2:
            break
        time.sleep(0.5)
    assert len(gone) == 2, f"idle slices not terminated: {gone}"
    alive = [n for n in cluster.rt.list_nodes() if n["alive"]]
    assert len(alive) == 1  # the head


def test_no_scale_up_when_demand_fits(cluster):
    provider = FakeSliceProvider(cluster, {
        "cpu-2": {"resources": {"CPU": 2}, "max_workers": 4},
    })
    scaler = StandardAutoscaler(cluster.rt, provider)

    @ray.remote
    def f():
        return 1

    # Head has 2 CPUs: a couple of 1-CPU tasks fit; no launch.
    refs = [f.remote() for _ in range(2)]
    report = scaler.update()
    assert report["launched"] == []
    assert ray.get(refs, timeout=60) == [1, 1]


def test_launch_capped_by_max_workers(cluster):
    provider = FakeSliceProvider(cluster, {
        "cpu-1": {"resources": {"CPU": 1}, "max_workers": 1},
    })
    scaler = StandardAutoscaler(cluster.rt, provider)

    @ray.remote(resources={"special": 1})
    def g():
        return "ok"

    # "special" exists nowhere and on no node type: never launches.
    ref = g.remote()
    report = scaler.update()
    assert report["launched"] == []
    ray.cancel(ref)
