"""Autoscaler tests against the in-process slice provider.

Reference pattern: ``python/ray/tests/test_autoscaler_fake_multinode.py``
— scale-up from queued infeasible demand and idle scale-down run with no
cloud, against FakeMultiNodeProvider (node_provider.py:237); here each
launched node is a REAL node_agent subprocess.
"""

import time

import pytest

import ray_tpu as ray
from ray_tpu.autoscaler import FakeSliceProvider, StandardAutoscaler
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(head_num_cpus=2)
    yield c
    c.shutdown()


def test_scale_up_for_infeasible_tpu_tasks_and_scale_down(cluster):
    provider = FakeSliceProvider(cluster, {
        "v5e-4": {"resources": {"CPU": 4, "TPU": 4}, "max_workers": 2},
    })
    scaler = StandardAutoscaler(cluster.rt, provider, idle_timeout_s=3.0)

    @ray.remote(num_tpus=4)
    def on_slice():
        import os

        return os.environ.get("TPU_VISIBLE_CHIPS", "")

    # Infeasible now: the head has no TPU resource at all.
    refs = [on_slice.remote() for _ in range(2)]
    time.sleep(0.2)
    report = scaler.update()
    # slice-atomic: both 4-chip tasks fit one v5e-4 node sequentially, but
    # the packer sees 2 concurrent shapes of TPU:4 -> 2 slices (cap 2)
    assert len(report["launched"]) == 2, report
    chips = ray.get(refs, timeout=120)
    assert all(c == "0,1,2,3" for c in chips)

    # idle: after the timeout both slices terminate (never the head)
    deadline = time.monotonic() + 30
    gone = []
    while time.monotonic() < deadline:
        gone += scaler.update()["terminated"]
        if len(gone) == 2:
            break
        time.sleep(0.5)
    assert len(gone) == 2, f"idle slices not terminated: {gone}"
    # Scale-down drains off-thread (elastic pods): the agents exit at
    # the drain's conclusion, moments after the report.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [n for n in cluster.rt.list_nodes() if n["alive"]]
        if len(alive) == 1:  # the head
            break
        time.sleep(0.2)
    assert len(alive) == 1, alive


def test_no_scale_up_when_demand_fits(cluster):
    provider = FakeSliceProvider(cluster, {
        "cpu-2": {"resources": {"CPU": 2}, "max_workers": 4},
    })
    scaler = StandardAutoscaler(cluster.rt, provider)

    @ray.remote
    def f():
        return 1

    # Head has 2 CPUs: a couple of 1-CPU tasks fit; no launch.
    refs = [f.remote() for _ in range(2)]
    report = scaler.update()
    assert report["launched"] == []
    assert ray.get(refs, timeout=60) == [1, 1]


def test_launch_capped_by_max_workers(cluster):
    provider = FakeSliceProvider(cluster, {
        "cpu-1": {"resources": {"CPU": 1}, "max_workers": 1},
    })
    scaler = StandardAutoscaler(cluster.rt, provider)

    @ray.remote(resources={"special": 1})
    def g():
        return "ok"

    # "special" exists nowhere and on no node type: never launches.
    ref = g.remote()
    report = scaler.update()
    assert report["launched"] == []
    ray.cancel(ref)


class _StubRuntime:
    """Just the surface StandardAutoscaler programs against, with
    scripted demand/activity — the pending-launch and spot-fallback
    logic needs no real agents."""

    def __init__(self):
        self.demand = []
        self.nodes = [{"node_id": "head", "alive": True, "is_head": True,
                       "busy": False, "draining": False,
                       "resources": {"CPU": 1}, "available": {"CPU": 1}}]

    def pending_resource_demand(self):
        return [dict(s) for s in self.demand]

    def node_activity(self):
        return [dict(n) for n in self.nodes]

    def add_alive(self, nid, resources):
        self.nodes.append({"node_id": nid, "alive": True, "is_head": False,
                           "busy": False, "draining": False,
                           "resources": dict(resources),
                           "available": dict(resources)})

    def kill(self, nid):
        self.nodes = [n for n in self.nodes if n["node_id"] != nid]


class _StubProvider:
    """Provider whose nodes never register on their own: launches stay
    pending until the test 'boots' them against the stub runtime."""

    def __init__(self, node_types):
        self.node_types = node_types
        self._seq = 0
        self._nodes = {}
        self.created = []

    def create_node(self, node_type):
        self._seq += 1
        nid = f"{node_type}-{self._seq}"
        self._nodes[nid] = node_type
        self.created.append(nid)
        return nid

    def terminate_node(self, node_id):
        self._nodes.pop(node_id, None)

    def non_terminated_nodes(self):
        return list(self._nodes)

    def node_type_of(self, node_id):
        return self._nodes.get(node_id)

    def node_resources(self, t):
        return dict(self.node_types[t]["resources"])

    def max_workers(self, t):
        return int(self.node_types[t].get("max_workers", 10))

    def is_spot(self, t):
        return bool(self.node_types[t].get("spot", False))


def test_pending_launch_timeout_reissues_without_double_count():
    """A launch that never registers is re-issued after
    _launch_timeout_s — and while pending it counts against caps and
    capacity, so the same demand is never double-launched meanwhile."""
    rt = _StubRuntime()
    provider = _StubProvider({
        "cpu-2": {"resources": {"CPU": 2}, "max_workers": 1},
    })
    scaler = StandardAutoscaler(rt, provider)
    scaler._launch_timeout_s = 0.3
    rt.demand = [{"CPU": 2}]
    report = scaler.update()
    assert len(report["launched"]) == 1
    # Pending (not yet registered, not yet timed out): the launch holds
    # the demand AND the max_workers=1 cap — no second node.
    assert scaler.update()["launched"] == []
    assert scaler.update()["launched"] == []
    assert len(provider.created) == 1
    time.sleep(0.35)
    # Timed out: the phantom stops counting and the demand is re-planned
    # — exactly one replacement launch (the cap still binds).
    report = scaler.update()
    assert len(report["launched"]) == 1
    assert len(provider.created) == 2
    # The replacement is itself pending now: still no third.
    assert scaler.update()["launched"] == []


def test_spot_preferred_then_fallback_after_preemptions():
    """Spot node types win ties while healthy; after
    spot_fallback_threshold observed preemptions of the type the
    planner launches the on-demand peer instead (per-type
    accounting)."""
    rt = _StubRuntime()
    provider = _StubProvider({
        # dict order puts spot first anyway — the ranking, not luck, is
        # what the fallback half of the test pins.
        "ondemand-2": {"resources": {"CPU": 2}, "max_workers": 8},
        "spot-2": {"resources": {"CPU": 2}, "max_workers": 8,
                   "spot": True},
    })
    scaler = StandardAutoscaler(rt, provider, spot_fallback_threshold=2)
    for round_no in range(2):
        rt.demand = [{"CPU": 2}]
        (nid,) = scaler.update()["launched"]
        assert nid.startswith("spot-2"), (round_no, nid)
        # Register it, then yank it without terminate: a preemption.
        rt.add_alive(nid, {"CPU": 2})
        rt.demand = []
        scaler.update()  # sees it alive; pending clears
        rt.kill(nid)
        scaler.update()  # sees it gone: counted + cleaned up
    assert scaler.stats()["preemptions_by_type"] == {"spot-2": 2}
    # Threshold reached: same demand now lands on-demand.
    rt.demand = [{"CPU": 2}]
    (nid,) = scaler.update()["launched"]
    assert nid.startswith("ondemand-2"), nid


def test_monitor_loop_counts_errors_instead_of_swallowing():
    """The background loop's failure path: errors are counted and
    rate-limit-logged (autoscaler_errors), never silently dropped, and
    the loop survives to keep reconciling."""
    rt = _StubRuntime()

    class _BrokenProvider(_StubProvider):
        def non_terminated_nodes(self):
            raise RuntimeError("cloud API down")

    scaler = StandardAutoscaler(
        rt, _BrokenProvider({"cpu-2": {"resources": {"CPU": 2}}}),
        update_interval_s=0.05)
    rt.demand = [{"CPU": 2}]
    scaler.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and scaler.stats()["autoscaler_errors"] < 2:
            time.sleep(0.05)
        # >= 2: the loop survived its own error and kept ticking.
        assert scaler.stats()["autoscaler_errors"] >= 2
    finally:
        scaler.stop()
