"""Head (GCS-analog) persistence + restart.

Reference: GCS table persistence (redis_store_client.h:28) and the
GcsInitData load-on-restart path (gcs_server.h:77): a restarted head
reloads KV/functions/named actors/jobs and the cluster resumes.
"""

import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

import pytest

import ray_tpu as ray


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_snapshot_restore_in_process(tmp_path):
    """Snapshot written by one runtime restores into a fresh one: KV,
    functions, and the named actor come back."""
    snap = str(tmp_path / "gcs.bin")
    rt = ray.init(num_cpus=2,
                  _system_config={"gcs_snapshot_path": snap})

    @ray.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.options(name="persistent_counter").remote(10)
    assert ray.get(c.incr.remote()) == 11
    rt.kv_put(b"mykey", b"myvalue")
    rt._snapshot_gcs()
    ray.shutdown()

    rt2 = ray.init(num_cpus=2,
                   _system_config={"gcs_snapshot_path": snap,
                                   "gcs_restore": True})
    try:
        assert rt2.kv_get(b"mykey") == b"myvalue"
        c2 = ray.get_actor("persistent_counter")
        # Fresh incarnation: state reset to creation args, identity kept.
        assert ray.get(c2.incr.remote(), timeout=30) == 11

        @ray.remote
        def task():
            return "works"

        assert ray.get(task.remote(), timeout=30) == "works"
    finally:
        ray.shutdown()


HEAD_SCRIPT = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import ray_tpu as ray

    rt = ray.init(num_cpus=2, _system_config={{
        "gcs_snapshot_path": {snap!r},
        "gcs_restore": {restore},
        "gcs_snapshot_interval_s": 0.2,
        "listen_port": {port},
        "authkey_hex": {key!r},
    }})

    @ray.remote
    class KVActor:
        def __init__(self):
            self.d = {{}}
        def put(self, k, v):
            self.d[k] = v
            return len(self.d)
        def get(self, k):
            return self.d.get(k)

    if not {restore}:
        KVActor.options(name="kv_actor").remote()
        rt.kv_put(b"epoch", b"one")
    print("HEAD_READY", flush=True)
    time.sleep(600)
""")


def _start_head(snap, port, key, restore):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    script = HEAD_SCRIPT.format(repo=REPO, snap=snap, port=port,
                                key=key, restore=restore)
    proc = subprocess.Popen([sys.executable, "-u", "-c", script],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    deadline = time.time() + 60
    line = b""
    while time.time() < deadline:
        line = proc.stdout.readline()
        if b"HEAD_READY" in line:
            return proc
        if proc.poll() is not None:
            break
    raise AssertionError(
        f"head did not start: {line!r} rc={proc.poll()}")


def test_head_kill_restart_client_reconnect(tmp_path):
    """kill -9 the head; a restarted head (same port/authkey) restores
    the snapshot; a client re-attaches, finds the named actor, and runs
    tasks (VERDICT round-3 'done' criterion).

    Since the head-failover PR the actor's WORKER survives the head's
    death (it parks on head-conn EOF and re-registers with the restarted
    head under the adopted session), so the actor keeps its STATE across
    the blip — adoption, not a fresh incarnation."""
    snap = str(tmp_path / "gcs.bin")
    key = os.urandom(16).hex()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    head = _start_head(snap, port, key, False)
    from ray_tpu._private import api_internal

    try:
        client = ray.init(address=f"tcp://127.0.0.1:{port}", _authkey=key)
        actor = ray.get_actor("kv_actor")
        assert ray.get(actor.put.remote("a", 1), timeout=60) == 1
        # Let the snapshot loop persist the actor + kv.
        deadline = time.time() + 20
        while not os.path.exists(snap) and time.time() < deadline:
            time.sleep(0.2)
        assert os.path.exists(snap)
        client.disconnect()
        api_internal.set_global_runtime(None)

        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)

        head = _start_head(snap, port, key, True)
        client = ray.init(address=f"tcp://127.0.0.1:{port}", _authkey=key)
        actor = ray.get_actor("kv_actor")
        # The surviving worker re-registered its incarnation: state
        # SURVIVES the head restart ({"a": 1} still there -> len 2).
        assert ray.get(actor.put.remote("b", 2), timeout=60) == 2

        @ray.remote
        def sq(x):
            return x * x

        assert ray.get(sq.remote(7), timeout=60) == 49
    finally:
        rt = api_internal.get_runtime()
        if rt is not None and getattr(rt, "is_client", False):
            try:
                rt.disconnect()
            except Exception:
                pass
        api_internal.set_global_runtime(None)
        try:
            head.kill()
        except Exception:
            pass
