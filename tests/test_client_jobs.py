"""Client mode, job submission, and CLI tests.

Reference patterns: ``python/ray/util/client`` tests (external process
drives the cluster), ``dashboard/modules/job/tests`` (submit/status/logs/
stop lifecycle), ``ray status`` CLI.
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu as ray


@pytest.fixture
def ray4():
    rt = ray.init(num_cpus=4)
    yield rt
    ray.shutdown()


def _client_env(rt):
    env = dict(os.environ)
    env["RAY_TPU_CLIENT_ADDRESS"] = rt.tcp_address
    env["RAY_TPU_CLIENT_AUTHKEY"] = rt._authkey.hex()
    env["PYTHONPATH"] = ("/root/repo" + os.pathsep
                         + env.get("PYTHONPATH", ""))
    return env


CLIENT_SCRIPT = """
import numpy as np
import ray_tpu as ray
ray.init()  # picks up RAY_TPU_CLIENT_ADDRESS from env

@ray.remote
def sq(x):
    return x * x

assert ray.get([sq.remote(i) for i in range(8)], timeout=60) == \
    [i * i for i in range(8)]

big = np.arange(2_000_000, dtype=np.int64)
ref = ray.put(big)  # lands in the HEAD's store via put_parts

@ray.remote
def total(a):
    return int(a.sum())

assert ray.get(total.remote(ref), timeout=60) == int(big.sum())
assert int(ray.get(ref, timeout=60).sum()) == int(big.sum())

@ray.remote
class Acc:
    def __init__(self):
        self.v = 0

    def add(self, x):
        self.v += x
        return self.v

a = Acc.remote()
assert ray.get([a.add.remote(1) for _ in range(3)], timeout=60) == [1, 2, 3]
ray.shutdown()
print("CLIENT_OK")
"""


def test_client_mode_end_to_end(ray4):
    p = subprocess.run([sys.executable, "-c", CLIENT_SCRIPT],
                       env=_client_env(ray4), capture_output=True,
                       text=True, timeout=180)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "CLIENT_OK" in p.stdout


def test_job_submission_lifecycle(ray4):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import os; "
                   f"print('hello from', os.environ['RAY_TPU_JOB_ID'])\"")
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(job_id) == "SUCCEEDED":
            break
        time.sleep(0.3)
    assert client.get_job_status(job_id) == "SUCCEEDED"
    assert "hello from" in client.get_job_logs(job_id)
    assert any(j["job_id"] == job_id for j in client.list_jobs())


def test_job_attaches_to_cluster(ray4):
    """The submitted entrypoint connects back to THIS cluster in client
    mode and runs tasks on it (reference: jobs are cluster drivers)."""
    from ray_tpu.job_submission import JobSubmissionClient

    script = ("import ray_tpu as ray; ray.init(); "
              "f = ray.remote(lambda: 40 + 2); "
              "print('answer:', ray.get(f.remote(), timeout=60)); "
              "ray.shutdown()")
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"{script}\"")
    deadline = time.time() + 120
    while time.time() < deadline:
        if client.get_job_status(job_id) not in ("PENDING", "RUNNING"):
            break
        time.sleep(0.3)
    assert client.get_job_status(job_id) == "SUCCEEDED", \
        client.get_job_logs(job_id)[-2000:]
    assert "answer: 42" in client.get_job_logs(job_id)


def test_job_stop(ray4):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import time; time.sleep(300)\"")
    time.sleep(0.5)
    assert client.stop_job(job_id)
    deadline = time.time() + 30
    while time.time() < deadline:
        if client.get_job_status(job_id) == "STOPPED":
            break
        time.sleep(0.2)
    assert client.get_job_status(job_id) == "STOPPED"


def test_cli_status_and_submit(ray4):
    env = _client_env(ray4)
    p = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "status",
         "--address", ray4.tcp_address, "--authkey", ray4._authkey.hex()],
        env=env, capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "resources" in p.stdout and "ALIVE" in p.stdout

    p = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "submit",
         "--address", ray4.tcp_address, "--authkey", ray4._authkey.hex(),
         "--follow", "--timeout", "90", "--",
         sys.executable, "-c", "print('cli job ran')"],
        env=env, capture_output=True, text=True, timeout=150)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "submitted: job_" in p.stdout
    assert "cli job ran" in p.stdout
    assert "status: SUCCEEDED" in p.stdout


def test_runtime_env_working_dir(ray4, tmp_path):
    """Tasks with runtime_env working_dir run chdir'ed into (and able to
    import from) a shipped copy of the directory."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "mymod.py").write_text("VALUE = 'from-working-dir'\n")
    (proj / "data.txt").write_text("payload\n")

    @ray.remote(runtime_env={"working_dir": str(proj)})
    def uses_dir():
        import mymod  # importable because cwd/sys.path include the pkg

        return mymod.VALUE, open("data.txt").read().strip()

    assert ray.get(uses_dir.remote(), timeout=60) == \
        ("from-working-dir", "payload")


def test_dashboard_endpoints(ray4):
    import json
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray.remote
    def f():
        return 1

    ray.get([f.remote() for _ in range(3)], timeout=60)
    url = start_dashboard(port=18265)
    try:
        def get(path):
            with urllib.request.urlopen(url + path, timeout=10) as r:
                return json.loads(r.read())

        cluster = get("/api/cluster")
        assert cluster["resources"].get("CPU") == 4.0
        nodes = get("/api/nodes")
        assert nodes and nodes[0]["alive"]
        tasks = get("/api/tasks")
        assert sum(1 for t in tasks if t["state"] == "FINISHED") >= 3
        assert isinstance(get("/api/summary"), dict)
        assert isinstance(get("/api/metrics"), dict)
        assert get("/api/jobs") == []
        assert isinstance(get("/api/handler_stats"), list)
        assert isinstance(get("/api/timeline"), list)
        with urllib.request.urlopen(url + "/", timeout=10) as r:
            html = r.read().decode()
        assert "<title>ray_tpu dashboard</title>" in html
        assert "/api/handler_stats" in html  # SPA wired to the REST API
    finally:
        stop_dashboard()
