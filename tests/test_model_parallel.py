"""Model-level parallelism tests: every mesh strategy must reproduce the
single-device numerics (the reference tests multi-node semantics with an
in-process Cluster, SURVEY.md §4.2; here the analog is the virtual 8-device
CPU mesh)."""

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import (
    LlamaConfig, init_params, forward, loss_fn, param_logical_axes,
)
from ray_tpu.models.llama import forward_pipelined
from ray_tpu.parallel import (MeshConfig, make_mesh, shard_pytree,
                              use_mesh)
from ray_tpu.train import TrainState, init_train_state, make_train_step


KEY = jax.random.PRNGKey(0)


def _batch(cfg, b=4, s=32):
    toks = jax.random.randint(KEY, (b, s + 1), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    return {"tokens": toks}


@pytest.mark.parametrize("name,cfg_kw,mesh_kw", [
    ("dp_fsdp_tp", {}, dict(dp=2, fsdp=2, tp=2)),
    ("flash_shmap", {"attn_impl": "flash"}, dict(dp=4, tp=2)),
    ("moe_ring_sp", {"num_experts": 4, "attn_impl": "ring"},
     dict(dp=2, sp=2, ep=2)),
    ("moe_ulysses", {"num_experts": 4, "attn_impl": "ulysses"},
     dict(sp=4, ep=2)),
])
def test_sharded_loss_matches_single_device(name, cfg_kw, mesh_kw):
    cfg = LlamaConfig.tiny(**cfg_kw)
    params = init_params(KEY, cfg)
    batch = _batch(cfg)
    ref, _ = loss_fn(params, batch, cfg)
    mesh = make_mesh(MeshConfig(**mesh_kw))
    with use_mesh(mesh):
        sp = shard_pytree(params, param_logical_axes(cfg), mesh)
        toks = jax.device_put(
            batch["tokens"], NamedSharding(mesh, P(("dp", "fsdp"), None)))
        got, _ = jax.jit(
            lambda p, t: loss_fn(p, {"tokens": t}, cfg, mesh=mesh))(sp, toks)
    assert abs(float(got) - float(ref)) < 1e-4, name


@pytest.mark.parametrize("attn", ["reference", "ring"])
def test_pipelined_forward_matches(attn):
    cfg = LlamaConfig.tiny(num_layers=4, attn_impl=attn)
    params = init_params(KEY, cfg)
    toks = jax.random.randint(KEY, (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    ref_logits, _ = forward(params, toks, cfg)
    mesh = make_mesh(MeshConfig(dp=2, pp=2, sp=2 if attn == "ring" else 1,
                                tp=1 if attn == "ring" else 2))
    with use_mesh(mesh):
        sp = shard_pytree(params, param_logical_axes(cfg), mesh)
        ts = jax.device_put(toks, NamedSharding(mesh, P(("dp", "fsdp"),
                                                        None)))
        got, _ = jax.jit(lambda p, t: forward_pipelined(
            p, t, cfg, mesh=mesh, num_microbatches=4))(sp, ts)
    assert jnp.max(jnp.abs(got - ref_logits)) < 5e-4


def test_train_step_decreases_loss_single_device():
    cfg = LlamaConfig.tiny()
    opt = optax.adam(1e-2)
    state = init_train_state(KEY, cfg, opt)
    step = make_train_step(cfg, opt)
    batch = _batch(cfg)
    state, m0 = step(state, batch)   # step donates its input state
    for _ in range(10):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(m0["loss"])


def test_train_step_sharded_matches_single_device():
    cfg = LlamaConfig.tiny()
    opt = optax.adam(1e-2)
    batch = _batch(cfg, b=8)

    state = init_train_state(KEY, cfg, opt)
    step = make_train_step(cfg, opt, donate=False)
    s1, m1 = step(state, batch)

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    with use_mesh(mesh):
        state_sh = init_train_state(KEY, cfg, opt, mesh=mesh)
        step_sh = make_train_step(cfg, opt, mesh=mesh, donate=False)
        toks = jax.device_put(
            batch["tokens"], NamedSharding(mesh, P(("dp", "fsdp"), None)))
        s2, m2 = step_sh(state_sh, {"tokens": toks})
        assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-4
        # params after one step agree
        d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                         s1.params, s2.params)
    assert max(jax.tree.leaves(d)) < 1e-4


@pytest.mark.slow  # ~38s of multichip mesh dryruns (the single biggest
# tier-1 sink); sharding coverage keeps its tier-1 representatives via
# test_train_step_sharded_matches_single_device and the
# test_sharded_loss_matches_single_device battery above.
def test_graft_entry_dryrun():
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import __graft_entry__ as g
    g.dryrun_multichip(8)
    fn, args = g.entry()
    jax.eval_shape(fn, *args)  # traceability; full compile covered by driver
