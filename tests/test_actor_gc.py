"""Actor-handle GC + blocked-slot lending (reference: actor out-of-scope
termination, gcs_actor_manager.h; extra workers for blocked ones,
ray_config_def.h:174-187)."""
import time

import pytest

import ray_tpu as ray


@pytest.fixture
def init4():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray.shutdown()


def test_actor_killed_when_handles_dropped(init4):
    @ray.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    b = A.remote()
    assert ray.get([a.ping.remote(), b.ping.remote()]) == [1, 1]
    del a, b
    time.sleep(2.5)  # deferred GC window
    # Both slots must be free again: 4 fresh actors fit on 4 CPUs.
    fresh = [A.remote() for _ in range(4)]
    assert ray.get([c.ping.remote() for c in fresh], timeout=30) == [1] * 4


def test_actor_survives_while_result_pending(init4):
    @ray.remote
    class S:
        def slow(self):
            import time
            time.sleep(3)
            return "done"

    s = S.remote()
    ref = s.slow.remote()
    del s  # handle gone, but the in-flight call must still complete
    assert ray.get(ref, timeout=30) == "done"


def test_named_actor_not_gcd(init4):
    @ray.remote
    class N:
        def ping(self):
            return "alive"

    N.options(name="keeper").remote()
    time.sleep(2.5)
    h = ray.get_actor("keeper")
    assert ray.get(h.ping.remote(), timeout=30) == "alive"
    ray.kill(h)


def test_handle_passed_through_task_keeps_actor(init4):
    @ray.remote
    class C:
        def val(self):
            return 42

    @ray.remote
    def use(handle):
        import ray_tpu as ray
        return ray.get(handle.val.remote())

    c = C.remote()
    ref = use.remote(c)
    del c  # in-flight pickled +1 keeps it alive for the task
    assert ray.get(ref, timeout=30) == 42


def test_stored_handle_materialized_twice_stays_balanced(init4):
    """A handle pickled into a stored object and fetched N times must not
    over-decref (token-based transfer-on-send)."""
    @ray.remote
    class K:
        def val(self):
            return 7

    @ray.remote
    def use(handles):
        import ray_tpu as ray
        return ray.get(handles[0].val.remote())

    k = K.remote()
    box = ray.put([k])
    assert ray.get([use.remote(box), use.remote(box)], timeout=60) == [7, 7]
    time.sleep(2.5)  # any premature GC would fire in this window
    assert ray.get(k.val.remote(), timeout=30) == 7


def test_blocked_workers_lend_slots(init4):
    """A cluster fully packed with actors must still run the tasks an
    actor blocks on (the extra-blocked-workers guarantee)."""
    @ray.remote
    def leaf():
        return 1

    @ray.remote
    class Waiter:
        def go(self, n):
            import ray_tpu as ray
            return sum(ray.get([leaf.remote() for _ in range(n)]))

    waiters = [Waiter.remote() for _ in range(4)]  # all 4 CPUs held
    out = ray.get([w.go.remote(10) for w in waiters], timeout=60)
    assert out == [10] * 4
