"""Runtime lock-order checker tests: cycle detection on deliberately
inverted locks, the RAY_TPU_LOCKCHECK env opt-in, the documented lock
conventions of object_transfer/shm_store verified against the recorded
acquisition graph, and the async event-loop stall watch."""

import asyncio
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from ray_tpu.devtools import lockcheck


@pytest.fixture
def checker():
    """Install instrumentation for one test; always restore the real
    threading.Lock/RLock factories afterwards."""
    lockcheck.install(raise_on_cycle=False)
    lockcheck.clear()
    yield lockcheck
    lockcheck.uninstall()


# -- core cycle detection ---------------------------------------------------

def _make_two_locks():
    # Distinct lines => distinct lock classes (site = creation file:line).
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    return lock_a, lock_b


def test_inverted_two_lock_acquisition_detected(checker):
    lock_a, lock_b = _make_two_locks()
    with lock_a:
        with lock_b:
            pass
    assert checker.violations() == []  # one order alone is fine
    with lock_b:
        with lock_a:
            pass
    assert len(checker.violations()) == 1
    assert "potential deadlock" in checker.violations()[0]
    with pytest.raises(lockcheck.LockOrderError):
        checker.assert_acyclic()


def test_consistent_order_stays_clean(checker):
    lock_a, lock_b = _make_two_locks()
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert checker.violations() == []
    checker.assert_acyclic()


def test_three_lock_cycle_detected(checker):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    lock_c = threading.Lock()
    with lock_a:
        with lock_b:
            pass
    with lock_b:
        with lock_c:
            pass
    with lock_c:
        with lock_a:
            pass  # closes a -> b -> c -> a
    assert len(checker.violations()) == 1


def test_raise_mode_raises_and_releases(checker):
    lockcheck.install(raise_on_cycle=True)
    lock_a, lock_b = _make_two_locks()
    with lock_a:
        with lock_b:
            pass
    with pytest.raises(lockcheck.LockOrderError):
        with lock_b:
            with lock_a:
                pass
    # The violating acquire must not leak either lock.
    assert not lock_a.locked()
    assert not lock_b.locked()


def test_rlock_reentrancy_is_not_a_cycle(checker):
    rlock = threading.RLock()
    with rlock:
        with rlock:
            pass
    assert checker.violations() == []


def test_condition_variable_wait_notify_under_proxies(checker):
    # Condition over a proxied Lock exercises the _release_save/_is_owned
    # fallback paths; a hang or crash here means the proxy broke the
    # threading.Condition contract.
    cond = threading.Condition(threading.Lock())
    ready = []

    def waiter():
        with cond:
            ready.append(True)
            cond.wait(timeout=5)
            ready.append("woken")

    thread = threading.Thread(target=waiter, daemon=True)
    thread.start()
    deadline = time.monotonic() + 5
    while not ready and time.monotonic() < deadline:
        time.sleep(0.005)
    with cond:
        cond.notify_all()
    thread.join(timeout=5)
    assert ready == [True, "woken"]
    checker.assert_acyclic()


def test_cross_thread_lock_handoff_leaves_no_stale_hold(checker):
    """A plain Lock acquired on one thread and released on another (the
    handoff pattern RTL401 suppressions endorse) must clear the
    ACQUIRING thread's held entry — otherwise every later acquisition on
    that thread records bogus edges from the handed-off lock."""
    handoff = threading.Lock()
    other_a = threading.Lock()
    other_b = threading.Lock()
    handoff.acquire()  # held by main thread, released elsewhere

    releaser = threading.Thread(target=handoff.release)
    releaser.start()
    releaser.join(timeout=5)
    assert not handoff.locked()
    # Main thread no longer holds anything: these nestings must not
    # record edges from the handed-off lock's site.  (Edges recorded
    # WHILE handoff was held — e.g. Thread.start()'s internal Event
    # lock — are legitimate and may exist.)
    with other_a:
        with other_b:
            pass
    handoff_site = handoff._site
    edges = checker.edges()
    assert other_a._site not in edges.get(handoff_site, set()), edges
    assert other_b._site not in edges.get(handoff_site, set()), edges
    assert other_b._site in edges.get(other_a._site, set())
    assert checker.violations() == []


def test_uninstall_restores_real_factories():
    lockcheck.install()
    lockcheck.uninstall()
    assert not lockcheck.enabled()
    assert not isinstance(threading.Lock(), lockcheck._LockProxy)


# -- env opt-in -------------------------------------------------------------

def test_env_flag_runtime_smoke_and_inversion_detection():
    """One subprocess covers both env-opt-in scenarios (kept to a single
    interpreter spawn for tier-1 budget):

    1. the standard-run smoke — a real init/task/actor/put workload under
       RAY_TPU_LOCKCHECK=1 completes with ZERO lock-order violations,
       which keeps future scale-out PRs honest about lock ordering;
    2. the acceptance scenario — a deliberately inverted two-lock
       acquisition afterwards IS reported by the env-installed checker.
    """
    code = textwrap.dedent("""
        import threading
        import ray_tpu
        from ray_tpu.devtools import lockcheck
        assert lockcheck.enabled(), "env flag did not install lockcheck"
        ray_tpu.init(num_cpus=2, num_tpus=0)

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get([f.remote(i) for i in range(4)]) == [1, 2, 3, 4]

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

            async def peek(self):
                return self.n

        c = Counter.remote()
        assert ray_tpu.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]
        assert ray_tpu.get(c.peek.remote()) == 3
        ref = ray_tpu.put(list(range(50000)))
        assert len(ray_tpu.get(ref)) == 50000
        ray_tpu.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations in runtime: " + repr(bad)

        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(lockcheck.violations()) == 1, lockcheck.violations()
        print("LOCKCHECK_SMOKE_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "LOCKCHECK_SMOKE_OK" in proc.stdout


# -- documented lock conventions --------------------------------------------

class _DeadConn:
    """Stand-in connection: dial succeeds, first send fails."""

    def fileno(self):
        raise OSError("no fd")  # enable_nodelay tolerates this

    def send_bytes(self, data):
        raise OSError("peer gone")

    def close(self):
        pass


def test_object_puller_lock_order_convention(checker, monkeypatch):
    """object_transfer.ObjectPuller's documented convention: the registry
    lock and every pool's condition lock are independent leaves — the
    recorded acquisition graph must contain NO edge between them (in
    either direction), even on the fetch-failure path where evict()
    (condition lock) follows a failed stream on an exclusively-held
    connection."""
    import multiprocessing.connection

    from ray_tpu._private.object_transfer import ObjectPuller

    monkeypatch.setattr(multiprocessing.connection, "Client",
                        lambda addr, authkey=None: _DeadConn())
    puller = ObjectPuller(authkey=b"x", pool_size=2, stripe_threshold=0)
    assert isinstance(puller._lock, lockcheck._LockProxy)
    with pytest.raises(OSError):
        puller.fetch("store-1", "tcp://127.0.0.1:1", "segment")
    # The failed fetch exercised: registry (pool creation), the pool
    # condition (acquire's count bump, dial outside it, evict's count
    # drop + waiter wakeup), and the stream send on a lock-free
    # exclusively-acquired connection.
    pool = puller._pools["store-1"]
    registry_site = puller._lock._site
    pool_site = pool.cv._lock._site
    edges = lockcheck.edges()
    assert pool_site not in edges.get(registry_site, set()), (
        "registry lock held while taking a pool condition lock")
    assert registry_site not in edges.get(pool_site, set()), (
        "pool condition lock held while taking the registry lock")
    assert all(registry_site not in targets
               for targets in edges.values()), (
        f"some lock is held while acquiring the registry lock: {edges}")
    checker.assert_acyclic()
    puller.close()


def test_pull_registry_lock_order_convention(checker):
    """object_transfer.PullRegistry's documented convention: the registry
    ``_lock`` is an INDEPENDENT LEAF — never held across a dial, stream
    I/O or an event wait, and NO other lock is acquired under it (note
    Event.set acquires the event's internal condition lock, so finish()
    must — and does — set outside ``_lock``).  The recorded acquisition
    graph must show zero outgoing edges from the registry lock across
    the leader/waiter/retain/consume/failure paths."""
    from ray_tpu._private.object_transfer import PullRegistry

    class _Seg:
        size = 7

        def close(self):
            pass

    reg = PullRegistry()
    assert isinstance(reg._lock, lockcheck._LockProxy)
    # Leader + concurrent waiter sharing its result.
    ent, leader = reg.begin(("s", "a"))
    assert leader
    got = []
    waiter = threading.Thread(target=lambda: got.append(ent.wait(5)))
    waiter.start()
    seg = _Seg()
    reg.finish(("s", "a"), ent, seg)
    waiter.join(timeout=5)
    assert got == [seg]
    assert reg.deduped_pulls == 0  # the waiter attached via wait(), not begin
    # Prefetch retention + consume.
    pent, pleader = reg.begin(("s", "b"), prefetch=True)
    assert pleader
    reg.finish(("s", "b"), pent, _Seg(), retain=True)
    cent, cleader = reg.begin(("s", "b"))
    assert not cleader and reg.take(("s", "b"), cent) is pent.seg
    # Failure path wakes into the fallback.
    fent, fleader = reg.begin(("s", "c"))
    assert fleader
    reg.finish(("s", "c"), fent, None)
    assert fent.wait(1) is None
    registry_site = reg._lock._site
    edges = checker.edges()
    assert edges.get(registry_site, set()) == set(), (
        f"a lock was acquired while holding the pull-registry lock: "
        f"{edges.get(registry_site)}")
    checker.assert_acyclic()


def test_put_registry_lock_order_convention(checker, tmp_path):
    """object_transfer.PutRegistry's documented convention: the
    server-side put-registry ``_lock`` is an INDEPENDENT LEAF — it
    guards only the entry table and writer counts; reservation (file
    create + store accounting), stripe recv streaming, and mapping
    teardown all run OUTSIDE it.  The recorded acquisition graph must
    show zero outgoing edges from it across the reserve/write/commit/
    abort/dead-writer paths.  (The store's own ``_lock``, taken inside
    reserve_put, is a separate class acquired while the registry lock is
    NOT held.)"""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_transfer import CHUNK, PutRegistry
    from ray_tpu._private.shm_store import ShmStore

    class _FeedConn:
        """recv_bytes_into stub: fills the requested range with zeros,
        one CHUNK-sized message at a time."""

        def __init__(self):
            self.left = 0

        def recv_bytes_into(self, view, off=0):
            n = min(CHUNK, len(view) - off)
            view[off:off + n] = b"\0" * n
            return n

    store = ShmStore(shm_dir=str(tmp_path), session_id="putlock")
    reg = PutRegistry(store)
    assert isinstance(reg._lock, lockcheck._LockProxy)
    # Reserve -> stripe write -> commit.
    name = reg.reserve(ObjectID.from_random().binary(), 4096)
    assert reg.write(name, _FeedConn(), 0, 4096)
    kind, ident, total = reg.commit(name)
    assert (kind, ident, total) == ("shm", name, 4096)
    # Reserve -> abort; a late stripe for the aborted put drains via the
    # discard path (needs recv_bytes, absent on the stub -> use a fresh
    # name with zero length instead: the bounds check refuses in-lock).
    name2 = reg.reserve(ObjectID.from_random().binary(), 4096)
    reg.abort(name2)
    assert not reg.write(name2, _FeedConn(), 0, 0)
    reg_site = reg._lock._site
    edges = checker.edges()
    assert edges.get(reg_site, set()) == set(), (
        f"a lock was acquired while holding the put-registry lock: "
        f"{edges.get(reg_site)}")
    checker.assert_acyclic()
    store.cleanup()


def test_streaming_stats_lock_convention(checker):
    """data/streaming_executor.StreamingStats._lock's documented
    convention: an independent LEAF — the executor's dispatch loop is
    single-threaded and the lock only guards counter snapshots read by
    Dataset.stats(), so it is never held across submission/wait/get and
    NO other lock is acquired under it.  The recorded acquisition graph
    must show zero outgoing edges from the stats lock across the
    row-create/update/snapshot paths."""
    from ray_tpu.data.streaming_executor import StreamingStats

    stats = StreamingStats(budget_bytes=1 << 20, inflight_cap=4)
    assert isinstance(stats._lock, lockcheck._LockProxy)
    row = stats.op_row("map+filter")
    with stats._lock:
        row["inflight"] += 1
        stats.admitted_tasks += 1
    stats.note_live_bytes(512)
    # Concurrent reader (the Dataset.stats() shape) while the "executor
    # thread" keeps mutating.
    got = []
    reader = threading.Thread(target=lambda: got.append(stats.summary()))
    reader.start()
    stats.note_live_bytes(1024)
    reader.join(timeout=5)
    assert got and got[0]["admitted_tasks"] == 1
    assert stats.summary()["peak_inflight_bytes"] == 1024
    stats_site = stats._lock._site
    edges = checker.edges()
    assert edges.get(stats_site, set()) == set(), (
        f"a lock was acquired while holding the streaming-stats lock: "
        f"{edges.get(stats_site)}")
    checker.assert_acyclic()


def test_shuffle_stats_lock_convention(checker, monkeypatch):
    """data/shuffle._STATS_LOCK's documented convention: an independent
    LEAF — it guards only the process-local shuffle counter dict read by
    ``shuffle_stats()`` (the xfer_stats flusher / transfer_stats merge)
    and is never held across serialization, a push, or any wire call.
    The recorded acquisition graph must show zero outgoing edges from
    the stats lock across the note/snapshot paths."""
    from ray_tpu.data import shuffle as _sh

    # Module-level lock predates install(): swap in one created under
    # instrumentation (the _copy_pool_lock test's pattern).
    monkeypatch.setattr(_sh, "_STATS_LOCK", threading.Lock())
    monkeypatch.setattr(_sh, "_STATS", {
        "shuffle_pushed_bytes": 0, "shuffle_merges": 0,
        "shuffle_spills": 0, "shuffle_hedges": 0})
    assert isinstance(_sh._STATS_LOCK, lockcheck._LockProxy)
    _sh.note("shuffle_pushed_bytes", 4096)
    _sh.note("shuffle_merges")
    # Concurrent reader (the flush-thread shape) while the "map task"
    # keeps counting.
    got = []
    reader = threading.Thread(
        target=lambda: got.append(_sh.shuffle_stats()))
    reader.start()
    _sh.note("shuffle_hedges")
    reader.join(timeout=5)
    assert got and got[0]["shuffle_pushed_bytes"] == 4096
    assert _sh.shuffle_stats()["shuffle_merges"] == 1
    stats_site = _sh._STATS_LOCK._site
    edges = checker.edges()
    assert edges.get(stats_site, set()) == set(), (
        f"a lock was acquired while holding the shuffle-stats lock: "
        f"{edges.get(stats_site)}")
    checker.assert_acyclic()


def test_train_stats_lock_convention(checker, monkeypatch):
    """train/pipeline_actors._STATS_LOCK's documented convention: an
    independent LEAF guarding only the process-local training counter
    dict read by ``train_stats()`` (the xfer_stats flusher /
    transfer_stats merge); never held across serialization, a push, or
    any wire call — zero outgoing edges across the note/snapshot paths."""
    from ray_tpu.train import pipeline_actors as _pa

    monkeypatch.setattr(_pa, "_STATS_LOCK", threading.Lock())
    monkeypatch.setattr(_pa, "_STATS", {
        "microbatch_pushes": 0, "stage_restarts": 0,
        "learner_queue_stalls": 0})
    assert isinstance(_pa._STATS_LOCK, lockcheck._LockProxy)
    _pa.note("microbatch_pushes", 3)
    _pa.note("stage_restarts")
    got = []
    reader = threading.Thread(
        target=lambda: got.append(_pa.train_stats()))
    reader.start()
    _pa.note("learner_queue_stalls")
    reader.join(timeout=5)
    assert got and got[0]["microbatch_pushes"] == 3
    assert _pa.train_stats()["stage_restarts"] == 1
    stats_site = _pa._STATS_LOCK._site
    edges = checker.edges()
    assert edges.get(stats_site, set()) == set(), (
        f"a lock was acquired while holding the training-stats lock: "
        f"{edges.get(stats_site)}")
    checker.assert_acyclic()


def test_lineage_table_lock_is_leaf(checker):
    """recovery.LineageTable._lock's documented convention: an
    independent LEAF.  Both owners take it while already holding their
    big lock — the head's runtime lock (record in _submit_specs, release
    in _maybe_free_locked) and every DirectCaller's ownership lock — and
    the table runs NO callbacks and acquires NO lock under it (eviction
    RETURNS entries for the caller to release at its own level).  The
    recorded graph must show the incoming edge and zero outgoing
    edges."""
    import ray_tpu as ray
    from ray_tpu._private import api_internal

    ray.init(num_cpus=2, num_tpus=0)
    try:
        rt = api_internal.get_runtime()
        assert isinstance(rt.lineage._lock, lockcheck._LockProxy)
        assert rt.config.recovery

        @ray.remote
        def f(x):
            return x + 1

        refs = [f.remote(i) for i in range(8)]
        assert ray.get(refs) == list(range(1, 9))
        # Release path: dropping the refs drives lineage.release under
        # the runtime lock (the recorded inward edge).
        del refs
        import gc

        gc.collect()
        time.sleep(0.2)
        lineage_site = rt.lineage._lock._site
    finally:
        ray.shutdown()
    edges = checker.edges()
    assert edges.get(lineage_site, set()) == set(), (
        f"a lock was acquired while holding the lineage-table lock: "
        f"{edges.get(lineage_site)}")
    checker.assert_acyclic()


def test_shm_store_copy_pool_lock_convention(checker, monkeypatch,
                                             tmp_path):
    """shm_store's documented convention: the module copy-pool lock and
    the store's _lock are independent leaves — a large (parallel-copied)
    put followed by pooled reuse must record no edge between them."""
    if (os.cpu_count() or 1) < 2:
        pytest.skip("parallel copy path needs >= 2 cores")
    from ray_tpu._private import shm_store as shm_mod
    from ray_tpu._private.ids import ObjectID

    # The module-level pool lock predates install(); swap in a fresh
    # (instrumented) one and force pool re-creation through it.
    monkeypatch.setattr(shm_mod, "_copy_pool_lock", threading.Lock())
    monkeypatch.setattr(shm_mod, "_copy_pool", None)
    store = shm_mod.ShmStore(shm_dir=str(tmp_path), session_id="lockchk",
                             pool_bytes=256 << 20)
    assert isinstance(store._lock, lockcheck._LockProxy)
    payload = memoryview(bytearray(shm_mod._PARALLEL_COPY_MIN + 1024))
    name, size = store.create_from_parts(ObjectID.from_random(), b"meta",
                                         [payload])
    store.unlink(name, size, reusable=True)
    # Second create reuses the pooled mapping (pool scan under _lock).
    name2, _size2 = store.create_from_parts(ObjectID.from_random(),
                                            b"meta", [payload])
    store_site = store._lock._site
    pool_site = shm_mod._copy_pool_lock._site
    edges = lockcheck.edges()
    assert pool_site not in edges.get(store_site, set()), (
        "store._lock held while taking the copy-pool lock")
    assert store_site not in edges.get(pool_site, set()), (
        "copy-pool lock held while taking store._lock")
    checker.assert_acyclic()
    store.cleanup()


def test_dispatch_shard_dirty_lock_convention(checker):
    """Decentralized dispatch's documented convention: the per-shard
    dirty-set lock (Runtime._dispatch_dirty_lock) is an independent LEAF
    — marking a shard dirty happens under the runtime lock on the hot
    paths, the dispatcher's wake event is set OUTSIDE it, and NO other
    lock is ever acquired under it.  The recorded acquisition graph must
    show zero outgoing edges from it across a real submit/result cycle
    (driver bursts route through the deferred-dispatch marking)."""
    import ray_tpu as ray
    from ray_tpu._private import api_internal

    ray.init(num_cpus=2, num_tpus=0)
    try:
        rt = api_internal.get_runtime()
        assert isinstance(rt._dispatch_dirty_lock, lockcheck._LockProxy)
        assert rt.config.decentralized_dispatch

        @ray.remote
        def f(x):
            return x + 1

        # Burst (deferred marking) + per-result class top-ups.
        assert ray.get([f.remote(i) for i in range(8)]) == \
            list(range(1, 9))
        dirty_site = rt._dispatch_dirty_lock._site
    finally:
        ray.shutdown()
    edges = checker.edges()
    assert edges.get(dirty_site, set()) == set(), (
        f"a lock was acquired while holding the dispatch dirty lock: "
        f"{edges.get(dirty_site)}")
    checker.assert_acyclic()


# -- event-loop stall watch -------------------------------------------------

def test_event_loop_stall_recorded(checker):
    loop = asyncio.new_event_loop()
    lockcheck.watch_loop(loop, threshold_s=0.05)
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    try:
        async def blocking_handler():
            time.sleep(0.12)  # noqa: RTL102 -- deliberate stall for test
            return "done"

        fut = asyncio.run_coroutine_threadsafe(blocking_handler(), loop)
        assert fut.result(timeout=5) == "done"
        deadline = time.monotonic() + 2
        while not lockcheck.stalls() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert any("took" in s for s in lockcheck.stalls()), \
            lockcheck.stalls()
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        loop.close()


def test_fast_async_handler_records_no_stall(checker):
    loop = asyncio.new_event_loop()
    lockcheck.watch_loop(loop, threshold_s=0.05)
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    try:
        async def quick():
            return 1

        assert asyncio.run_coroutine_threadsafe(quick(), loop).result(5) \
            == 1
        assert lockcheck.stalls() == []
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        loop.close()


def test_serve_batcher_locks_are_leaves(checker):
    """serve/batching + serve/continuous documented convention: both
    batcher locks are independent LEAVES — they guard only the pending
    queue and counters, the wrapped/step function runs with no lock
    held, and caller events are set outside them.  The recorded
    acquisition graph must show zero outgoing edges from either lock
    across a concurrent submit/step/retire cycle (including a stats
    snapshot taken mid-flight, the serving_stats path)."""
    from ray_tpu.serve.batching import _Batcher
    from ray_tpu.serve.continuous import _ContinuousBatcher

    def stepfn(slots):
        time.sleep(0.001)
        for s in slots:
            s.state = (s.state or 0) + 1
            if s.state >= s.request:
                s.finish(s.state)

    cont = _ContinuousBatcher(stepfn, None, 4, 0.0, continuous=True)
    oneshot = _Batcher(lambda items: [x * 2 for x in items], None, 4,
                       0.02)
    assert isinstance(cont._lock, lockcheck._LockProxy)
    assert isinstance(oneshot._lock, lockcheck._LockProxy)
    results = []
    threads = [threading.Thread(target=lambda n=n:
                                results.append(cont.submit(n)))
               for n in (1, 2, 3, 1, 2, 3)]
    threads += [threading.Thread(target=lambda n=n:
                                 results.append(oneshot.submit(n)))
                for n in (4, 5, 6)]
    for t in threads:
        t.start()
    cont.stats()  # concurrent snapshot while the batch runs
    oneshot.stats()
    for t in threads:
        t.join(timeout=30)
    assert len(results) == 9
    edges = checker.edges()
    for site in (cont._lock._site, oneshot._lock._site):
        assert edges.get(site, set()) == set(), (
            f"a lock was acquired while holding a serve batcher lock: "
            f"{edges.get(site)}")
    checker.assert_acyclic()


def test_disagg_chain_lock_is_leaf(checker, monkeypatch):
    """serve/tpu_replica documented convention: the replica's
    ``_chain_lock`` (handoff bookkeeping + ingest-info cache) is an
    independent LEAF — kv_debug releases it BEFORE taking the engine
    guard, prefill_export's fallback counting nests nothing under it,
    and no wire call runs while it is held.  Driven through a real
    prefill-only handoff (inline fallback: no runtime) plus the debug
    snapshot, the acquisition graph must show zero outgoing edges from
    the chain lock."""
    from ray_tpu._private import config as _cfg
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    # The paged batcher attaches at first call off the process config.
    monkeypatch.setattr(_cfg.GLOBAL_CONFIG, "paged_kv", True)
    dec = MeshShardedDecoder(paged=True, kv_blocks=32, kv_block_size=8)
    assert isinstance(dec._chain_lock, lockcheck._LockProxy)
    assert dec.kv_ingest_info() is None          # no runtime: inline tier
    descr, sampler = dec.prefill_export(
        {"prompt": list(range(12)), "tokens": 4})
    assert descr[0] == "inline" and sampler["pos"] == 12
    dbg = dec.kv_debug()
    assert dbg["chain"]["inline_fallbacks"] == 1
    assert dbg["exports_outstanding"] == 0
    chain_site = dec._chain_lock._site
    edges = checker.edges()
    assert edges.get(chain_site, set()) == set(), (
        f"a lock was acquired while holding the chain-handoff lock: "
        f"{edges.get(chain_site)}")
    checker.assert_acyclic()


def test_disagg_router_affinity_lock_is_leaf(checker):
    """serve/api documented convention: DeploymentHandle's
    ``_affinity_lock`` (prefix-affinity table + router counters) is an
    independent LEAF — _pick_prefill takes the router ``_lock`` and the
    affinity lock STRICTLY sequentially (reps snapshot, then table
    lookup; p2c fallback, then registration), so the recorded graph
    must show zero outgoing edges from the affinity lock and no edge
    between the two in either direction."""
    from ray_tpu.serve.api import DeploymentHandle

    class _Rep:
        def __init__(self, aid):
            self._actor_id = aid

    h = object.__new__(DeploymentHandle)
    h._router_init()
    h._affinity_on = True
    from collections import OrderedDict

    h._affinity = OrderedDict()
    h._affinity_lock = threading.Lock()
    h._router_prefix_hits = 0
    h._router_prefix_misses = 0
    h._prefill_replicas = [_Rep(b"a"), _Rep(b"b")]
    assert isinstance(h._affinity_lock, lockcheck._LockProxy)
    prompt = list(range(24))
    first = h._pick_prefill(prompt)              # miss -> p2c + register
    assert first in h._prefill_replicas
    assert h._pick_prefill(prompt) is first      # affinity hit
    h._prefill_replicas = [_Rep(b"c")]           # old pick died
    again = h._pick_prefill(prompt)              # stale prune + re-pin
    assert again._actor_id == b"c"
    stats = h.router_stats()
    assert stats["router_prefix_hits"] == 1
    assert stats["router_prefix_misses"] == 2
    aff_site = h._affinity_lock._site
    lock_site = h._lock._site
    edges = checker.edges()
    assert edges.get(aff_site, set()) == set(), (
        f"a lock was acquired while holding the affinity lock: "
        f"{edges.get(aff_site)}")
    assert aff_site not in edges.get(lock_site, set()), (
        "router _lock held while taking the affinity lock")
    checker.assert_acyclic()


def test_paged_batcher_lock_stays_leaf_with_kv_engine(checker):
    """Paged-KV admission convention (serve/kv_cache.py): the engine
    adopts the batcher's LEAF lock via bind() — block-availability
    re-checks at admission, retire-time frees, step-side write planning,
    and a mid-flight stats snapshot all run under the ONE batcher lock,
    with caller events still set outside it.  Driven through allocator
    exhaustion (parks + re-admission) the acquisition graph must show
    zero outgoing edges from the batcher lock."""
    from ray_tpu.serve.continuous import _ContinuousBatcher
    from ray_tpu.serve.kv_cache import PagedKVEngine

    eng = PagedKVEngine(4, 4, tokens_for=lambda r: ((), r),
                        prefix_caching=False)

    def stepfn(slots):
        time.sleep(0.001)
        for s in slots:
            s.state = (s.state or 0) + 1
            # Step-side engine paths acquire the SAME (leaf) guard.
            eng.plan_writes(s, s.state - 1, 1)
            eng.note_tokens(1)
            if s.state >= s.request:
                s.finish(s.state)

    b = _ContinuousBatcher(stepfn, None, 8, 0.0, continuous=True, kv=eng)
    assert isinstance(b._lock, lockcheck._LockProxy)
    assert eng._guard is b._lock   # bind() adopted the batcher leaf
    results = []
    # 16-token pool, 8-token budgets: >2 concurrent submits exhaust the
    # pool so the run exercises park -> retire -> re-admit boundaries.
    threads = [threading.Thread(target=lambda n=n:
                                results.append(b.submit(n)))
               for n in (8, 8, 8, 8, 8, 8)]
    for t in threads:
        t.start()
    b.stats()                      # concurrent snapshot mid-flight
    for t in threads:
        t.join(timeout=30)
    assert len(results) == 6
    s = b.stats()
    assert s["admission_parks"] >= 1 and s["kv_blocks_used"] == 0
    edges = checker.edges()
    assert edges.get(b._lock._site, set()) == set(), (
        f"a lock was acquired while holding the paged batcher leaf "
        f"lock: {edges.get(b._lock._site)}")
    checker.assert_acyclic()
