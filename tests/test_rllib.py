"""RL-stack tests (reference pattern: rllib/**/tests — per-algorithm
learning smoke tests on CartPole, SURVEY.md §4.2)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.rllib import PPOConfig, ImpalaConfig
from ray_tpu.rllib.sample_batch import (
    ADVANTAGES, DONES, REWARDS, SampleBatch, VF_PREDS, VALUE_TARGETS,
)
from ray_tpu.rllib.rollout_worker import compute_gae
from ray_tpu.rllib.vtrace import vtrace


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    ray.shutdown()


def cartpole():
    import gymnasium
    return gymnasium.make("CartPole-v1")


def test_gae_matches_manual():
    batch = SampleBatch({
        REWARDS: np.array([1.0, 1.0, 1.0], np.float32),
        VF_PREDS: np.array([0.5, 0.4, 0.3], np.float32),
        DONES: np.array([False, False, True]),
    })
    g, lam = 0.9, 0.8
    out = compute_gae(batch, last_value=9.9, gamma=g, lam=lam)
    # t=2 terminal: delta = 1 - 0.3
    d2 = 1 - 0.3
    d1 = 1 + g * 0.3 - 0.4
    d0 = 1 + g * 0.4 - 0.5
    a2 = d2
    a1 = d1 + g * lam * a2
    a0 = d0 + g * lam * a1
    assert np.allclose(out[ADVANTAGES], [a0, a1, a2], atol=1e-5)
    assert np.allclose(out[VALUE_TARGETS],
                       out[ADVANTAGES] + batch[VF_PREDS], atol=1e-6)


def test_vtrace_on_policy_reduces_to_returns():
    """With target==behavior (rho=c=1), vs must equal n-step returns."""
    import jax.numpy as jnp
    t, b = 5, 2
    rng = np.random.default_rng(0)
    logp = jnp.asarray(rng.normal(size=(t, b)).astype(np.float32))
    rewards = jnp.asarray(rng.normal(size=(t, b)).astype(np.float32))
    values = jnp.asarray(rng.normal(size=(t, b)).astype(np.float32))
    bootstrap = jnp.asarray(rng.normal(size=(b,)).astype(np.float32))
    discounts = jnp.full((t, b), 0.9, jnp.float32)
    out = vtrace(logp, logp, rewards, values, bootstrap, discounts)
    # manual n-step return
    ret = np.zeros((t + 1, b), np.float32)
    ret[t] = np.asarray(bootstrap)
    for i in reversed(range(t)):
        ret[i] = np.asarray(rewards)[i] + 0.9 * ret[i + 1]
    assert np.allclose(np.asarray(out.vs), ret[:t], atol=1e-4)


@pytest.mark.slow
def test_ppo_learns_cartpole(ray8):
    config = (PPOConfig()
              .environment(cartpole)
              .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                        rollout_fragment_length=256)
              .training(lr=3e-3, num_sgd_iter=8, sgd_minibatch_size=256,
                        entropy_coeff=0.01))
    algo = config.build()
    best = 0.0
    for i in range(12):
        result = algo.train()
        best = max(best, result.get("episode_reward_mean", 0.0))
        if best >= 120.0:
            break
    algo.stop()
    assert best >= 120.0, f"PPO failed to learn CartPole: best={best}"


@pytest.mark.slow
def test_impala_learns_cartpole(ray8):
    config = (ImpalaConfig()
              .environment(cartpole)
              .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                        rollout_fragment_length=64)
              .training(lr=4e-3, entropy_coeff=0.01))
    algo = config.build()
    best = 0.0
    for i in range(30):
        result = algo.train()
        best = max(best, result.get("episode_reward_mean", 0.0))
        if best >= 100.0:
            break
    algo.stop()
    assert best >= 100.0, f"IMPALA failed to learn CartPole: best={best}"


@pytest.mark.slow  # ~31s; duplicate coverage: tune.run wiring is tier-1
                   # in test_tune.py and Algorithm.train() keeps its
                   # tier-1 representative in the checkpoint test below
def test_algorithm_is_tunable(ray8):
    """Reference: every Algorithm inherits Tune's Trainable — tune.run(PPO)
    works (rllib/algorithms/algorithm.py:146)."""
    from ray_tpu import tune

    grid = tune.run(
        __import__("ray_tpu.rllib", fromlist=["PPO"]).PPO,
        config={"env_maker": cartpole, "num_rollout_workers": 1,
                "rollout_fragment_length": 64,
                "lr": tune.grid_search([1e-3, 3e-3])},
        stop={"training_iteration": 2}, metric="num_env_steps_sampled",
        mode="max", max_concurrent_trials=2)
    assert len(grid) == 2
    assert grid.num_errors == 0


def test_checkpoint_restore_roundtrip(ray8):
    config = (PPOConfig().environment(cartpole)
              .rollouts(num_rollout_workers=1, rollout_fragment_length=64))
    algo = config.build()
    algo.train()
    blob = algo.save()
    w_before = algo.learner_group.get_weights()
    algo2 = config.copy().build()
    algo2.restore(blob)
    w_after = algo2.learner_group.get_weights()
    import jax
    d = jax.tree.map(lambda a, b: float(np.max(np.abs(a - b))),
                     w_before, w_after)
    assert max(jax.tree.leaves(d)) < 1e-7
    algo.stop()
    algo2.stop()


def test_vector_env_autoreset():
    from ray_tpu.rllib.env import VectorEnv

    venv = VectorEnv(cartpole, 3, seed=0)
    obs = venv.vector_reset()
    assert obs.shape == (3, 4)
    for _ in range(50):  # long enough for some episode to end
        obs, rews, terms, truncs, finals, _ = venv.vector_step([0, 1, 0])
        assert obs.shape == (3, 4) and finals.shape == (3, 4)
        if (terms | truncs).any():
            break
    else:
        raise AssertionError("no episode terminated in 50 steps")
    venv.close()


def test_prioritized_replay_semantics():
    from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer
    from ray_tpu.rllib.sample_batch import SampleBatch

    buf = PrioritizedReplayBuffer(capacity=100, alpha=1.0, seed=0)
    buf.add(SampleBatch({"x": np.arange(100)}))
    # uniform priorities first; then make one item dominate
    buf.update_priorities(np.arange(100), np.full(100, 1e-6))
    buf.update_priorities(np.array([7]), np.array([1e6]))
    s = buf.sample(64, beta=0.0)
    assert (s["batch_indexes"] == 7).mean() > 0.9
    # importance weights: beta=1 gives w ∝ 1/P, normalized to max 1
    s = buf.sample(64, beta=1.0)
    assert s["weights"].max() <= 1.0 + 1e-6


def test_replay_actor_roundtrip(ray8):
    from ray_tpu.rllib.replay_buffers import ReplayActor
    from ray_tpu.rllib.sample_batch import SampleBatch

    actor = ReplayActor.remote(capacity=1000, prioritized=True)
    n = ray.get(actor.add.remote(dict(SampleBatch(
        {"x": np.arange(50, dtype=np.int64)}))))
    assert n == 50
    out = ray.get(actor.sample.remote(16))
    assert len(out["x"]) == 16
    ray.get(actor.update_priorities.remote(out["batch_indexes"],
                                           np.ones(16)))
    ray.kill(actor)


@pytest.mark.slow
def test_dqn_learns_cartpole(ray8):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment(cartpole)
              .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                        rollout_fragment_length=64)
              .training(lr=1e-3, num_steps_sampled_before_learning=500,
                        epsilon_timesteps=4000,
                        target_network_update_freq=500))
    algo = config.build()
    best = 0.0
    for _ in range(30):
        result = algo.train()
        best = max(best, result.get("episode_reward_mean", 0.0))
        if best >= 100.0:
            break
    algo.stop()
    assert best >= 100.0, f"DQN failed to learn CartPole: best={best}"


# --- ISSUE 18: distributed IMPALA (aggregators + h2d double-buffer) ---

def test_h2d_queue_double_buffer_order_and_stalls():
    """The loader thread preserves FIFO order, moves batches to device
    arrays, and counts a learner_queue_stalls when a get blocks on an
    empty device queue."""
    import jax.numpy as jnp

    from ray_tpu.rllib.impala import _HostToDeviceQueue
    from ray_tpu.train.pipeline_actors import train_stats

    base = train_stats()["learner_queue_stalls"]
    q = _HostToDeviceQueue(depth=2)
    try:
        for i in range(3):
            q.put({"x": np.full((4,), i, np.float32)})
        got = [q.get() for _ in range(3)]
        assert [int(g["x"][0]) for g in got] == [0, 1, 2]
        assert all(isinstance(g["x"], jnp.ndarray) for g in got)
        st = q.queue_stats()
        assert st["gets"] == 3
        # At least the first get raced the loader thread's h2d; every
        # stall is mirrored into the module counter.
        assert st["stalls"] == \
            train_stats()["learner_queue_stalls"] - base
    finally:
        q.stop()


def test_aggregator_matches_to_time_major(ray8):
    """_BatchAggregator.aggregate over a sample ObjectRef argument
    (payload flows over the data plane) equals the driver-side
    _to_time_major reshape exactly."""
    from ray_tpu.rllib.impala import _BatchAggregator, _to_time_major
    from ray_tpu.rllib.sample_batch import (
        ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS,
    )

    frag, n_envs, obs_dim = 5, 3, 4
    n = frag * n_envs

    @ray.remote
    def fake_sample(seed):
        rng = np.random.default_rng(seed)
        return SampleBatch({
            OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
            ACTIONS: rng.integers(0, 2, size=n).astype(np.int32),
            REWARDS: np.ones(n, np.float32),
            DONES: np.zeros(n, bool),
            LOGP: rng.normal(size=n).astype(np.float32),
            NEXT_OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        })

    flat = ray.get(fake_sample.remote(7))
    agg = _BatchAggregator.options(num_cpus=1).remote()
    got = ray.get(agg.aggregate.remote(frag, fake_sample.remote(7)),
                  timeout=120)
    want = _to_time_major(flat, frag)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])
    assert got[ACTIONS].shape == (frag, n_envs)
    ray.kill(agg)


@pytest.mark.slow
def test_impala_distributed_aggregator_path(ray8):
    """num_aggregators > 0 engages the distributed path end to end:
    time-major prep runs off-driver, the h2d double-buffer feeds the
    learner, and training still makes progress."""
    config = (ImpalaConfig()
              .environment(cartpole)
              .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                        rollout_fragment_length=16)
              .training(lr=4e-3, num_aggregators=2,
                        max_batches_per_step=4))
    algo = config.build()
    assert len(algo._aggregators) == 2 and algo._h2d is not None
    result = {}
    for _ in range(3):
        result = algo.train()
    assert result["num_env_steps_sampled"] > 0
    st = algo._h2d.queue_stats()
    assert st["gets"] > 0
    algo.stop()
