"""Multi-agent RL + offline RL (reference: rllib/env/multi_agent_env.py,
rllib/policy/sample_batch.py MultiAgentBatch, rllib/offline/)."""
import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.rllib import (
    BCConfig, CQLConfig, ImportanceSampling, JsonReader, JsonWriter,
    MARWILConfig, MultiAgentEnv, PPOConfig, SampleBatch,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS,
)


@pytest.fixture(scope="module")
def cluster():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray.shutdown()


class TwoAgentMatch(MultiAgentEnv):
    """Cooperative: each agent sees a one-hot cue and must answer with the
    matching action; both agents' rewards sum per step.  Solvable to
    reward 2.0/step."""

    N = 4
    HORIZON = 8
    agent_ids = ["a0", "a1"]

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._cues = {}

    class _Box:
        shape = (4,)

    class _Disc:
        n = 4

    observation_space = _Box()
    action_space = _Disc()

    def _obs(self):
        out = {}
        for a in self.agent_ids:
            cue = int(self._rng.integers(self.N))
            self._cues[a] = cue
            vec = np.zeros(self.N, np.float32)
            vec[cue] = 1.0
            out[a] = vec
        return out

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        self._t += 1
        rews = {a: float(action_dict[a] == self._cues[a])
                for a in self.agent_ids}
        done = self._t >= self.HORIZON
        obs = self._obs()
        terms = {a: done for a in self.agent_ids}
        terms["__all__"] = done
        truncs = {"__all__": False}
        return obs, rews, terms, truncs, {}


def test_two_policy_ppo_learns(cluster):
    cfg = (PPOConfig()
           .environment(TwoAgentMatch)
           .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
           .training(lr=3e-3, num_sgd_iter=4, sgd_minibatch_size=64)
           .multi_agent(policies={"p0": None, "p1": None},
                        policy_mapping_fn=lambda aid: "p" + aid[-1]))
    algo = cfg.build()
    first = None
    last = {}
    for i in range(12):
        last = algo.step()
        if first is None and "episode_reward_mean" in last:
            first = last["episode_reward_mean"]
    algo.cleanup()
    # Max is 16.0/episode (2 agents x 8 steps); random is ~4.
    assert last.get("episode_reward_mean", 0.0) > 9.0, (first, last)
    assert last["num_agent_steps_sampled"] == \
        2 * last["num_env_steps_sampled"]


def _logged_batches(tmp_path, n_batches=24, steps=64, good=0.8, seed=0):
    """Behavior policy: picks the correct cue-matching action with prob
    ``good``, else uniform — logged with true action probs."""
    rng = np.random.default_rng(seed)
    path = str(tmp_path / "data.json")
    w = JsonWriter(path)
    N = 4
    for _ in range(n_batches):
        cues = rng.integers(N, size=steps)
        obs = np.eye(N, dtype=np.float32)[cues]
        greedy = rng.random(steps) < good
        acts = np.where(greedy, cues, rng.integers(N, size=steps))
        p = good * (acts == cues) + (1 - good) / N
        rews = (acts == cues).astype(np.float32)
        dones = np.zeros(steps, bool)
        dones[7::8] = True  # 8-step episodes
        nxt = np.eye(N, dtype=np.float32)[rng.integers(N, size=steps)]
        w.write(SampleBatch({
            OBS: obs, ACTIONS: acts.astype(np.int32), REWARDS: rews,
            DONES: dones, LOGP: np.log(p).astype(np.float32),
            NEXT_OBS: nxt,
        }))
    w.close()
    return path


def test_bc_learns_from_logged_data(tmp_path):
    path = _logged_batches(tmp_path)
    algo = (BCConfig()
            .offline_data(input_path=path, num_batches_per_step=12)
            .training(lr=1e-2)
            .build())
    for _ in range(10):
        m = algo.step()
    assert m["bc_loss"] < 0.9, m
    obs = np.eye(4, dtype=np.float32)
    acts = algo.compute_actions(obs)
    # The behavior policy mostly matches the cue; BC must clone that.
    assert (acts == np.arange(4)).mean() >= 0.75, acts


def test_marwil_beats_behavior(tmp_path):
    path = _logged_batches(tmp_path, good=0.6)
    algo = (MARWILConfig()
            .offline_data(input_path=path, num_batches_per_step=12)
            .training(lr=1e-2, beta=1.0)
            .build())
    for _ in range(12):
        algo.step()
    obs = np.eye(4, dtype=np.float32)
    acts = algo.compute_actions(obs)
    assert (acts == np.arange(4)).mean() >= 0.75, acts


def test_cql_learns_q_from_logged_data(tmp_path):
    path = _logged_batches(tmp_path, good=0.7)
    algo = (CQLConfig()
            .offline_data(input_path=path, num_batches_per_step=12)
            .training(lr=1e-2, min_q_weight=1.0)
            .build())
    for _ in range(12):
        m = algo.step()
    obs = np.eye(4, dtype=np.float32)
    acts = algo.compute_actions(obs)
    assert (acts == np.arange(4)).mean() >= 0.75, (acts, m)


def test_is_wis_estimators(tmp_path):
    """Target = always-correct policy; behavior = 70% correct.  IS/WIS
    must estimate the target's value ABOVE the behavior value."""
    path = _logged_batches(tmp_path, good=0.7, n_batches=40)
    batch = JsonReader(path, shuffle=False).read_all()

    def target_logp(obs, actions):
        cue = np.argmax(obs, axis=-1)
        # near-deterministic correct policy
        p = np.where(actions == cue, 0.97, 0.01)
        return np.log(p)

    is_est = ImportanceSampling(target_logp, gamma=1.0).estimate(batch)
    wis_est = WeightedImportanceSampling(target_logp,
                                         gamma=1.0).estimate(batch)
    assert is_est["episodes"] > 100
    # Behavior: P(correct) = 0.7 + 0.3/4 = 0.775 -> ~6.2 per 8-step
    # episode; target ~7.8.
    assert is_est["v_behavior"] == pytest.approx(6.2, abs=0.5)
    assert wis_est["v_target"] > wis_est["v_behavior"]
    assert is_est["v_target"] > is_est["v_behavior"]
    assert wis_est["v_gain"] > 1.05
