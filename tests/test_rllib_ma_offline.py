"""Multi-agent RL + offline RL (reference: rllib/env/multi_agent_env.py,
rllib/policy/sample_batch.py MultiAgentBatch, rllib/offline/)."""
import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.rllib import (
    BCConfig, CQLConfig, ImportanceSampling, JsonReader, JsonWriter,
    MARWILConfig, MultiAgentEnv, PPOConfig, SampleBatch,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS,
)


@pytest.fixture(scope="module")
def cluster():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray.shutdown()


class TwoAgentMatch(MultiAgentEnv):
    """Cooperative: each agent sees a one-hot cue and must answer with the
    matching action; both agents' rewards sum per step.  Solvable to
    reward 2.0/step."""

    N = 4
    HORIZON = 8
    agent_ids = ["a0", "a1"]

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._cues = {}

    class _Box:
        shape = (4,)

    class _Disc:
        n = 4

    observation_space = _Box()
    action_space = _Disc()

    def _obs(self):
        out = {}
        for a in self.agent_ids:
            cue = int(self._rng.integers(self.N))
            self._cues[a] = cue
            vec = np.zeros(self.N, np.float32)
            vec[cue] = 1.0
            out[a] = vec
        return out

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        self._t += 1
        rews = {a: float(action_dict[a] == self._cues[a])
                for a in self.agent_ids}
        done = self._t >= self.HORIZON
        obs = self._obs()
        terms = {a: done for a in self.agent_ids}
        terms["__all__"] = done
        truncs = {"__all__": False}
        return obs, rews, terms, truncs, {}


@pytest.mark.slow  # ~8s of PPO convergence; the "X learns" battery is
# slow-tier by convention (test_rllib.py) — multi-agent ROLLOUT
# mechanics keep sub-second tier-1 coverage via the turn-based reward
# tests below, and PPO wiring via test_rllib's checkpoint roundtrip.
def test_two_policy_ppo_learns(cluster):
    cfg = (PPOConfig()
           .environment(TwoAgentMatch)
           .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
           .training(lr=3e-3, num_sgd_iter=4, sgd_minibatch_size=64)
           .multi_agent(policies={"p0": None, "p1": None},
                        policy_mapping_fn=lambda aid: "p" + aid[-1]))
    algo = cfg.build()
    first = None
    last = {}
    for i in range(12):
        last = algo.step()
        if first is None and "episode_reward_mean" in last:
            first = last["episode_reward_mean"]
    algo.cleanup()
    # Max is 16.0/episode (2 agents x 8 steps); random is ~4.
    assert last.get("episode_reward_mean", 0.0) > 9.0, (first, last)
    assert last["num_agent_steps_sampled"] == \
        2 * last["num_env_steps_sampled"]


def _logged_batches(tmp_path, n_batches=24, steps=64, good=0.8, seed=0):
    """Behavior policy: picks the correct cue-matching action with prob
    ``good``, else uniform — logged with true action probs."""
    rng = np.random.default_rng(seed)
    path = str(tmp_path / "data.json")
    w = JsonWriter(path)
    N = 4
    for _ in range(n_batches):
        cues = rng.integers(N, size=steps)
        obs = np.eye(N, dtype=np.float32)[cues]
        greedy = rng.random(steps) < good
        acts = np.where(greedy, cues, rng.integers(N, size=steps))
        p = good * (acts == cues) + (1 - good) / N
        rews = (acts == cues).astype(np.float32)
        dones = np.zeros(steps, bool)
        dones[7::8] = True  # 8-step episodes
        nxt = np.eye(N, dtype=np.float32)[rng.integers(N, size=steps)]
        w.write(SampleBatch({
            OBS: obs, ACTIONS: acts.astype(np.int32), REWARDS: rews,
            DONES: dones, LOGP: np.log(p).astype(np.float32),
            NEXT_OBS: nxt,
        }))
    w.close()
    return path


def test_bc_learns_from_logged_data(tmp_path):
    path = _logged_batches(tmp_path)
    algo = (BCConfig()
            .offline_data(input_path=path, num_batches_per_step=12)
            .training(lr=1e-2)
            .build())
    for _ in range(10):
        m = algo.step()
    assert m["bc_loss"] < 0.9, m
    obs = np.eye(4, dtype=np.float32)
    acts = algo.compute_actions(obs)
    # The behavior policy mostly matches the cue; BC must clone that.
    assert (acts == np.arange(4)).mean() >= 0.75, acts


def test_marwil_beats_behavior(tmp_path):
    path = _logged_batches(tmp_path, good=0.6)
    algo = (MARWILConfig()
            .offline_data(input_path=path, num_batches_per_step=12)
            .training(lr=1e-2, beta=1.0)
            .build())
    for _ in range(12):
        algo.step()
    obs = np.eye(4, dtype=np.float32)
    acts = algo.compute_actions(obs)
    assert (acts == np.arange(4)).mean() >= 0.75, acts


def test_cql_learns_q_from_logged_data(tmp_path):
    path = _logged_batches(tmp_path, good=0.7)
    algo = (CQLConfig()
            .offline_data(input_path=path, num_batches_per_step=12)
            .training(lr=1e-2, min_q_weight=1.0)
            .build())
    for _ in range(12):
        m = algo.step()
    obs = np.eye(4, dtype=np.float32)
    acts = algo.compute_actions(obs)
    assert (acts == np.arange(4)).mean() >= 0.75, (acts, m)


def test_is_wis_estimators(tmp_path):
    """Target = always-correct policy; behavior = 70% correct.  IS/WIS
    must estimate the target's value ABOVE the behavior value."""
    path = _logged_batches(tmp_path, good=0.7, n_batches=40)
    batch = JsonReader(path, shuffle=False).read_all()

    def target_logp(obs, actions):
        cue = np.argmax(obs, axis=-1)
        # near-deterministic correct policy
        p = np.where(actions == cue, 0.97, 0.01)
        return np.log(p)

    is_est = ImportanceSampling(target_logp, gamma=1.0).estimate(batch)
    wis_est = WeightedImportanceSampling(target_logp,
                                         gamma=1.0).estimate(batch)
    assert is_est["episodes"] > 100
    # Behavior: P(correct) = 0.7 + 0.3/4 = 0.775 -> ~6.2 per 8-step
    # episode; target ~7.8.
    assert is_est["v_behavior"] == pytest.approx(6.2, abs=0.5)
    assert wis_est["v_target"] > wis_est["v_behavior"]
    assert is_est["v_target"] > is_est["v_behavior"]
    assert wis_est["v_gain"] > 1.05


class TurnBasedDuel(MultiAgentEnv):
    """Strictly turn-based: exactly ONE agent acts per step (only it
    appears in the obs dict), but the env pays BOTH agents a reward on
    every step — the non-acting agent's reward arrives on a step where
    it has no entry in the action dict, the exact shape that used to be
    dropped from trajectories and episode returns."""

    HORIZON = 6
    agent_ids = ["a0", "a1"]

    class _Box:
        shape = (4,)

    class _Disc:
        n = 4

    observation_space = _Box()
    action_space = _Disc()

    def __init__(self):
        self._t = 0

    def _obs_for(self, aid):
        vec = np.zeros(4, np.float32)
        vec[int(aid[-1])] = 1.0
        return {aid: vec}

    def reset(self, *, seed=None):
        self._t = 0
        return self._obs_for("a0"), {}

    def step(self, action_dict):
        assert list(action_dict) == [self.agent_ids[self._t % 2]]
        self._t += 1
        done = self._t >= self.HORIZON
        # Acting agent earns 1.0; the OTHER (non-acting) agent earns 0.5
        # this same step — deliverable only via its last transition.
        actor = self.agent_ids[(self._t - 1) % 2]
        other = self.agent_ids[self._t % 2]
        rews = {actor: 1.0, other: 0.5}
        terms = {a: done for a in self.agent_ids}
        terms["__all__"] = done
        return (self._obs_for(self.agent_ids[self._t % 2]), rews, terms,
                {"__all__": False}, {})


def test_turn_based_rewards_credit_non_acting_agents():
    """Rewards returned for agents absent from the action dict must fold
    into their buffered last transition (trajectory) AND the episode
    return — a turn-based env's terminal rewards otherwise vanish."""
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker

    worker_cls = MultiAgentRolloutWorker._cls  # in-process, no cluster
    w = worker_cls(TurnBasedDuel,
                   {"p": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: "p", seed=0)
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP

    w.set_weights({"p": ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))})
    batch = w.sample(TurnBasedDuel.HORIZON)  # exactly one episode
    # Each step hands out 1.0 + 0.5; a0 never receives a1's final-step
    # 0.5 unless non-acting credit works.  Episode return = 6 * 1.5.
    returns = w.episode_returns()
    assert returns == [pytest.approx(TurnBasedDuel.HORIZON * 1.5)], returns
    # Trajectory-level: each agent acted HORIZON/2 times and every
    # waiting-step 0.5 landed on a transition (a1's first carries the
    # pre-first-action accrual AND the 0.5 earned right after it ->
    # 2.0; its last has no later waiting step -> 1.0).  Nothing of the
    # 9.0 total is dropped.
    b = batch["p"]
    assert len(b) == TurnBasedDuel.HORIZON  # 3 transitions per agent
    assert b[REWARDS].sum() == pytest.approx(9.0)
    np.testing.assert_allclose(np.sort(b[REWARDS]),
                               [1.0, 1.5, 1.5, 1.5, 1.5, 2.0])


def test_terminal_reward_after_horizon_flush_reaches_trajectory():
    """The sample horizon splitting an agent's last action from its
    off-turn terminal reward must not drop the reward: the horizon flush
    holds each agent's newest transition buffered, so the opponent's
    game-ending move in the NEXT sample() still credits a real
    transition (and flips its done flag) instead of evaporating with
    the episode reset."""
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker

    worker_cls = MultiAgentRolloutWorker._cls  # in-process, no cluster
    w = worker_cls(TurnBasedDuel,
                   {"p": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: "p", seed=0)
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP

    w.set_weights({"p": ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))})
    # Steps 1..5: episode NOT done; a0's last action (step 5) would be
    # flushed here, before its terminal 0.5 arrives on step 6.
    b1 = w.sample(TurnBasedDuel.HORIZON - 1)
    # Step 6: a1 acts, game ends, a0 is paid 0.5 off-turn.
    b2 = w.sample(1)
    assert w.episode_returns() == \
        [pytest.approx(TurnBasedDuel.HORIZON * 1.5)]
    # Held-back transitions ship with the terminal flush: 3 + 3 rows,
    # and the full 9.0 reaches trajectories across the two batches.
    assert len(b1["p"]) == 3 and len(b2["p"]) == 3
    total = b1["p"][REWARDS].sum() + b2["p"][REWARDS].sum()
    assert total == pytest.approx(9.0)
    # a0's held transition carries 1.0 (its action) + 0.5 (terminal,
    # off-turn) and is marked done; a1's held row stays mid-episode.
    np.testing.assert_allclose(np.sort(b2["p"][REWARDS]),
                               [1.0, 1.5, 1.5])
    assert b2["p"][DONES].sum() == 2  # a0 held + a1's acting row
    assert not b1["p"][DONES].any()


def test_turn_based_sample1_horizons_keep_terminal_rewards():
    """Turn-based detection must not depend on a buffered agent
    surviving into the next step: with sample(1) horizons every flush
    empties the buffers, so the env's declared roster (an agent absent
    from the action dict from step 1) is what flips the flag — and the
    full 9.0 still reaches trajectories across the six one-step
    batches."""
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker

    worker_cls = MultiAgentRolloutWorker._cls
    w = worker_cls(TurnBasedDuel,
                   {"p": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: "p", seed=0)
    w.set_weights({"p": ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))})
    batches = [w.sample(1) for _ in range(TurnBasedDuel.HORIZON)]
    assert w._turn_based  # roster signal: a1 absent on step 1
    assert w.episode_returns() == \
        [pytest.approx(TurnBasedDuel.HORIZON * 1.5)]
    total = sum(float(b["p"][REWARDS].sum()) for b in batches
                if "p" in b.policy_batches)
    rows = sum(len(b["p"]) for b in batches
               if "p" in b.policy_batches)
    assert rows == TurnBasedDuel.HORIZON
    assert total == pytest.approx(9.0)


def test_simultaneous_env_horizon_flush_holds_nothing():
    """hold_last is gated on turn-based dynamics: a simultaneous-action
    env (every agent acts every step, off-turn rewards impossible) keeps
    the flush-everything horizon path — sample(1) returns both agents'
    transitions immediately, never an empty batch nor a one-transition
    training lag."""
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker

    worker_cls = MultiAgentRolloutWorker._cls
    w = worker_cls(TwoAgentMatch,
                   {"p": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: "p", seed=0)
    w.set_weights({"p": ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))})
    b = w.sample(1)  # horizon cut after one simultaneous step
    assert len(b["p"]) == 2  # one transition per agent, nothing held


class RosterlessDuel(TurnBasedDuel):
    """Turn-based like the parent but (a) declares no ``agent_ids``
    roster and (b) pays the off-turn agent ONLY at game end — so
    neither the env's roster nor an early off-turn reward can flip the
    turn-based flag; only the seen-agents fallback can."""

    agent_ids = ()
    _CAST = ("a0", "a1")

    def step(self, action_dict):
        self._t += 1
        done = self._t >= self.HORIZON
        actor = self._CAST[(self._t - 1) % 2]
        rews = {actor: 1.0}
        if done:
            rews[self._CAST[self._t % 2]] = 3.0  # terminal, off-turn
        terms = {a: done for a in self._CAST}
        terms["__all__"] = done
        return (self._obs_for(self._CAST[self._t % 2]), rews, terms,
                {"__all__": False}, {})


def test_rosterless_env_seen_agents_fallback_keeps_terminal_reward():
    """Without a declared roster, agents OBSERVED this episode form the
    fallback roster: a0 sitting out step 2 flips the flag, so the
    horizon flush before the final step holds its newest transition and
    the off-turn terminal 3.0 still reaches a trajectory."""
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker

    worker_cls = MultiAgentRolloutWorker._cls
    w = worker_cls(RosterlessDuel,
                   {"p": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: "p", seed=0)
    w.set_weights({"p": ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))})
    b1 = w.sample(RosterlessDuel.HORIZON - 1)
    assert w._turn_based  # flipped by the seen-agents roster at step 2
    b2 = w.sample(1)
    assert w.episode_returns() == [pytest.approx(9.0)]  # 6x1.0 + 3.0
    total = sum(float(b["p"][REWARDS].sum()) for b in (b1, b2)
                if "p" in b.policy_batches)
    assert total == pytest.approx(9.0)


def test_turn_based_truncation_bootstraps_off_turn_agents():
    """Time-limit truncation mid-game: the off-turn agent (absent from
    the final obs dict) must bootstrap from its last recorded value
    prediction, not a flat 0.0 that biases its advantages."""
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker
    from ray_tpu.rllib.sample_batch import ADVANTAGES, VF_PREDS

    class TruncatedDuel(TurnBasedDuel):
        def step(self, action_dict):
            obs, rews, terms, truncs, info = super().step(action_dict)
            if self._t >= 3:  # time limit BEFORE the game decides
                terms = {a: False for a in terms}
                truncs = {"__all__": True}
            return obs, rews, terms, truncs, info

    worker_cls = MultiAgentRolloutWorker._cls
    gamma = 0.97
    w = worker_cls(TruncatedDuel,
                   {"a0": {"obs_dim": 4, "num_actions": 4},
                    "a1": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: aid, seed=0, gamma=gamma)
    params = ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))
    w.set_weights({"a0": params, "a1": params})
    b = w.sample(3)  # steps 1-3; truncation after step 3, a0 off-turn
    a0 = b["a0"]
    # Last-row GAE delta with the VF bootstrap: r + g*vf - vf (done
    # False); with the old 0.0 bootstrap it would be r - vf.
    r, vf, adv = (float(a0[REWARDS][-1]), float(a0[VF_PREDS][-1]),
                  float(a0[ADVANTAGES][-1]))
    assert adv == pytest.approx(r + gamma * vf - vf, abs=1e-5)


class EarlyDropout(MultiAgentEnv):
    """Simultaneous-action env where a1 terminates on step 1 while the
    episode (and a0) continues: a1 then sits in the buffers without
    acting, which must NOT read as turn-based dynamics — its trajectory
    is done, not waiting a turn."""

    HORIZON = 4
    agent_ids = ["a0", "a1"]

    class _Box:
        shape = (4,)

    class _Disc:
        n = 4

    observation_space = _Box()
    action_space = _Disc()

    def __init__(self):
        self._t = 0

    def _obs(self, agents):
        return {a: np.zeros(4, np.float32) for a in agents}

    def reset(self, *, seed=None):
        self._t = 0
        return self._obs(self.agent_ids), {}

    def step(self, action_dict):
        self._t += 1
        done = self._t >= self.HORIZON
        rews = {a: 1.0 for a in action_dict}
        terms = {"a0": done, "a1": True, "__all__": done}
        live = ["a0"] if not done else []
        return self._obs(live), rews, terms, {"__all__": False}, {}


def test_early_terminated_agent_does_not_mark_turn_based():
    """An agent that terminated early in a simultaneous env must not
    flip the sticky turn-based flag: horizon flushes keep shipping every
    transition immediately (no hold-back lag) because no off-turn reward
    can ever arrive for a finished agent."""
    import jax

    from ray_tpu.rllib.models import ActorCriticMLP
    from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker

    worker_cls = MultiAgentRolloutWorker._cls
    w = worker_cls(EarlyDropout,
                   {"p": {"obs_dim": 4, "num_actions": 4}},
                   lambda aid: "p", seed=0)
    w.set_weights({"p": ActorCriticMLP(obs_dim=4, num_actions=4).init(
        jax.random.PRNGKey(0))})
    # Steps 1-2: a1 dies on step 1, a0 plays on.  The horizon flush
    # after step 2 must ship ALL three transitions (a0 x2 + a1 x1).
    b1 = w.sample(2)
    assert not w._turn_based
    assert len(b1["p"]) == 3
    # Steps 3-4 end the episode; every step paid 1.0 per acting agent.
    w.sample(2)
    assert w.episode_returns() == [pytest.approx(5.0)]
