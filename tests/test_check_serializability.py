"""check_serializability / find_unserializable tests, plus the @remote
error-path wiring: a pickling failure at submit must name the exact
non-serializable leaf with its path."""

import threading

import pytest

import ray_tpu as ray
from ray_tpu.devtools.serializability import (
    SerializationTrapError,
    check_serializability,
    find_unserializable,
)
from ray_tpu.util import check_serializability as util_export


def test_exported_via_ray_tpu_util():
    # Reference parity surface: ray.util.check_serializability.
    assert util_export is check_serializability


def test_clean_objects_pass():
    assert check_serializability({"a": [1, "x", (2.0, None)]}) is None
    assert find_unserializable([1, 2, 3]) is None


def test_closure_capture_path():
    model = threading.Lock()  # classic unpicklable leaf

    def train(x):
        return model, x

    path, leaf, err = find_unserializable(train, "train")
    assert path == "train.__closure__['model']"
    assert leaf is model
    assert isinstance(err, TypeError)


def test_nested_container_path():
    bad = {"cfg": [1, {"sock": threading.Lock()}]}
    path, leaf, _err = find_unserializable(bad, "obj")
    assert path == "obj['cfg'][1]['sock']"


def test_attribute_path():
    class Holder:
        def __init__(self):
            self.name = "h"
            self.state = {"inner": threading.Lock()}

    path, _leaf, _err = find_unserializable(Holder(), "holder")
    assert path == "holder.state['inner']"


def test_check_raises_with_path_and_remedy():
    with pytest.raises(SerializationTrapError) as info:
        check_serializability({"model": threading.Lock()}, "obj")
    message = str(info.value)
    assert "obj['model']" in message
    assert "lock" in message.lower()
    assert info.value.path == "obj['model']"


def test_trap_error_is_typeerror_and_picklable():
    err = SerializationTrapError("obj.x", "<lock>", "TypeError(...)")
    assert isinstance(err, TypeError)
    import pickle

    clone = pickle.loads(pickle.dumps(err))
    assert clone.path == "obj.x"


def test_failed_submit_frees_earlier_arg_segments(ray_start_regular):
    """A later arg failing to pickle must not leak the shm segments
    already written for earlier (large) args — the spec is never
    submitted, so the normal task-end free never runs."""
    import numpy as np

    rt = ray_start_regular

    @ray.remote
    def f(x, y):
        return x

    big = np.zeros(1 << 20, dtype=np.uint8)  # well past max_inline
    before = set(rt.shm._created)
    for _ in range(3):
        with pytest.raises(SerializationTrapError):
            f.remote(big, threading.Lock())
    assert set(rt.shm._created) == before, (
        "failed submits leaked shm segments")


def test_failed_submit_frees_spill_files_without_shm_acct(tmp_path):
    """Store-full args spill to DISK paths; the failed-submit cleanup
    must plain-unlink those, not route them through ShmStore.unlink
    (which would debit node-shared shm accounting for bytes never
    charged to it)."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.shm_store import ShmStore
    from ray_tpu.remote_function import serialize_args

    store = ShmStore(shm_dir=str(tmp_path), session_id="spilltest",
                     capacity=1 << 30)
    # Charge real bytes first so a bogus debit would be visible.
    store.create_from_parts(ObjectID.from_random(), b"m",
                            [memoryview(b"x" * 4096)])
    charged = store._node_used()
    assert charged > 0
    spill = tmp_path / "spill-seg"
    spill.write_bytes(b"y" * 1024)

    class StubRT:
        shm = store

        def begin_ref_collection(self):
            pass

        def end_ref_collection(self):
            return []

        def serialize_value(self, value, oid):
            if value == "big":
                return ("spilled", str(spill), 1024, "store-1")
            raise TypeError("cannot pickle _thread.lock")

    with pytest.raises(SerializationTrapError):
        serialize_args(StubRT(), ["big", threading.Lock()], {}, {})
    assert not spill.exists(), "spill file leaked by failed submit"
    assert store._node_used() == charged, "shm accounting was debited"
    store.cleanup()


def test_devtools_not_imported_by_default():
    """`import ray_tpu` keeps devtools off the import path (it loads
    lazily on ray_tpu.util.check_serializability use or under
    RAY_TPU_LOCKCHECK); guards the laziness the error-path imports rely
    on."""
    import subprocess
    import sys

    code = (
        "import ray_tpu, sys;"
        "assert 'ray_tpu.devtools' not in sys.modules, 'eager devtools';"
        "from ray_tpu.util import check_serializability;"
        "assert 'ray_tpu.devtools.serializability' in sys.modules;"
        "print('LAZY_OK')"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "LAZY_OK" in proc.stdout


def test_remote_submit_failures_name_leaf(ray_start_regular):
    """One runtime boot covers the three @remote wiring paths: positional
    arg, kwarg, and the function payload's own closure."""
    @ray.remote
    def f(x, y=None):
        return x

    class Config:
        def __init__(self):
            self.lr = 0.1
            self.lock = threading.Lock()

    with pytest.raises(SerializationTrapError) as info:
        f.remote(1, Config())
    assert info.value.path == "arg[1].lock"

    with pytest.raises(SerializationTrapError) as info:
        f.remote(x=Config())
    assert info.value.path == "kwargs['x'].lock"

    resource = threading.Lock()

    @ray.remote
    def uses_resource():
        return resource

    with pytest.raises(SerializationTrapError) as info:
        uses_resource.remote()
    assert info.value.path == "uses_resource.__closure__['resource']"
