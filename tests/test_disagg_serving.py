"""Disaggregated prefill/decode serving: KV-chain streaming over the
striped put path, prefix-affinity routing, per-pool observability, and
the chaos battery.

The battery pins the ISSUE acceptance contract: with the
``disaggregated_serving`` knob on, one serve.run deploys a prefill twin
pool behind the logical name, prefill replicas export finished KV-block
chains as segment images streamed into the decode replica's node store
(counted in ``kv_chain_bytes_streamed``), decode replicas adopt the
blocks under their own allocator, and every decoded chain stays bitwise
the host reference.  With the knob OFF the deployment is the
byte-identical monolithic engine and every new counter is pinned zero.
A killed prefill replica re-prefills on a healthy pool member; a killed
decode replica leaks nothing prefill-side.  The pool autoscaler raises
a prefill pool on admission-park growth and a decode pool on
tokens_per_step saturation, via the controller metric windows.
"""

import time

import pytest

import ray_tpu as ray
from ray_tpu import serve
from ray_tpu.serve.api import CONTROLLER_NAME, PREFILL_SUFFIX

DISAGG_CONF = {"paged_kv": True, "disaggregated_serving": True}


def _deploy(name, *, prefill_replicas=1, num_replicas=1, **dep_kw):
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    dep = serve.deployment(MeshShardedDecoder, name=name,
                           max_concurrency=16,
                           num_replicas=num_replicas,
                           prefill_replicas=prefill_replicas, **dep_kw)
    return serve.run(dep.bind(), name=name)


def _pool_reps(name):
    """Live replica ActorHandles of a (possibly twin) deployment."""
    ctrl = ray.get_actor(CONTROLLER_NAME)
    _ver, reps, _inc = ray.get(ctrl.handle_snapshot.remote(name))
    return reps


def _kv_debug(rep):
    return ray.get(rep.call_method.remote("kv_debug", (), {}))


# -- the tentpole e2e: split pools, streamed chains, bitwise output ---------

def test_disagg_e2e_bitwise_streamed_chains_and_pool_rollup():
    """One serve.run under the knob => prefill twin + decode pool; every
    response is bitwise the host reference (imported page rows ARE the
    recomputed prefill rows); the chain counters move and the bytes ride
    the put path; serving_stats rolls the pools up per role and folds
    the twin into the logical name."""
    ray.init(num_cpus=6, _system_config=DISAGG_CONF)
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        handle = _deploy("disagg")
        shared = list(range(16))                 # 2 shared prefix blocks
        reqs = [{"prompt": shared + [i], "tokens": 1 + i % 5}
                for i in range(10)]
        outs = ray.get([handle.remote(r) for r in reqs], timeout=120)
        ref = MeshShardedDecoder()
        for r, out in zip(reqs, outs):
            assert out == ref.reference_decode(r["prompt"], r["tokens"])
        stats = serve.serving_stats("disagg")
        # Chain handoff counters: one export + one import per request,
        # bytes > 0 (pages crossed as a streamed segment, not inline).
        assert stats["kv_chains_exported"] >= len(reqs)
        assert stats["kv_chains_imported"] >= len(reqs)
        assert stats["kv_chain_bytes_streamed"] > 0
        # Per-pool rollup: the twin folded under the logical name.
        assert set(stats["pools"]) == {"prefill", "decode"}
        assert stats["pools"]["prefill"]["replicas"] == 1
        assert stats["pools"]["decode"]["replicas"] == 1
        assert stats["prefill_replicas"] == 1
        for pool in stats["pools"].values():
            assert "admission_parks" in pool
            assert "tokens_per_step" in pool
        # Decode emitted every token; prefill emitted none (prompt-only
        # steps finish before the emit phase).
        assert stats["pools"]["prefill"]["tokens_emitted"] == 0
        assert stats["pools"]["decode"]["tokens_emitted"] == \
            sum(r["tokens"] for r in reqs)
        # The shared prefix paid off router-side and the global rollup
        # carries the router counters.
        rs = handle.router_stats()
        assert rs["router_prefix_hits"] > 0
        agg = serve.serving_stats()
        assert agg["_router"]["prefix_hits"] == rs["router_prefix_hits"]
        # No unreleased exports once idle (blocks still resident belong
        # to the PrefixCache — deliberate retention, not a leak; the
        # chaos battery pins used==0 with caching off).
        for rep in _pool_reps("disagg" + PREFILL_SUFFIX):
            dbg = _kv_debug(rep)
            assert dbg["role"] == "prefill"
            assert dbg["exports_outstanding"] == 0
    finally:
        serve.shutdown()
        ray.shutdown()


def test_prefix_affinity_beats_random_routing():
    """Acceptance: affinity routing lands shared-prefix prompts on the
    prefill replica that already holds the chain — its engine-level
    prefix_hits must sit STRICTLY above the affinity-off (p2c/random)
    baseline on the identical workload, and the router's own hit
    counter only moves when affinity is on."""
    def run(affinity):
        ray.init(num_cpus=8, _system_config={
            **DISAGG_CONF, "prefix_affinity": affinity})
        try:
            handle = _deploy("aff", prefill_replicas=2)
            families = [list(range(100, 116)), list(range(200, 216)),
                        list(range(300, 316))]
            reqs = [{"prompt": fam + [i], "tokens": 2}
                    for i in range(6) for fam in families]
            # Serialized on purpose: a family's second request must
            # not race the first one's prefix registration, and both
            # pools hold all three families, so a concurrent burst
            # makes BOTH sides' hit counts schedule-dependent.
            for r in reqs:
                ray.get(handle.remote(r), timeout=120)
            hits = sum(_kv_debug(r)["prefix_hits"]
                       for r in _pool_reps("aff" + PREFILL_SUFFIX))
            return hits, handle.router_stats()
        finally:
            serve.shutdown()
            ray.shutdown()

    aff_hits, aff_router = run(True)
    rnd_hits, rnd_router = run(False)
    assert aff_router["router_prefix_hits"] > 0
    assert rnd_router["router_prefix_hits"] == 0
    assert aff_hits > rnd_hits, (aff_hits, rnd_hits)


# -- the off switch ---------------------------------------------------------

def test_disagg_off_monolithic_byte_identical_zero_counters():
    """Knob off (the default): the SAME deployment call is the
    monolithic paged engine — no twin deployment exists, the handle
    never diverts, outputs match the host reference bitwise, and every
    disaggregation counter (engine chain counters, router affinity
    counters, pool split) is pinned zero/absent."""
    ray.init(num_cpus=4, _system_config={"paged_kv": True})
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        handle = _deploy("mono")
        shared = list(range(16))
        reqs = [{"prompt": shared + [i], "tokens": 1 + i % 5}
                for i in range(10)]
        outs = ray.get([handle.remote(r) for r in reqs], timeout=120)
        ref = MeshShardedDecoder()
        for r, out in zip(reqs, outs):
            assert out == ref.reference_decode(r["prompt"], r["tokens"])
        stats = serve.serving_stats("mono")
        assert stats["kv_chains_exported"] == 0
        assert stats["kv_chains_imported"] == 0
        assert stats["kv_chain_bytes_streamed"] == 0
        assert set(stats["pools"]) == {"all"}
        assert "prefill_replicas" not in stats
        assert not handle._disagg
        rs = handle.router_stats()
        assert rs == {"router_prefix_hits": 0, "router_prefix_misses": 0}
        agg = serve.serving_stats()
        assert agg["_router"] == {"prefix_hits": 0, "prefix_misses": 0}
        ctrl = ray.get_actor(CONTROLLER_NAME)
        deps = ray.get(ctrl.list_deployments.remote())
        assert not any(n.endswith(PREFILL_SUFFIX) for n in deps), deps
        # Monolithic replica never exported/imported: no handoff state.
        (rep,) = _pool_reps("mono")
        dbg = _kv_debug(rep)
        assert dbg["chain"] == {"inline_fallbacks": 0,
                                "handoff_retries": 0}
        assert dbg["role"] is None
    finally:
        serve.shutdown()
        ray.shutdown()


def test_disagg_knobs_ride_worker_config_env():
    """The three knobs probe through _worker_config_env (the dict BOTH
    spawn paths consume — RTL504 keeps that invariant) so replica and
    controller workers rebuild the driver's _system_config from env."""
    from ray_tpu._private import api_internal

    ray.init(num_cpus=2, _system_config={
        "disaggregated_serving": True,
        "kv_stream_stripe_threshold": 12345,
        "prefix_affinity": False})
    try:
        rt = api_internal.get_runtime()
        env = rt._worker_config_env()
        assert env["RAY_TPU_DISAGGREGATED_SERVING"] == "1"
        assert env["RAY_TPU_KV_STREAM_STRIPE_THRESHOLD"] == "12345"
        assert env["RAY_TPU_PREFIX_AFFINITY"] == "0"

        @ray.remote
        def probe():
            from ray_tpu._private.config import GLOBAL_CONFIG
            return (GLOBAL_CONFIG.disaggregated_serving,
                    GLOBAL_CONFIG.kv_stream_stripe_threshold,
                    GLOBAL_CONFIG.prefix_affinity)

        assert ray.get(probe.remote(), timeout=60) == (True, 12345, False)
    finally:
        ray.shutdown()


# -- chaos ------------------------------------------------------------------

def test_chaos_killed_prefill_replica_reprefills_on_healthy_pool():
    """Kill one of two prefill replicas, then hand its (dead) handle to
    disagg_generate: the decode side's retry re-fetches the pool from
    the controller and re-prefills on the healthy member — the request
    completes bitwise-correct and the retry is counted.  (Any half-
    received chain on the decode node was aborted by the put path's
    reserving-connection-close cleanup, so the retry starts clean.)"""
    ray.init(num_cpus=8, _system_config=DISAGG_CONF)
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        handle = _deploy("chaosp", prefill_replicas=2)
        # Warm both pools up on the normal path first.
        warm = {"prompt": list(range(8)), "tokens": 2}
        ray.get(handle.remote(warm), timeout=60)
        pre = _pool_reps("chaosp" + PREFILL_SUFFIX)
        assert len(pre) == 2
        (dec,) = _pool_reps("chaosp")
        ray.kill(pre[0])
        body = {"prompt": list(range(40, 52)), "tokens": 3}
        out = ray.get(dec.call_method.remote(
            "disagg_generate", (body, pre[0], "chaosp" + PREFILL_SUFFIX),
            {}), timeout=60)
        ref = MeshShardedDecoder()
        assert out == ref.reference_decode(body["prompt"], body["tokens"])
        assert _kv_debug(dec)["chain"]["handoff_retries"] >= 1
        # The router path keeps serving through the death too (the
        # controller replaces the replica; the handle long-poll and the
        # in-call retry cover the gap).
        reqs = [{"prompt": list(range(60, 70)) + [i], "tokens": 2}
                for i in range(4)]
        outs = ray.get([handle.remote(r) for r in reqs], timeout=120)
        for r, o in zip(reqs, outs):
            assert o == ref.reference_decode(r["prompt"], r["tokens"])
    finally:
        serve.shutdown()
        ray.shutdown()


def test_chaos_killed_decode_replica_leaks_nothing_prefill_side():
    """Kill the decode replica after it adopted streamed chains: the
    prefill pool's allocator must sit back at baseline (exports are
    released at handoff completion, not decode retirement — a dead
    importer cannot pin exporter blocks), and the deployment keeps
    serving once the controller replaces the replica.  Prefix caching
    is OFF here so the prefill baseline is exactly zero blocks (with it
    on, the cache deliberately retains chain blocks for reuse)."""
    ray.init(num_cpus=8, _system_config={
        **DISAGG_CONF, "prefix_caching": False})
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        handle = _deploy("chaosd")
        reqs = [{"prompt": list(range(16)) + [i], "tokens": 2}
                for i in range(6)]
        outs = ray.get([handle.remote(r) for r in reqs], timeout=120)
        ref = MeshShardedDecoder()
        for r, o in zip(reqs, outs):
            assert o == ref.reference_decode(r["prompt"], r["tokens"])
        (dec,) = _pool_reps("chaosd")
        ray.kill(dec)
        (pre,) = _pool_reps("chaosd" + PREFILL_SUFFIX)
        dbg = _kv_debug(pre)
        assert dbg["exports_outstanding"] == 0
        assert dbg["used"] == 0, dbg
        # Recovery: the controller replaces the dead decode replica and
        # fresh requests complete (retry until the replacement lands).
        deadline = time.monotonic() + 60
        out = None
        body = {"prompt": list(range(16)) + [99], "tokens": 2}
        while time.monotonic() < deadline:
            try:
                out = ray.get(handle.remote(body), timeout=30)
                break
            except Exception:
                time.sleep(0.5)
        assert out == ref.reference_decode(body["prompt"], body["tokens"])
    finally:
        serve.shutdown()
        ray.shutdown()


# -- independent pool autoscaling -------------------------------------------

def test_pool_autoscaler_parks_grow_prefill_and_tps_grows_decode():
    """Pool-saturation scaling rides the controller metric windows
    (record_pool_metric is public precisely so tests can drive the
    scaler without real traffic): a GROWING admission_parks window
    raises the prefill pool, a tokens_per_step peak at/above the
    configured target raises the decode pool — both within their
    autoscaling_config max."""
    ray.init(num_cpus=10, _system_config=DISAGG_CONF)
    try:
        _deploy("scale", autoscaling_config={
            "min_replicas": 1, "max_replicas": 2,
            "target_ongoing_requests": 1000,   # ongoing never triggers
            "scale_on_parks": True,
            "target_tokens_per_step": 4.0})
        ctrl = ray.get_actor(CONTROLLER_NAME)
        twin = "scale" + PREFILL_SUFFIX
        assert len(_pool_reps("scale")) == 1
        assert len(_pool_reps(twin)) == 1
        # Prefill: parks grew inside the look-back window.
        ray.get(ctrl.record_pool_metric.remote(
            twin, "admission_parks", 0))
        ray.get(ctrl.record_pool_metric.remote(
            twin, "admission_parks", 5))
        # Decode: tokens_per_step peaked at the saturation target.
        ray.get(ctrl.record_pool_metric.remote(
            "scale", "tokens_per_step", 4.5))
        # Keep feeding the windows while we wait: the look-back is
        # short (serve_metric_lookback_s) and a reconcile tick that is
        # busy spawning the decode replica can outlive a one-shot
        # sample — a genuinely saturated pool keeps reporting growing
        # parks, so the test does too.
        parks_v = 5
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if len(_pool_reps("scale")) == 2 and \
                    len(_pool_reps(twin)) == 2:
                break
            parks_v += 1
            ray.get(ctrl.record_pool_metric.remote(
                twin, "admission_parks", parks_v))
            ray.get(ctrl.record_pool_metric.remote(
                "scale", "tokens_per_step", 4.5))
            time.sleep(0.25)
        assert len(_pool_reps(twin)) == 2, "prefill pool did not scale"
        assert len(_pool_reps("scale")) == 2, "decode pool did not scale"
    finally:
        serve.shutdown()
        ray.shutdown()


# -- delete cascade ---------------------------------------------------------

def test_delete_deployment_cascades_to_prefill_twin():
    ray.init(num_cpus=6, _system_config=DISAGG_CONF)
    try:
        _deploy("gone")
        ctrl = ray.get_actor(CONTROLLER_NAME)
        deps = ray.get(ctrl.list_deployments.remote())
        assert "gone" in deps and "gone" + PREFILL_SUFFIX in deps
        ray.get(ctrl.delete_deployment.remote("gone"))
        deps = ray.get(ctrl.list_deployments.remote())
        assert "gone" not in deps
        assert "gone" + PREFILL_SUFFIX not in deps, deps
    finally:
        serve.shutdown()
        ray.shutdown()
