"""Whole-program conformance checker tests: the seeded-mutation battery
(each protocol-breaking edit to a COPY of the real tree produces exactly
the expected finding), the catalog's agreement with the shipped code and
the lockcheck-pinned leaf conventions, and the CLI contract.

The fixture-level EXPECT coverage for RTL500–505 lives in
test_devtools_lint.py (the shared harness); this file owns the
whole-tree properties."""

import os
import re
import shutil
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu._private import object_transfer, protocol
from ray_tpu.devtools import protocheck

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))


# -- catalog sanity ---------------------------------------------------------

def test_catalog_shape():
    roles = set()
    for verb, spec in protocol.VERBS.items():
        assert re.match(r"^[a-z][a-z0-9_]*$", verb), verb
        assert spec.senders and spec.handlers, verb
        roles.update(spec.senders)
        roles.update(spec.handlers)
        if spec.arity is not None:
            lo, hi = spec.arity
            assert 1 <= lo <= hi, verb
        assert spec.doc, f"{verb}: every catalog verb carries a doc line"
    assert roles <= {"head", "worker", "client", "agent", "objsrv"}


def test_catalog_caps_match_advertised_caps():
    """The verbs the catalog marks object_caps-gated are EXACTLY the
    verbs the object server advertises out of band — a new advertised
    verb must enter the catalog as gated, and vice versa."""
    gated = {v for v, spec in protocol.VERBS.items()
             if spec.caps == "object_caps"}
    assert gated == set(object_transfer.CAPS)


def test_readme_verb_table_matches_generated_doc():
    """The README says its wire-protocol table 'cannot drift from the
    code' — make that true: the pasted table must equal
    `protocheck --doc` byte-for-byte (regenerate with
    `python -m ray_tpu.devtools.protocheck --doc` after editing
    protocol.VERBS)."""
    readme = os.path.join(os.path.dirname(PKG_DIR), "README.md")
    with open(readme, "r", encoding="utf-8") as f:
        content = f.read()
    assert protocheck.catalog_doc() in content, (
        "README.md's verb table is stale — regenerate it with "
        "`python -m ray_tpu.devtools.protocheck --doc`")


def test_lock_graph_agrees_with_lockcheck_leaf_conventions():
    """Every independent-leaf convention pinned dynamically in
    test_lockcheck.py is ALSO declared statically ('# lock-order: leaf')
    where the lock is created, so RTL505 enforces it on paths the
    runtime checker never executes."""
    analysis = protocheck.Analysis([PKG_DIR])
    leaves = set()
    for mod in analysis.modules:
        base = os.path.basename(mod.path)
        for cls in mod.classes:
            for attr, (_line, leaf) in cls.lock_attrs.items():
                if leaf:
                    leaves.add((base, cls.name, attr))
        for name, (_line, leaf) in mod.module_locks.items():
            if leaf:
                leaves.add((base, None, name))
    expected = {
        ("object_transfer.py", "PullRegistry", "_lock"),
        ("object_transfer.py", "PutRegistry", "_lock"),
        ("object_transfer.py", "_PoolHost", "_lock"),
        ("recovery.py", "LineageTable", "_lock"),
        ("runtime.py", "Runtime", "_dispatch_dirty_lock"),
        ("streaming_executor.py", "StreamingStats", "_lock"),
        ("batching.py", "_Batcher", "_lock"),
        ("continuous.py", "_ContinuousBatcher", "_lock"),
        ("shm_store.py", "ShmStore", "_lock"),
        ("shm_store.py", None, "_copy_pool_lock"),
        ("shuffle.py", None, "_STATS_LOCK"),
    }
    missing = expected - leaves
    assert not missing, (
        f"lockcheck-pinned leaves without a static '# lock-order: leaf' "
        f"annotation: {sorted(missing)}")


# -- seeded mutations -------------------------------------------------------

def _mutate(pkg: str, rel: str, old: str, new: str):
    path = os.path.join(pkg, rel)
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    assert old in src, f"mutation anchor vanished from {rel}: {old!r}"
    with open(path, "w", encoding="utf-8") as f:
        f.write(src.replace(old, new, 1))
    return path, src


def test_seeded_mutations_each_produce_the_expected_finding(tmp_path):
    """The acceptance battery: deleting one handler arm, widening one
    sender tuple, dropping one caps guard, and removing one knob from
    _worker_config_env each produce exactly the expected finding class
    on an otherwise-clean copy of the shipped tree."""
    pkg = str(tmp_path / "ray_tpu")
    shutil.copytree(PKG_DIR, pkg,
                    ignore=shutil.ignore_patterns("__pycache__"))
    assert protocheck.check_paths([pkg]) == [], \
        "the copied tree must be clean before any mutation"

    def run():
        return protocheck.check_paths([pkg])

    # 1. Delete a handler arm: the lease_renew verb loses its only head
    #    handler -> RTL501 missing-handler anchored at a sender.
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        'elif tag == "lease_renew":', 'elif tag == "lease_renew_gone":')
    findings = run()
    assert any(f.rule == "RTL501" and "lease_renew" in f.message
               and "handles it" in f.message for f in findings), findings
    # (The renamed arm itself is also flagged as an unknown verb.)
    assert any(f.rule == "RTL501" and "lease_renew_gone" in f.message
               for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 2. Widen a sender tuple beyond the catalog arity -> RTL502 at the
    #    send site.
    path, orig = _mutate(
        pkg, "_private/worker_main.py",
        '("actor_token_new", actor_id, token)',
        '("actor_token_new", actor_id, token, 0)')
    findings = run()
    assert any(f.rule == "RTL502" and "actor_token_new" in f.message
               and "arity 4" in f.message for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 3. Drop the caps guard off the striped-fetch path -> RTL503 on the
    #    fetch_range sends (PR 3's "never probe an old peer").
    path, orig = _mutate(
        pkg, "_private/object_transfer.py",
        'if "fetch_range" in caps and self._stripe > 0:',
        'if self._stripe > 0:')
    findings = run()
    assert any(f.rule == "RTL503" and "fetch_range" in f.message
               for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 4. Remove a knob from _worker_config_env -> RTL504 at the config
    #    field (the knob would silently stop reaching spawned workers).
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        '            "RAY_TPU_LEASE_SLOTS": str(self.config.lease_slots),\n',
        '')
    findings = run()
    assert any(f.rule == "RTL504" and "lease_slots" in f.message
               for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 5. Remove a serving-memory knob from _worker_config_env -> RTL504:
    #    the paged_kv switch is read in REPLICA workers and would
    #    silently stop following _system_config.
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        '            "RAY_TPU_PAGED_KV":\n'
        '                "1" if self.config.paged_kv else "0",\n',
        '')
    findings = run()
    assert any(f.rule == "RTL504" and "paged_kv" in f.message
               for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 6. Remove the push-shuffle switch from _worker_config_env ->
    #    RTL504: the knob is read in the WORKER process (map tasks and
    #    worker-driven datasets) and would silently stop following
    #    _system_config there.
    path, orig = _mutate(
        pkg, "_private/runtime.py",
        '            "RAY_TPU_PUSH_SHUFFLE":\n'
        '                "1" if self.config.push_shuffle else "0",\n',
        '')
    findings = run()
    assert any(f.rule == "RTL504" and "push_shuffle" in f.message
               for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    # 7. Drop a serving-memory counter from the controller rollup ->
    #    RTL504 anchored at the batcher/engine stats dict that ships it
    #    (the serve-plane twin of the xfer-stats survival rule).
    # cow_copies, not prefix_hits: the rule is name-granular and
    # prefix_hits now legitimately appears at three rollup sites (the
    # sum, the per-pool breakdown, the _router sub-dict) — any one of
    # them keeps the name visible, so a single-site drop can't fire.
    path, orig = _mutate(
        pkg, "serve/api.py", '"cow_copies",', '')
    findings = run()
    assert any(f.rule == "RTL504" and "cow_copies" in f.message
               and "rollup" in f.message for f in findings), findings
    with open(path, "w", encoding="utf-8") as f:
        f.write(orig)

    assert run() == [], "restores must return the copy to clean"


# -- CLI contract -----------------------------------------------------------

def test_cli_exits_nonzero_on_bad_fixture_with_rule_and_line():
    """The real `python -m ray_tpu.devtools.protocheck` entry on a bad
    fixture: exit 1 with the pinned rule ID and file:line (one
    subprocess keeps this cheap; other CLI behaviors run in-process)."""
    bad = os.path.join(os.path.dirname(__file__), "lint_fixtures",
                       "bad_proto_caps.py")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.protocheck", bad],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "RTL503" in proc.stdout
    assert re.search(r"bad_proto_caps\.py:13:", proc.stdout)


def test_cli_doc_renders_catalog_table():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.protocheck", "--doc"],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert "| verb | senders | handlers |" in proc.stdout
    for verb in ("exec", "fetch_range", "lease_req", "put_commit"):
        assert f"| `{verb}` |" in proc.stdout
    # Caps-gated verbs carry their family in the table.
    assert "object_caps" in proc.stdout


def test_main_select_filters_rules(tmp_path, capsys):
    bad = tmp_path / "bad_select.py"
    bad.write_text(
        "# protocheck: role=head\n"
        "from ray_tpu._private import protocol\n\n\n"
        "def f(conn, rid):\n"
        '    protocol.send(conn, ("repyl", rid))\n')
    assert protocheck.main([str(bad)]) == 1
    assert "RTL501" in capsys.readouterr().out
    # Selecting a different family silences this finding.
    assert protocheck.main([f"--select=RTL505", str(bad)]) == 0
    assert capsys.readouterr().out.strip() == ""


def test_main_rejects_unknown_select(capsys):
    # A typo'd selector must not filter every finding and exit green.
    assert protocheck.main(["--select=RTL55", PKG_DIR]) == 2
    assert "matches no rule" in capsys.readouterr().err


def test_main_exit_codes(capsys):
    assert protocheck.main([]) == 2
    capsys.readouterr()
    assert protocheck.main(["no_such_dir/"]) == 2
    assert "no such path" in capsys.readouterr().err
    assert protocheck.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in protocheck.RULES:
        assert rule_id in out


def test_reasonless_protocheck_suppression_is_flagged(tmp_path, capsys):
    bad = tmp_path / "bad_noqa.py"
    bad.write_text(
        "# protocheck: role=head\n"
        "from ray_tpu._private import protocol\n\n\n"
        "def f(conn, rid):\n"
        '    protocol.send(conn, ("repyl", rid))  # noqa: RTL501\n')
    findings = protocheck.check_paths([str(bad)])
    assert [f.rule for f in findings] == ["RTL500"]
    # With a reason, the suppression stands.
    bad.write_text(
        "# protocheck: role=head\n"
        "from ray_tpu._private import protocol\n\n\n"
        "def f(conn, rid):\n"
        '    protocol.send(conn, ("repyl", rid))  # noqa: RTL501 -- deliberate interop probe\n')
    assert protocheck.check_paths([str(bad)]) == []
