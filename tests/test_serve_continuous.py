"""Continuous (iteration-level) batching engine tests: in-process
scheduler semantics (mid-flight admission, one-shot all-or-nothing
baseline, error isolation, scheduler-death backstop), the paced-decode
acceptance micro (continuous >= 2x one-shot req/s at equal
max_batch_size, best-of-3), the mesh-sharded TPU-resident replica
example end to end through serve, and the RAY_TPU_CONTINUOUS_BATCHING
switch plumbing into replica workers."""

import threading
import time

import pytest

import ray_tpu as ray
from ray_tpu import serve
from ray_tpu.serve.continuous import SlotCancelled, _ContinuousBatcher


def _paced_decode_step(step_s):
    """Step fn: every live slot needs request["tokens"] iterations; one
    fixed sleep per step models the device step cost (occupancy-
    independent, like a real fused decode step)."""

    def stepfn(slots):
        time.sleep(step_s)
        for s in slots:
            if s.state is None:
                s.state = {"n": 0, "need": s.request["tokens"]}
            s.state["n"] += 1
            if s.state["n"] >= s.state["need"]:
                s.finish({"tokens": s.state["n"],
                          "id": s.request.get("id")})
        return None

    return stepfn


def _drive(batcher, requests, timeout=60):
    """Submit every request from its own thread; return results by id."""
    results = {}
    errors = {}

    def client(req):
        try:
            results[req["id"]] = batcher.submit(req)
        except BaseException as e:  # noqa: BLE001 — recorded for asserts
            errors[req["id"]] = e

    threads = [threading.Thread(target=client, args=(r,))
               for r in requests]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    return results, errors, time.perf_counter() - t0


def test_continuous_engine_varied_lengths():
    b = _ContinuousBatcher(_paced_decode_step(0.002), None, 4, 0.01,
                           continuous=True)
    reqs = [{"id": i, "tokens": 1 + i % 5} for i in range(12)]
    results, errors, _ = _drive(b, reqs)
    assert not errors
    assert all(results[i]["tokens"] == 1 + i % 5 for i in range(12))
    s = b.stats()
    assert s["mode"] == "continuous"
    assert s["admitted"] == s["retired"] == 12
    assert s["steps"] >= 5 and s["batch_occupancy"] > 1.0


def test_continuous_admits_mid_flight():
    """Iteration-level admission: a short request submitted while a
    long one is mid-decode joins the RUNNING batch and finishes first
    — impossible under the all-or-nothing window."""
    b = _ContinuousBatcher(_paced_decode_step(0.01), None, 4, 0.0,
                           continuous=True)
    order = []

    def run(req):
        b.submit(req)
        order.append(req["id"])

    long_t = threading.Thread(target=run,
                              args=({"id": "long", "tokens": 40},))
    long_t.start()
    deadline = time.monotonic() + 5
    while b.stats()["steps"] < 3 and time.monotonic() < deadline:
        time.sleep(0.005)  # the long request is decoding now
    short_t = threading.Thread(target=run,
                               args=({"id": "short", "tokens": 2},))
    short_t.start()
    long_t.join(30)
    short_t.join(30)
    assert order == ["short", "long"]


def test_oneshot_mode_is_all_or_nothing():
    """continuous=False (the RAY_TPU_CONTINUOUS_BATCHING=0 baseline):
    a request arriving mid-batch is admitted only after EVERY slot of
    the running batch finished.  A real batching window (0.3s) makes
    the FIRST batch deterministically contain both long requests —
    with a zero window the leader can step off with only one of them
    and the latecomer shares the second batch instead of waiting."""
    b = _ContinuousBatcher(_paced_decode_step(0.01), None, 4, 0.3,
                           continuous=False)
    finished_at = {}

    def run(req):
        b.submit(req)
        finished_at[req["id"]] = time.monotonic()

    first = [threading.Thread(target=run,
                              args=({"id": f"a{i}", "tokens": 12},))
             for i in range(2)]
    for t in first:
        t.start()
    deadline = time.monotonic() + 5
    while b.stats()["steps"] < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    late = threading.Thread(target=run,
                            args=({"id": "late", "tokens": 1},))
    late.start()
    for t in first + [late]:
        t.join(30)
    # The 1-token latecomer (admitted mid-batch under continuous mode)
    # had to wait for both 12-token requests.
    assert finished_at["late"] >= max(finished_at["a0"],
                                      finished_at["a1"])
    s = b.stats()
    assert s["mode"] == "oneshot" and s["retired"] == 3


def test_step_error_fails_live_batch_and_recovers():
    calls = {"n": 0}

    def stepfn(slots):
        calls["n"] += 1
        if calls["n"] == 2:
            raise ValueError("device poof")
        for s in slots:
            if s.state is None:
                s.state = 0
            s.state += 1
            if s.state >= s.request["tokens"]:
                s.finish("ok")

    b = _ContinuousBatcher(stepfn, None, 4, 0.0, continuous=True)
    with pytest.raises(ValueError, match="device poof"):
        b.submit({"tokens": 3})
    # The scheduler survives the step error; fresh requests complete.
    assert b.submit({"tokens": 2}) == "ok"
    s = b.stats()
    assert s["step_errors"] == 1 and s["retired"] == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_scheduler_death_backstop(monkeypatch):
    """A hard-killed scheduler thread can never fire caller events; the
    caller-side liveness backstop must surface SlotCancelled instead of
    hanging, and the next submit must start a fresh scheduler."""
    b = _ContinuousBatcher(_paced_decode_step(0.001), None, 4, 0.0,
                           continuous=True)
    monkeypatch.setattr(_ContinuousBatcher, "_BACKSTOP_S", 0.1)

    def boom(live):
        raise SystemExit  # escapes the step-error handler's BaseException
        # (SystemExit inside _admit_locked, i.e. OUTSIDE the step call)

    b._admit_locked = boom  # scheduler dies before admitting anything
    with pytest.raises(SlotCancelled):
        b.submit({"id": 0, "tokens": 1})
    del b.__dict__["_admit_locked"]  # restore the real (class) method
    assert b.submit({"id": 1, "tokens": 1})["tokens"] == 1


def test_acceptance_continuous_2x_oneshot_paced_decode():
    """THE acceptance micro: a paced decode workload (fixed per-step
    cost, skewed request lengths — most short, some long, the shape
    continuous batching exists for) sustains >= 2x the req/s of
    one-shot batching at equal max_batch_size.  Best-of-3 per mode;
    sleep-paced steps make the ratio host-load-independent."""
    step_s = 0.004
    reqs = [{"id": i, "tokens": 24 if i % 4 == 0 else 2}
            for i in range(96)]

    def req_rate(continuous):
        best = 0.0
        samples = []
        for _ in range(3):
            # 50ms window: the one-shot baseline's FIRST batch gets a
            # fair chance to fill (later batches fill from the queue
            # instantly; continuous mode never waits).
            b = _ContinuousBatcher(_paced_decode_step(step_s), None, 8,
                                   0.05, continuous=continuous)
            results, errors, dt = _drive(b, reqs)
            assert not errors and len(results) == len(reqs)
            samples.append(round(len(reqs) / dt, 1))
            best = max(best, len(reqs) / dt)
        return best, samples

    cont, cont_samples = req_rate(True)
    oneshot, oneshot_samples = req_rate(False)
    assert cont >= 2.0 * oneshot, (
        f"continuous {cont:.0f} req/s vs one-shot {oneshot:.0f} req/s "
        f"(samples: {cont_samples} vs {oneshot_samples})")


# -- the TPU-resident replica example through serve -------------------------

@pytest.fixture
def ray4():
    rt = ray.init(num_cpus=4)
    yield rt
    serve.shutdown()
    ray.shutdown()


def test_mesh_sharded_decoder_numerics_via_serve(ray4):
    """The TPU-resident replica example end to end: weights resident on
    the (degenerate, CPU) device mesh, device-resident decode state,
    double-buffered joins — decoded chains must match the host-side
    sequential reference exactly (integer-exact weights)."""
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    dep = serve.deployment(MeshShardedDecoder, name="decoder",
                           max_concurrency=16)
    handle = serve.run(dep.bind(), name="decoder")
    reqs = [{"prompt": i, "tokens": 1 + i % 6} for i in range(12)]
    outs = ray.get([handle.remote(r) for r in reqs], timeout=120)
    ref = MeshShardedDecoder()
    for r, out in zip(reqs, outs):
        assert out == ref.reference_decode(r["prompt"], r["tokens"]), r
    stats = serve.serving_stats("decoder")
    assert stats["mode"] == "continuous"
    assert stats["steps"] >= 6 and stats["retired"] == 12
    assert stats["batch_occupancy"] > 0


def test_continuous_switch_off_env_plumbing():
    """_system_config{continuous_batching: False} must reach replica
    workers (the knob rides _worker_config_env): the same deployment's
    batcher then reports one-shot mode and still serves correctly."""
    ray.init(num_cpus=4,
             _system_config={"continuous_batching": False})
    try:
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        dep = serve.deployment(MeshShardedDecoder, name="decoder_off",
                               max_concurrency=16)
        handle = serve.run(dep.bind(), name="decoder_off")
        outs = ray.get([handle.remote({"prompt": i, "tokens": 2})
                        for i in range(6)], timeout=120)
        ref = MeshShardedDecoder()
        for i, out in enumerate(outs):
            assert out == ref.reference_decode(i, 2)
        stats = serve.serving_stats("decoder_off")
        assert stats["mode"] == "oneshot"
        assert stats["retired"] == 6
    finally:
        serve.shutdown()
        ray.shutdown()
