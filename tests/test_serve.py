"""Serve-layer tests (reference pattern: python/ray/serve/tests)."""

import time

import pytest

import ray_tpu as ray
from ray_tpu import serve


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    serve.shutdown()
    ray.shutdown()


def test_function_deployment(ray8):
    @serve.deployment
    def echo(body):
        return {"echo": body}

    handle = serve.run(echo)
    out = ray.get(handle.remote({"x": 1}))
    assert out == {"echo": {"x": 1}}


def test_class_deployment_with_state(ray8):
    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self, body):
            self.n += 1
            return self.n

    handle = serve.run(Counter.bind(10))
    vals = [ray.get(handle.remote({})) for _ in range(3)]
    assert vals == [11, 12, 13]


def test_multiple_replicas_round_robin(ray8):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, body):
            import os
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = {ray.get(handle.remote({})) for _ in range(6)}
    assert len(pids) == 2


def test_scale_and_reconcile(ray8):
    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, body):
            return "ok"

    serve.run(S.bind(), name="s")
    controller = serve._get_controller() if hasattr(serve, "_get_controller") \
        else None
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    ray.get(controller.scale.remote("s", 3))
    assert len(ray.get(controller.get_replicas.remote("s"))) == 3
    ray.get(controller.scale.remote("s", 1))
    assert len(ray.get(controller.get_replicas.remote("s"))) == 1


def test_dead_replica_replacement(ray8):
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self, body):
            return "alive"

    serve.run(D.bind(), name="d")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    reps = ray.get(controller.get_replicas.remote("d"))
    ray.kill(reps[0])
    time.sleep(0.3)
    counts = ray.get(controller.reconcile.remote())
    assert counts["d"] == 2


def test_http_proxy_end_to_end(ray8):
    import requests

    @serve.deployment(route_prefix="/classify")
    def classify(body):
        return {"label": "cat", "score": body.get("score", 0.5)}

    serve.run(classify)
    url = serve.start_http_proxy(port=18472)
    r = requests.post(f"{url}/classify", json={"score": 0.9}, timeout=10)
    assert r.status_code == 200
    assert r.json()["result"]["label"] == "cat"
    r404 = requests.get(f"{url}/nope", timeout=10)
    assert r404.status_code == 404
