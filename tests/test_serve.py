"""Serve-layer tests (reference pattern: python/ray/serve/tests)."""

import time

import pytest

import ray_tpu as ray
from ray_tpu import serve


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    serve.shutdown()
    ray.shutdown()


def test_function_deployment(ray8):
    @serve.deployment
    def echo(body):
        return {"echo": body}

    handle = serve.run(echo)
    out = ray.get(handle.remote({"x": 1}))
    assert out == {"echo": {"x": 1}}


def test_class_deployment_with_state(ray8):
    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self, body):
            self.n += 1
            return self.n

    handle = serve.run(Counter.bind(10))
    vals = [ray.get(handle.remote({})) for _ in range(3)]
    assert vals == [11, 12, 13]


def test_multiple_replicas_round_robin(ray8):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, body):
            import os
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = {ray.get(handle.remote({})) for _ in range(6)}
    assert len(pids) == 2


def test_scale_and_reconcile(ray8):
    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, body):
            return "ok"

    serve.run(S.bind(), name="s")
    controller = serve._get_controller() if hasattr(serve, "_get_controller") \
        else None
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    ray.get(controller.scale.remote("s", 3))
    assert len(ray.get(controller.get_replicas.remote("s"))) == 3
    ray.get(controller.scale.remote("s", 1))
    assert len(ray.get(controller.get_replicas.remote("s"))) == 1


def test_dead_replica_replacement(ray8):
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self, body):
            return "alive"

    serve.run(D.bind(), name="d")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    reps = ray.get(controller.get_replicas.remote("d"))
    ray.kill(reps[0])
    time.sleep(0.3)
    counts = ray.get(controller.reconcile.remote())
    assert counts["d"] == 2


def test_http_proxy_end_to_end(ray8):
    import requests

    @serve.deployment(route_prefix="/classify")
    def classify(body):
        return {"label": "cat", "score": body.get("score", 0.5)}

    serve.run(classify)
    url = serve.start_http_proxy(port=18472)
    r = requests.post(f"{url}/classify", json={"score": 0.9}, timeout=10)
    assert r.status_code == 200
    assert r.json()["result"]["label"] == "cat"
    r404 = requests.get(f"{url}/nope", timeout=10)
    assert r404.status_code == 404


def test_background_reconcile_heals_without_deploy(ray8):
    """Kill a replica: the controller's OWN loop replaces it — no deploy,
    scale, or explicit reconcile call (reference: the continuously-running
    DeploymentStateManager.update loop, deployment_state.py:1855)."""
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self, body):
            return "alive"

    h = serve.run(D.bind(), name="heal")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    reps = ray.get(controller.get_replicas.remote("heal"))
    ray.kill(reps[0])
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if ray.get(controller.num_replicas.remote("heal")) == 2:
            # and requests flow again
            assert ray.get(h.remote({}), timeout=30) == "alive"
            return
        time.sleep(0.3)
    raise AssertionError("background loop never replaced the dead replica")


def test_autoscaling_up_and_down(ray8):
    """Queue depth above target doubles replicas; idle + downscale delay
    shrinks back to min (reference: autoscaling_policy.py)."""
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 2, "downscale_delay_s": 2.0})
    class Slow:
        def __call__(self, body):
            time.sleep(0.4)
            return "ok"

    h = serve.run(Slow.bind(), name="auto")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    assert ray.get(controller.num_replicas.remote("auto")) == 1

    # sustained load: keep ~8 in flight for a few seconds
    stop = time.monotonic() + 6
    refs = []
    peak = 1
    while time.monotonic() < stop:
        refs = [r for r in refs
                if not ray.wait([r], num_returns=1, timeout=0)[0]]
        while len(refs) < 8:
            refs.append(h.remote({}))
        peak = max(peak, ray.get(controller.num_replicas.remote("auto")))
        time.sleep(0.2)
    assert peak >= 2, f"never scaled up (peak={peak})"
    for r in refs:
        ray.get(r, timeout=60)
    # idle: back to min after the downscale delay
    deadline = time.monotonic() + 25
    while time.monotonic() < deadline:
        if ray.get(controller.num_replicas.remote("auto")) == 1:
            return
        time.sleep(0.5)
    raise AssertionError("never scaled back down to min_replicas")


def test_rolling_update_changes_version(ray8):
    """Redeploying a changed callable rolls replicas to the new version
    while the deployment keeps serving."""
    @serve.deployment(num_replicas=2)
    class V:
        def __call__(self, body):
            return "v1"

    h = serve.run(V.bind(), name="roll")
    assert ray.get(h.remote({}), timeout=30) == "v1"

    @serve.deployment(num_replicas=2, name="V")
    class V2:
        def __call__(self, body):
            return "v2"

    h = serve.run(V2.bind(), name="roll")
    deadline = time.monotonic() + 30
    seen = set()
    while time.monotonic() < deadline:
        out = ray.get(h.remote({}), timeout=30)  # never errors mid-roll
        seen.add(out)
        if out == "v2":
            # drain: eventually ONLY v2 responds
            got = {ray.get(h.remote({}), timeout=30) for _ in range(8)}
            if got == {"v2"}:
                return
        time.sleep(0.3)
    raise AssertionError(f"rolling update never completed (saw {seen})")
