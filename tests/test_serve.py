"""Serve-layer tests (reference pattern: python/ray/serve/tests)."""

import time

import pytest

import ray_tpu as ray
from ray_tpu import serve


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    serve.shutdown()
    ray.shutdown()


def test_function_deployment(ray8):
    @serve.deployment
    def echo(body):
        return {"echo": body}

    handle = serve.run(echo)
    out = ray.get(handle.remote({"x": 1}))
    assert out == {"echo": {"x": 1}}


def test_class_deployment_with_state(ray8):
    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self, body):
            self.n += 1
            return self.n

    handle = serve.run(Counter.bind(10))
    vals = [ray.get(handle.remote({})) for _ in range(3)]
    assert vals == [11, 12, 13]


def test_multiple_replicas_round_robin(ray8):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, body):
            import os
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = {ray.get(handle.remote({})) for _ in range(6)}
    assert len(pids) == 2


def test_scale_and_reconcile(ray8):
    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, body):
            return "ok"

    serve.run(S.bind(), name="s")
    controller = serve._get_controller() if hasattr(serve, "_get_controller") \
        else None
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    ray.get(controller.scale.remote("s", 3))
    assert len(ray.get(controller.get_replicas.remote("s"))) == 3
    ray.get(controller.scale.remote("s", 1))
    assert len(ray.get(controller.get_replicas.remote("s"))) == 1


def test_dead_replica_replacement(ray8):
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self, body):
            return "alive"

    serve.run(D.bind(), name="d")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    reps = ray.get(controller.get_replicas.remote("d"))
    ray.kill(reps[0])
    time.sleep(0.3)
    counts = ray.get(controller.reconcile.remote())
    assert counts["d"] == 2


def test_http_proxy_end_to_end(ray8):
    import requests

    @serve.deployment(route_prefix="/classify")
    def classify(body):
        return {"label": "cat", "score": body.get("score", 0.5)}

    serve.run(classify)
    url = serve.start_http_proxy(port=18472)
    r = requests.post(f"{url}/classify", json={"score": 0.9}, timeout=10)
    assert r.status_code == 200
    assert r.json()["result"]["label"] == "cat"
    r404 = requests.get(f"{url}/nope", timeout=10)
    assert r404.status_code == 404


def test_background_reconcile_heals_without_deploy(ray8):
    """Kill a replica: the controller's OWN loop replaces it — no deploy,
    scale, or explicit reconcile call (reference: the continuously-running
    DeploymentStateManager.update loop, deployment_state.py:1855)."""
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self, body):
            return "alive"

    h = serve.run(D.bind(), name="heal")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    reps = ray.get(controller.get_replicas.remote("heal"))
    ray.kill(reps[0])
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if ray.get(controller.num_replicas.remote("heal")) == 2:
            # and requests flow again
            assert ray.get(h.remote({}), timeout=30) == "alive"
            return
        time.sleep(0.3)
    raise AssertionError("background loop never replaced the dead replica")


def test_autoscaling_up_and_down(ray8):
    """Queue depth above target doubles replicas; idle + downscale delay
    shrinks back to min (reference: autoscaling_policy.py)."""
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 2, "downscale_delay_s": 2.0})
    class Slow:
        def __call__(self, body):
            time.sleep(0.4)
            return "ok"

    h = serve.run(Slow.bind(), name="auto")
    from ray_tpu.serve.api import _get_controller
    controller = _get_controller()
    assert ray.get(controller.num_replicas.remote("auto")) == 1

    # sustained load: keep ~8 in flight for a few seconds
    stop = time.monotonic() + 6
    refs = []
    peak = 1
    while time.monotonic() < stop:
        refs = [r for r in refs
                if not ray.wait([r], num_returns=1, timeout=0)[0]]
        while len(refs) < 8:
            refs.append(h.remote({}))
        peak = max(peak, ray.get(controller.num_replicas.remote("auto")))
        time.sleep(0.2)
    assert peak >= 2, f"never scaled up (peak={peak})"
    for r in refs:
        ray.get(r, timeout=60)
    # idle: back to min after the downscale delay
    deadline = time.monotonic() + 25
    while time.monotonic() < deadline:
        if ray.get(controller.num_replicas.remote("auto")) == 1:
            return
        time.sleep(0.5)
    raise AssertionError("never scaled back down to min_replicas")


def test_rolling_update_changes_version(ray8):
    """Redeploying a changed callable rolls replicas to the new version
    while the deployment keeps serving."""
    @serve.deployment(num_replicas=2)
    class V:
        def __call__(self, body):
            return "v1"

    h = serve.run(V.bind(), name="roll")
    assert ray.get(h.remote({}), timeout=30) == "v1"

    @serve.deployment(num_replicas=2, name="V")
    class V2:
        def __call__(self, body):
            return "v2"

    h = serve.run(V2.bind(), name="roll")
    deadline = time.monotonic() + 30
    seen = set()
    while time.monotonic() < deadline:
        out = ray.get(h.remote({}), timeout=30)  # never errors mid-roll
        seen.add(out)
        if out == "v2":
            # drain: eventually ONLY v2 responds
            got = {ray.get(h.remote({}), timeout=30) for _ in range(8)}
            if got == {"v2"}:
                return
        time.sleep(0.3)
    raise AssertionError(f"rolling update never completed (saw {seen})")


def test_push_propagation_on_downscale(ray8):
    """VERDICT #8 'done': after a downscale, no request lands on a
    retired replica — the handle learns by PUSH (long-poll), not TTL."""
    @serve.deployment(num_replicas=3)
    class Who:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, body):
            return self.pid

    handle = serve.run(Who.bind())
    pids = {ray.get(handle.remote({})) for _ in range(30)}
    assert len(pids) == 3
    from ray_tpu.serve.api import _get_controller

    ray.get(_get_controller().scale.remote(Who.name
                                           if hasattr(Who, "name")
                                           else "Who", 1))
    # Push should land well inside a second (no 2s TTL window).
    deadline = time.time() + 10
    while time.time() < deadline:
        with handle._lock:
            n = len(handle._replicas)
        if n == 1:
            break
        time.sleep(0.05)
    with handle._lock:
        assert len(handle._replicas) == 1
    after = {ray.get(handle.remote({})) for _ in range(20)}
    assert len(after) == 1


def test_serve_batch_coalesces(ray8):
    """@serve.batch: concurrent requests coalesce into list calls
    (reference: serve/batching.py)."""
    @serve.deployment(num_replicas=1)
    class Doubler:
        def __init__(self):
            self.calls = 0

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        def handle_batch(self, items):
            self.calls += 1
            return [x * 2 for x in items]

        def __call__(self, body):
            return self.handle_batch(body)

        def n_calls(self, body):
            return self.calls

    handle = serve.run(Doubler.bind())
    refs = [handle.remote(i) for i in range(16)]
    vals = ray.get(refs, timeout=60)
    assert sorted(vals) == [i * 2 for i in range(16)]
    calls = ray.get(handle.method("call_method_is_not")
                    if False else handle.method("n_calls").remote({}))
    # 16 requests, batches of up to 8 -> far fewer underlying calls.
    assert calls <= 6, calls


def test_batch_leader_exception_fails_followers_not_hangs():
    """Satellite pin: an exception landing in the LEADER before the
    batch runs (async kill, interrupted wait) must set every follower
    entry's event — nobody hangs forever."""
    import threading

    from ray_tpu.serve.batching import _Batcher

    def fn(items):
        return [x * 2 for x in items]

    b = _Batcher(fn, None, max_batch_size=4, batch_wait_timeout_s=0.2)
    orig_wait = b._full.wait
    release = threading.Event()

    def dying_wait(timeout=None):
        release.wait(5)  # let followers enqueue first
        raise RuntimeError("async kill in the batching window")

    b._full.wait = dying_wait
    results = {}

    def leader():
        try:
            results["leader"] = ("ok", b.submit(1))
        except BaseException as e:  # noqa: BLE001 — recorded for asserts
            results["leader"] = ("err", e)

    def follower():
        b._full.wait = orig_wait  # only the first (leader) wait dies
        try:
            results["follower"] = ("ok", b.submit(2))
        except BaseException as e:  # noqa: BLE001 — recorded for asserts
            results["follower"] = ("err", e)

    lt = threading.Thread(target=leader)
    lt.start()
    time.sleep(0.05)  # leader is parked in the window
    ft = threading.Thread(target=follower)
    ft.start()
    time.sleep(0.05)
    release.set()
    lt.join(10)
    ft.join(10)
    assert not lt.is_alive() and not ft.is_alive(), "batch entry hung"
    assert results["leader"][0] == "err"
    assert results["follower"][0] == "err"
    assert "leader failed" in str(results["follower"][1])
    # The batcher stays usable: the next batch elects a fresh leader.
    assert b.submit(3) == 6


def test_batch_leader_death_rescued_by_follower_backstop(monkeypatch):
    """Satellite pin: a HARD-killed leader (thread gone, no exception
    path ran) leaves its entries pending forever in the old code; the
    follower backstop must detect the dead leader and rescue-run the
    pending batch."""
    import threading

    from ray_tpu.serve.batching import _Batcher, _Entry

    monkeypatch.setattr(_Batcher, "_BACKSTOP_S", 0.1)

    def fn(items):
        return [x * 10 for x in items]

    b = _Batcher(fn, None, max_batch_size=8, batch_wait_timeout_s=30.0)
    # Simulate the post-mortem state: a leader that appended its entry
    # and died before collecting the batch.
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    orphan = _Entry(1)
    with b._lock:
        b._pending.append(orphan)
        b._leader = dead
    # A live follower joins the orphaned batch; its backstop must take
    # over leadership and run BOTH entries.
    assert b.submit(2) == 20
    assert orphan.event.is_set() and orphan.result == 10


def test_redeploy_same_name_ignores_stale_handle_metrics(ray8):
    """Satellite pin: metric windows are keyed by (name, incarnation) —
    a handle from a DELETED deployment keeps reporting, but its samples
    must not feed the autoscaler of a same-name redeploy (the old
    controller keyed by name only and scaled the fresh deployment on
    the stale handle's ongoing count)."""
    from ray_tpu.serve.api import _get_controller

    cfg = {"min_replicas": 1, "max_replicas": 4,
           "target_ongoing_requests": 1, "downscale_delay_s": 1.0}

    @serve.deployment(autoscaling_config=cfg)
    class A:
        def __call__(self, body):
            return "a"

    handle = serve.run(A.bind(), name="redeploy")
    controller = _get_controller()
    assert ray.get(handle.remote({})) == "a"
    stale_inc = ray.get(
        controller.deployment_incarnation.remote("redeploy"))
    ray.get(controller.delete_deployment.remote("redeploy"))

    @serve.deployment(autoscaling_config=cfg)
    class B:
        def __call__(self, body):
            return "b"

    handle2 = serve.run(B.bind(), name="redeploy")
    assert ray.get(handle2.remote({})) == "b"
    new_inc = ray.get(
        controller.deployment_incarnation.remote("redeploy"))
    assert new_inc == stale_inc + 1
    # A SURVIVING old handle re-keys itself: its long-poll carries the
    # new incarnation along with the replica set, so a handle that
    # keeps being used after a redeploy reports under the fresh key
    # instead of being dropped forever.
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        with handle._lock:
            if handle._incarnation == new_inc:
                break
        time.sleep(0.2)
    with handle._lock:
        assert handle._incarnation == new_inc
    # The stale handle screams "12 ongoing" (dangling refs against dead
    # replicas).  Keyed by incarnation, the report is dropped...
    assert ray.get(controller.record_handle_metric.remote(
        "redeploy", "stale-handle", 12, stale_inc)) is False
    for _ in range(3):
        ray.get(controller.reconcile.remote())
    assert ray.get(controller.num_replicas.remote("redeploy")) == 1
    # ...while a current-incarnation report still drives scaling.
    assert ray.get(controller.record_handle_metric.remote(
        "redeploy", "live-handle", 4, new_inc)) is True
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        ray.get(controller.reconcile.remote())
        if ray.get(controller.num_replicas.remote("redeploy")) == 4:
            break
        time.sleep(0.2)
    assert ray.get(controller.num_replicas.remote("redeploy")) == 4
    stats = ray.get(controller.serving_stats.remote("redeploy"))
    assert stats["scale_ups"] >= 1


def test_least_loaded_routing_skews_away_from_busy(ray8):
    @serve.deployment(num_replicas=2)
    class Sleepy:
        def __call__(self, body):
            import os
            import time as _t

            _t.sleep(body.get("sleep", 0))
            return os.getpid()

    handle = serve.run(Sleepy.bind())
    # Saturate one replica with slow calls, then fire quick ones; the
    # quick ones should mostly land on the other replica.
    slow = [handle.remote({"sleep": 2.0}) for _ in range(6)]
    time.sleep(0.6)  # metrics period: in-flight counts materialize
    quick = ray.get([handle.remote({"sleep": 0}) for _ in range(10)],
                    timeout=60)
    assert len(set(quick)) >= 1  # sanity: quick calls completed fast
    ray.get(slow, timeout=60)
