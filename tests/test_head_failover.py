"""Head failover: full-state snapshots + worker reconnect-and-replay.

The acceptance battery for ROADMAP item 5(a): a LIVE 2-agent cluster
under sustained task + serve traffic crosses a hard head kill
(SIGKILL — no atexit, no final snapshot) and restart with

- every ``ray.get`` correct (no errors, no wrong values),
- agent worker processes NOT respawned (PIDs stable across the blip),
- a restored named actor resuming from retained state (adoption for a
  surviving worker; ``__ray_restore__`` of the last ``__ray_save__``
  checkpoint for one that died with the head — NOT a fresh __init__),
- traffic stalling for a bounded window rather than failing,

plus the reconnect-off control (``RAY_TPU_AGENT_RECONNECT=0`` keeps
today's kill-workers outage with every failover counter zero), the
head-role chaos env rules, knob env-plumbing through both worker spawn
paths, and the battery's lockcheck re-run.

Reference analog: GCS failover — redis-backed table persistence
(redis_store_client.h:28), GcsInitData load (gcs_server.h:77), and
workers reconnecting across a GCS restart
(gcs_failover_worker_reconnect_timeout, ray_config_def.h:62).
"""

import os
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

import pytest

import ray_tpu as ray
from ray_tpu.chaos import ChaosController
from ray_tpu.cluster_utils import Cluster


FAILOVER_COUNTERS = ("reconnected_nodes", "reregistered_workers",
                     "adopted_actors")


@ray.remote
def _double(x):
    return x * 2, os.getpid()


@ray.remote(max_restarts=-1, max_task_retries=-1)
class _Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n

    def pid(self):
        return os.getpid()

    def __ray_save__(self):
        return self.n

    def __ray_restore__(self, n):
        self.n = n


class _Traffic(threading.Thread):
    """Sustained request loop: records per-op completion times and any
    error — the blip shows up as a completion GAP, never as a failure."""

    def __init__(self, op, check):
        super().__init__(daemon=True)
        self._op = op
        self._check = check
        self.completions = []
        self.errors = []
        self.stop = threading.Event()

    def run(self):
        i = 0
        while not self.stop.is_set():
            try:
                out = ray.get(self._op(i), timeout=60)
                if not self._check(i, out):
                    self.errors.append((i, "wrong value", out))
                self.completions.append(time.monotonic())
            except Exception as e:  # noqa: BLE001
                self.errors.append((i, "error", repr(e)))
            i += 1
            time.sleep(0.03)

    def max_gap(self):
        gaps = [b - a for a, b in zip(self.completions,
                                      self.completions[1:])]
        return max(gaps) if gaps else float("inf")


# ------------------------------------------------------------ acceptance --

def test_head_failover_acceptance_live_cluster():
    """THE acceptance scenario: 2-agent cluster, sustained task + serve
    traffic, hard head kill + restart = a bounded blip."""
    from ray_tpu import serve

    c = Cluster(external_head=True, head_num_cpus=0)
    chaos = None
    task_t = serve_t = None
    try:
        c.add_node(num_cpus=2, external=True)
        c.add_node(num_cpus=2, external=True)
        chaos = ChaosController(c.rt, arm_syncpoints=False, head=c)

        cnt = _Counter.options(name="survivor").remote()
        assert ray.get([cnt.incr.remote() for _ in range(5)],
                       timeout=60) == [1, 2, 3, 4, 5]
        actor_pid = ray.get(cnt.pid.remote(), timeout=30)

        @serve.deployment(num_replicas=1)
        class Echo:
            def __call__(self, x):
                return x * 3, os.getpid()

        handle = serve.run(Echo.bind())
        triple, serve_pid = ray.get(handle.remote(7), timeout=60)
        assert triple == 21

        # Warm-up so the lease plane + direct actor channels exist,
        # then record the task-worker PID set the blip must preserve.
        warm = ray.get([_double.remote(i) for i in range(8)], timeout=60)
        pids_before = {p for _, p in warm}

        task_t = _Traffic(lambda i: _double.remote(i),
                          lambda i, out: out[0] == i * 2)
        serve_t = _Traffic(lambda i: handle.remote(i),
                           lambda i, out: out[0] == i * 3)
        task_t.start()
        serve_t.start()
        time.sleep(1.2)  # traffic flowing; snapshot loop has the state

        t_kill = time.monotonic()
        assert chaos.kill_head() is not None
        time.sleep(0.8)  # a real restart takes operator/systemd time
        chaos.restart_head()

        # Let traffic run well past the blip, then stop.
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            if task_t.completions and serve_t.completions \
                    and task_t.completions[-1] > t_kill + 6 \
                    and serve_t.completions[-1] > t_kill + 6:
                break
            time.sleep(0.25)
        task_t.stop.set()
        serve_t.stop.set()
        task_t.join(timeout=70)
        serve_t.join(timeout=70)

        # Every get correct — the blip is a GAP, never a failure.
        assert task_t.errors == [], task_t.errors[:5]
        assert serve_t.errors == [], serve_t.errors[:5]
        assert task_t.completions[-1] > t_kill + 2, "no post-blip tasks"
        assert serve_t.completions[-1] > t_kill + 2, "no post-blip serves"
        # Stall bounded: well under the grace windows, nowhere near an
        # outage.
        assert task_t.max_gap() < 30, task_t.max_gap()
        assert serve_t.max_gap() < 30, serve_t.max_gap()

        # Worker processes were NOT respawned: every pre-blip worker
        # process is still alive (none was torn down and replaced), and
        # both actors kept their exact process.  (A fresh worker MAY
        # additionally spawn if dispatch raced a survivor's re-dial —
        # progress beats strict reuse; what must never happen is a
        # survivor dying.)
        for p in pids_before:
            os.kill(p, 0)  # raises if the pre-blip worker died
        assert ray.get(cnt.pid.remote(), timeout=60) == actor_pid
        # The named actor resumed from retained state (adoption — its
        # counter kept counting, it never re-ran __init__).
        assert ray.get(cnt.incr.remote(), timeout=60) >= 6
        _t, pid2 = ray.get(handle.remote(1), timeout=60)
        assert pid2 == serve_pid

        stats = c.rt.transfer_stats()
        assert stats["reconnected_nodes"] == 2, stats
        # Both agents' workers + this client re-registered.
        assert stats["reregistered_workers"] >= 3, stats
        # Counter actor + serve controller + replica all adopted.
        assert stats["adopted_actors"] >= 3, stats
        assert chaos.stats()["head_kills"] == 1
    finally:
        for t in (task_t, serve_t):
            if t is not None:
                t.stop.set()
        if chaos is not None:
            chaos.stop()
        try:
            serve.shutdown()
        except Exception:
            pass
        c.shutdown()


def test_cold_restore_named_actor_from_checkpoint():
    """An actor whose worker DIES WITH THE HEAD (head-hosted, worker
    reconnect disabled) is re-created by the restarted head from its
    retained ``__ray_save__`` checkpoint — state continues, __init__'s
    fresh state does not win."""
    c = Cluster(external_head=True, head_num_cpus=2,
                _system_config={"head_failover": False})
    try:
        cnt = _Counter.options(name="ck").remote()
        assert ray.get([cnt.incr.remote() for _ in range(3)],
                       timeout=60) == [1, 2, 3]
        time.sleep(0.8)  # checkpoint + snapshot both land
        c.kill_head()
        c.restart_head()
        # head_failover=False on the head side killed its workers with
        # it; this CLIENT still reconnects (its own switch is on).
        cnt2 = ray.get_actor("ck")
        # 4, not 1: __ray_restore__ ran over the fresh __init__.
        assert ray.get(cnt2.incr.remote(), timeout=90) == 4
        stats = c.rt.transfer_stats()
        assert stats["adopted_actors"] == 0, stats  # cold path, not adoption
    finally:
        c.shutdown()


def test_reconnect_off_reproduces_outage_with_zero_counters():
    """The escape hatch: RAY_TPU_AGENT_RECONNECT=0 keeps today's
    behavior — the agent tears its workers down on head death and never
    returns, so the restarted head sees an empty cluster and every
    failover counter stays zero."""
    c = Cluster(external_head=True, head_num_cpus=0)
    try:
        nid = c.add_node(num_cpus=2, external=True,
                         env_overrides={"RAY_TPU_AGENT_RECONNECT": "0"})
        _v, worker_pid = ray.get(_double.remote(21), timeout=60)
        agent_proc = c._agents[nid]
        # Detach the client FIRST: this run drills the agent-side
        # outage, and a fresh client against the restarted head must
        # see zero failover counters.
        ray.shutdown()
        c.kill_head()
        # Agent exits on its own (reconnect off) and its worker dies
        # with it — today's outage.
        agent_proc.wait(timeout=30)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                os.kill(worker_pid, 0)
            except OSError:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("worker survived reconnect-off outage")
        c.restart_head()
        c.rt = ray.init(address=c._head_address,
                        _authkey=c._authkey_hex)
        assert all(not n["alive"] or n["labels"].get("head")
                   for n in c.rt.list_nodes())
        stats = c.rt.transfer_stats()
        for k in FAILOVER_COUNTERS:
            assert stats[k] == 0, (k, stats)
    finally:
        c.shutdown()


# ----------------------------------------------------- head chaos rules --

def test_env_rule_kills_head_at_snapshot_syncpoint():
    """RAY_TPU_CHAOS head-role rules arm in the head process (the gap
    this PR closes — only workers and agents armed them before):
    ``head:snapshot:2`` hard-kills the head at its 2nd snapshot write,
    the one-shot claim file proves it fired, and a restart resumes the
    cluster."""
    chaos_dir = tempfile.mkdtemp()
    c = Cluster(external_head=True, head_num_cpus=0,
                head_env={"RAY_TPU_CHAOS": "head:snapshot:2",
                          "RAY_TPU_CHAOS_DIR": chaos_dir})
    try:
        c.add_node(num_cpus=2, external=True)
        assert ray.get(_double.remote(5), timeout=60)[0] == 10
        # Keep the head's tables dirty until the rule fires: steady-
        # state task traffic rides the lease plane (zero head messages),
        # so mutate the head-registered object table with client puts —
        # over-inline-size ones, which register via put_parts.
        deadline = time.time() + 30
        while c.head_proc.poll() is None and time.time() < deadline:
            try:
                ref = ray.put(os.urandom(1_200_000))
                del ref
            except Exception:
                break  # head died mid-put: exactly what we want
            time.sleep(0.1)
        c.head_proc.wait(timeout=30)
        claims = [f for f in os.listdir(chaos_dir)
                  if "_head_snapshot_" in f]
        assert claims, "head chaos rule never fired"
        c.restart_head()
        assert ray.get(_double.remote(6), timeout=90)[0] == 12
    finally:
        c.shutdown()


# ------------------------------------------------------- knob plumbing --

def test_failover_knob_env_plumbing_both_spawn_paths():
    """PR 5-9 convention for new knobs: _system_config overrides reach
    spawned workers through the RAY_TPU_* env namespace via
    _worker_config_env — probed through BOTH spawn paths (head-local
    subprocess and agent-forked), with every failover counter zero in a
    blip-free run."""
    c = Cluster(head_num_cpus=2, _system_config={
        "head_failover": False,
        "head_reconnect_grace_s": 7.25,
        "head_reregister_timeout_s": 3.5,
    })
    try:
        nid = c.add_node(num_cpus=1, external=True)
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy as NA,
        )

        @ray.remote
        def probe():
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg

            return (cfg.head_failover, cfg.head_reconnect_grace_s,
                    cfg.head_reregister_timeout_s)

        expected = (False, 7.25, 3.5)
        # Head-local spawn path.
        assert ray.get(probe.options(scheduling_strategy=NA(
            node_id=c.rt.head_node.node_id.hex(), soft=False)).remote(),
            timeout=60) == expected
        # Agent spawn path.
        assert ray.get(probe.options(scheduling_strategy=NA(
            node_id=nid, soft=False)).remote(), timeout=60) == expected
        stats = c.rt.transfer_stats()
        for k in FAILOVER_COUNTERS:
            assert stats[k] == 0, (k, stats)
    finally:
        c.shutdown()


def test_snapshot_hygiene_counters_and_final_snapshot(tmp_path):
    """Satellite: gcs_snapshots/gcs_snapshot_failures surface in
    transfer_stats()/state_query, and a clean shutdown() writes a final
    snapshot even when nothing dirty was pending a periodic write."""
    snap = str(tmp_path / "gcs.bin")
    rt = ray.init(num_cpus=2, _system_config={
        "gcs_snapshot_path": snap,
        "gcs_snapshot_interval_s": 0.2,
    })
    try:
        rt.kv_put(b"k", b"v")
        deadline = time.time() + 10
        while time.time() < deadline \
                and rt.transfer_stats()["gcs_snapshots"] == 0:
            time.sleep(0.05)
        stats = rt.state_query("transfer_stats")[0]
        assert stats["gcs_snapshots"] >= 1, stats
        assert stats["gcs_snapshot_failures"] == 0, stats
        rt.kv_put(b"k2", b"v2")  # dirty again, inside the interval
        before = os.path.getmtime(snap)
        n_before = rt.transfer_stats()["gcs_snapshots"]
    finally:
        ray.shutdown()
    # The final shutdown snapshot captured the last-interval mutation.
    assert os.path.getmtime(snap) >= before
    from ray_tpu._private import serialization

    with open(snap, "rb") as f:
        data = serialization.loads_inline(f.read())
    assert data["kv"]["default"][b"k2"] == b"v2"
    assert data["version"] >= 2
    assert n_before >= 1


# --------------------------------------------------- lockcheck battery --

@pytest.mark.slow  # duplicate-coverage drill: the acceptance test above
#                   exercises the same failover machinery; this re-runs
#                   it with the lockdep checker installed (sub-second
#                   tier-1 representatives: the hygiene + plumbing tests)
def test_failover_battery_under_lockcheck_zero_cycles():
    """The failover drill re-run under RAY_TPU_LOCKCHECK=1: snapshot
    widening, restore/reconcile, client reconnect-and-replay must
    introduce no lock-order cycles in the driver/client process (the
    head + workers inherit the checker via the env too)."""
    code = textwrap.dedent("""
        import os, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        import ray_tpu as ray
        from ray_tpu.devtools import lockcheck
        from ray_tpu.cluster_utils import Cluster
        assert lockcheck.enabled()

        # Leg 1: in-process snapshot -> restore (the head-side paths).
        snap = "/tmp/rtpu_lockcheck_gcs_%d" % os.getpid()
        rt = ray.init(num_cpus=2, _system_config={
            "gcs_snapshot_path": snap})

        @ray.remote
        def f(i):
            return i + 1

        @ray.remote(max_restarts=1)
        class C:
            def __init__(self):
                self.n = 0
            def inc(self):
                self.n += 1
                return self.n
            def __ray_save__(self):
                return self.n
            def __ray_restore__(self, n):
                self.n = n

        c = C.options(name="lc").remote()
        assert ray.get([f.remote(i) for i in range(8)]) == list(range(1, 9))
        assert ray.get(c.inc.remote()) == 1
        rt._snapshot_gcs()
        ray.shutdown()
        rt2 = ray.init(num_cpus=2, _system_config={
            "gcs_snapshot_path": snap, "gcs_restore": True})
        c2 = ray.get_actor("lc")
        assert ray.get(c2.inc.remote(), timeout=60) >= 1
        assert ray.get(f.remote(41), timeout=60) == 42
        ray.shutdown()
        os.unlink(snap)

        # Leg 2: live kill+restart with the client machinery under the
        # checker (head/agent/workers inherit RAY_TPU_LOCKCHECK).
        cl = Cluster(external_head=True, head_num_cpus=0)
        try:
            cl.add_node(num_cpus=2, external=True)
            assert ray.get(f.remote(1), timeout=60) == 2
            time.sleep(0.5)
            cl.kill_head()
            cl.restart_head()
            assert ray.get(f.remote(2), timeout=90) == 3
        finally:
            cl.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        print("FAILOVER_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    assert "FAILOVER_LOCKCHECK_OK" in proc.stdout
