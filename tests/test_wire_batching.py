"""Batched wire protocol + bulk submission tests.

Covers the ("batch", ...) envelope (protocol round trip, legacy
"msg_batch" spelling, interop with a peer that never batches), the bulk
submit path's refcount correctness (the submit-time ``local_refs += 1``
race must stay closed when n specs register under one lock), and a
fan-out smoke under RAY_TPU_LOCKCHECK=1 asserting zero lock-order
cycles."""

import gc
import multiprocessing
import os
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu._private import protocol


# -- protocol round trip ----------------------------------------------------

def test_batch_envelope_roundtrip():
    a, b = multiprocessing.Pipe()
    msgs = [("exec", {"task_id": b"t1"}), ("func", "fid", b"payload"),
            ("free_segment", "seg", 123, True)]
    protocol.send_batch(a, msgs)
    got = protocol.recv(b)
    assert protocol.is_batch(got)
    assert got == ("batch", msgs)
    a.close()
    b.close()


def test_batch_singleton_and_empty_collapse():
    a, b = multiprocessing.Pipe()
    # A single message ships unwrapped — no envelope overhead, and a
    # receiver that predates the envelope still understands it.
    protocol.send_batch(a, [("result", b"t", True, [], {})])
    assert protocol.recv(b) == ("result", b"t", True, [], {})
    # Empty list: nothing on the wire at all.
    protocol.send_batch(a, [])
    protocol.send(a, ("sentinel",))  # noqa: RTL501 -- deliberate non-catalog verb: proves the empty batch wrote nothing ahead of it
    assert protocol.recv(b) == ("sentinel",)
    a.close()
    b.close()


def test_legacy_msg_batch_still_recognized():
    assert protocol.is_batch(("msg_batch", [("exec", {})]))
    assert protocol.is_batch(("batch", [("exec", {})]))
    assert not protocol.is_batch(("exec", {}))


def test_make_batch():
    one = [("exec", {})]
    assert protocol.make_batch(one) is one[0]
    two = [("exec", {}), ("func", "f", b"")]
    assert protocol.make_batch(two) == ("batch", two)


# -- unbatched-peer interop -------------------------------------------------

def _dial_head(rt):
    """Raw client-protocol connection to the head's TCP listener."""
    from multiprocessing.connection import Client

    addr = protocol.parse_address(rt.tcp_address)
    conn = Client(addr, authkey=rt._authkey)
    protocol.send(conn, ("client_ready", os.urandom(8).hex()))
    ack = protocol.recv(conn)
    assert ack[0] == "client_ack"
    return conn


def _recv_unwrapped(conn, timeout=15.0):
    """Receive messages, unwrapping any batch envelope the head sends."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not conn.poll(0.2):
            continue
        msg = protocol.recv(conn)
        if protocol.is_batch(msg):
            for m in msg[1]:
                yield m
        else:
            yield msg
    raise AssertionError("no reply from head within timeout")


def test_unbatched_peer_interoperates(ray_start_regular):
    """A peer that only ever sends plain (unbatched) messages must work
    against a batching head — old messages remain valid on the wire."""
    import ray_tpu as ray

    rt = ray_start_regular
    conn = _dial_head(rt)
    try:
        oid = os.urandom(16)
        payload = protocol.INLINE, __import__(
            "ray_tpu._private.serialization", fromlist=["x"]
        ).dumps_inline({"v": 42})
        # Plain one-message-per-send traffic, no envelope anywhere.
        protocol.send(conn, ("put", oid, tuple(payload), []))
        protocol.send(conn, ("mget", 7, [oid], 10.0))
        for msg in _recv_unwrapped(conn):
            if msg[0] == "mgot":
                assert msg[1] == 7
                ok, descr = msg[2][0]
                assert ok
                break
        # The driver sees the put through its normal table.
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef

        assert ray.get(ObjectRef(ObjectID(oid))) == {"v": 42}
    finally:
        conn.close()


def test_legacy_batch_envelope_from_peer(ray_start_regular):
    """The pre-envelope "msg_batch" spelling (what an old peer's
    conflation sender emits) must still be unwrapped by the head."""
    from ray_tpu._private import serialization

    rt = ray_start_regular
    conn = _dial_head(rt)
    try:
        oid1, oid2 = os.urandom(16), os.urandom(16)
        d1 = (protocol.INLINE, serialization.dumps_inline("a"))
        d2 = (protocol.INLINE, serialization.dumps_inline("b"))
        protocol.send(conn, ("msg_batch", [
            ("put", oid1, d1, []),
            ("put", oid2, d2, []),
            ("mget", 3, [oid1, oid2], 10.0),
        ]))
        for msg in _recv_unwrapped(conn):
            if msg[0] == "mgot":
                assert msg[1] == 3
                assert [ok for ok, _ in msg[2]] == [True, True]
                break
    finally:
        conn.close()


# -- bulk submission --------------------------------------------------------

def test_bulk_submit_matches_individual_calls(ray_start_regular):
    import ray_tpu as ray
    from ray_tpu.remote_function import _bulk_submit

    @ray.remote
    def add(x, y=0):
        return x + y

    refs = _bulk_submit([(add, (i,), {"y": 10}) for i in range(64)])
    assert ray.get(refs) == [i + 10 for i in range(64)]

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, k):
            self.n += k
            return self.n

    c = Counter.remote()
    # Bulk actor-method submission keeps per-handle FIFO order.
    out = ray.get(_bulk_submit([(c.bump, (1,), None) for _ in range(32)]))
    assert out == list(range(1, 33))


def test_bulk_submit_refcount_race_stays_closed(ray_start_regular):
    """The submit-time ``local_refs += 1`` must land under the same lock
    acquisition that registers the batch: instantly-completing tasks and
    immediate gets must never observe a freed return object, and
    dropping the refs must actually drain the object table."""
    import ray_tpu as ray
    from ray_tpu.remote_function import _bulk_submit

    rt = ray_start_regular

    @ray.remote
    def quick(i):
        return i

    for _round in range(5):
        refs = _bulk_submit([(quick, (i,), None) for i in range(80)])
        assert ray.get(refs) == list(range(80))
        ids = [r.id() for r in refs]
        del refs
        gc.collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with rt.lock:
                live = [oid for oid in ids if oid in rt.objects]
            if not live:
                break
            time.sleep(0.05)
        assert not live, f"{len(live)} return objects never freed"


def test_bulk_submit_from_worker(ray_start_regular):
    """Worker-side bulk path: eligible specs ride DirectCaller.submit_many,
    the rest one ("submit_batch", ...) message."""
    import ray_tpu as ray

    @ray.remote
    def sq(x):
        return x * x

    @ray.remote
    class Fan:
        def run(self, n):
            from ray_tpu.remote_function import _bulk_submit
            import ray_tpu as ray
            return sum(ray.get(_bulk_submit(
                [(sq, (i,), None) for i in range(n)])))

    f = Fan.remote()
    assert ray.get(f.run.remote(40)) == sum(i * i for i in range(40))


# -- fan-out smoke under lockcheck ------------------------------------------

def test_fanout_smoke_under_lockcheck():
    """500-task fan-out + n×n actor calls with the lock-order checker
    installed: the whole batched submit→dispatch→result path must record
    ZERO lock-order cycles."""
    code = textwrap.dedent("""
        import ray_tpu as ray
        from ray_tpu.devtools import lockcheck
        assert lockcheck.enabled(), "env flag did not install lockcheck"
        ray.init(num_cpus=4, num_tpus=0)

        @ray.remote
        def f():
            return None

        assert ray.get([f.remote() for _ in range(500)]) == [None] * 500

        @ray.remote
        class Target:
            def m(self):
                return None

        @ray.remote
        class Caller:
            def call(self, target, n):
                import ray_tpu as ray
                ray.get([target.m.remote() for _ in range(n)])
                return n

        targets = [Target.remote() for _ in range(2)]
        callers = [Caller.remote() for _ in range(2)]
        done = ray.get([c.call.remote(t, 25)
                        for c, t in zip(callers, targets)])
        assert done == [25, 25]
        ray.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        print("FANOUT_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "FANOUT_LOCKCHECK_OK" in proc.stdout
