"""Data-layer tests (reference pattern: python/ray/data/tests)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import data as rd


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    ray.shutdown()


def test_range_map_filter_count(ray8):
    ds = rd.range(100, parallelism=4)
    assert ds.num_blocks() == 4
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 10 == 0)
    assert out.count() == 20
    assert sorted(out.take_all())[:3] == [0, 10, 20]


def test_map_batches_numpy(ray8):
    ds = rd.from_items([{"x": float(i)} for i in range(32)], parallelism=4)

    def double(batch):
        return {"x": batch["x"] * 2}

    out = ds.map_batches(double, batch_format="numpy")
    rows = out.take_all()
    assert sorted(r["x"] for r in rows)[-1] == 62.0


def test_flat_map_and_union(ray8):
    ds = rd.range(5, parallelism=2).flat_map(lambda x: [x, x])
    assert ds.count() == 10
    u = ds.union(rd.range(3, parallelism=1))
    assert u.count() == 13


def test_random_shuffle_preserves_multiset(ray8):
    ds = rd.range(50, parallelism=5)
    sh = ds.random_shuffle(seed=7)
    assert sorted(sh.take_all()) == list(range(50))
    assert sh.take_all() != list(range(50))


def test_sort(ray8):
    ds = rd.from_items([{"k": i % 7, "v": i} for i in range(21)],
                       parallelism=3)
    out = ds.sort(key="k").take_all()
    assert [r["k"] for r in out] == sorted(i % 7 for i in range(21))


def test_split_for_train_shards(ray8):
    ds = rd.range(64, parallelism=4)
    shards = ds.split(4)
    assert len(shards) == 4
    assert all(s.count() == 16 for s in shards)
    union = sorted(sum((s.take_all() for s in shards), []))
    assert union == list(range(64))


def test_iter_batches(ray8):
    ds = rd.from_items([{"x": i} for i in range(10)], parallelism=2)
    batches = list(ds.iter_batches(batch_size=4))
    assert len(batches) == 3
    assert batches[0]["x"].shape == (4,)
    batches = list(ds.iter_batches(batch_size=4, drop_last=True))
    assert len(batches) == 2


def test_parquet_roundtrip(ray8, tmp_path):
    ds = rd.from_items([{"a": i, "b": str(i)} for i in range(12)],
                       parallelism=3)
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 12
    assert sorted(r["a"] for r in back.take_all()) == list(range(12))


def test_csv_json_roundtrip(ray8, tmp_path):
    ds = rd.from_items([{"a": i} for i in range(6)], parallelism=2)
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 6
    ds.write_json(str(tmp_path / "js"))
    assert rd.read_json(str(tmp_path / "js")).count() == 6


def test_stats_and_schema(ray8):
    ds = rd.from_items([{"x": float(i)} for i in range(10)], parallelism=2)
    assert ds.sum("x") == 45.0
    assert ds.mean("x") == 4.5
    assert ds.schema() == {"x": "float"}


def test_lazy_plan_fuses_ops(ray8):
    """Transforms build a plan (no tasks yet); execution fuses the chain
    into one task per block (reference: operator fusion in the streaming
    executor)."""
    ds = rd.range(32, parallelism=4).map(lambda x: x + 1) \
        .filter(lambda x: x % 2 == 0).map(lambda x: x * 10)
    assert len(ds._ops) == 3          # still unexecuted
    assert ds.num_blocks() == 4
    assert sorted(ds.take_all()) == [x * 10 for x in range(2, 34, 2)]


def test_streaming_window_bounds_inflight(ray8):
    """The executor keeps at most DEFAULT_STREAMING_WINDOW block tasks in
    flight: with 3x window blocks, consuming the first row must not have
    executed every block (bulk execution would).

    Tasks are PACED (0.15s): with instant tasks the executed-count
    assertion raced task completion against the driver's wakeup — on a
    fast/idle host a third admission wave could start before next(it)
    returned, tripping the 2x-window bound on identical code (observed
    pre-existing flake, ~1 in 5 full-suite runs).  The pacing gives the
    driver a full wave time of cushion; the timing-free concurrency
    invariant is additionally pinned against the engine's own
    peak_inflight counter."""
    import ray_tpu.data.dataset as dsmod

    marker_dir = "/tmp/rtpu_stream_markers_%d" % __import__("os").getpid()
    import os
    import shutil

    shutil.rmtree(marker_dir, ignore_errors=True)
    os.makedirs(marker_dir)
    n_blocks = dsmod.DEFAULT_STREAMING_WINDOW * 3

    def touch(x):
        import time as _t

        open(os.path.join(marker_dir, "%d_%d" % (x, os.getpid())), "w")
        _t.sleep(0.15)
        return x

    ds = rd.range(n_blocks, parallelism=n_blocks).map(touch)
    it = ds.iter_rows()
    first = next(it)
    assert first == 0
    executed = len(os.listdir(marker_dir))
    assert executed <= 2 * dsmod.DEFAULT_STREAMING_WINDOW, (
        f"{executed} blocks executed after first row; window is "
        f"{dsmod.DEFAULT_STREAMING_WINDOW}")
    rest = list(it)
    assert sorted([first] + rest) == list(range(n_blocks))
    summary = ds._stats.streaming_summary()
    if summary["ops"]:  # streaming engine on: concurrency never exceeded
        cap = summary["inflight_cap"]
        assert all(op["peak_inflight"] <= cap
                   for op in summary["ops"].values()), summary["ops"]
    shutil.rmtree(marker_dir, ignore_errors=True)


def test_repartition_no_driver_collect(ray8):
    ds = rd.range(100, parallelism=7).repartition(4)
    assert ds.num_blocks() == 4
    counts = [ray.get(rd.dataset._count_block.remote(b))
              for b in ds._blocks]
    assert counts == [25, 25, 25, 25]
    assert sorted(ds.take_all()) == list(range(100))


def test_split_lazy_consumed_in_workers(ray8):
    """split() shards are block refs + plan; Train-style workers iterate
    them inside their own processes (no driver round trip for rows)."""
    ds = rd.range(60, parallelism=6).map(lambda x: {"v": x})
    shards = ds.split(3)

    @ray.remote
    def consume(shard):
        total = 0
        rows = 0
        for batch in shard.iter_batches(batch_size=8):
            total += int(batch["v"].sum())
            rows += len(batch["v"])
        return rows, total

    got = ray.get([consume.remote(s) for s in shards], timeout=120)
    assert sum(r for r, _ in got) == 60
    assert sum(t for _, t in got) == sum(range(60))


def test_limit_early_exit(ray8):
    ds = rd.range(1000, parallelism=100)
    out = ds.limit(25).take_all()
    assert out == list(range(25))


def test_arrow_blocks_roundtrip(ray8, tmp_path):
    pa = pytest.importorskip("pyarrow")
    table = pa.Table.from_pylist([{"a": i, "b": i * 0.5} for i in range(40)])
    ds = rd.from_arrow(table, parallelism=4)
    assert ds.count() == 40
    # map_batches in pyarrow format keeps Table blocks end-to-end
    def double(t):
        import pyarrow as pa
        return t.set_column(0, "a", pa.array([x * 2 for x in
                                              t.column("a").to_pylist()]))
    ds2 = ds.map_batches(double, batch_format="pyarrow")
    assert sorted(r["a"] for r in ds2.take_all()) == \
        sorted(i * 2 for i in range(40))
    ds2.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 40


def test_distributed_sort_many_blocks(ray8):
    """Sort outputs P globally-ordered blocks — no single-reducer merge
    (reference: _internal/push_based_shuffle.py + sort.py)."""
    import random

    vals = list(range(500))
    random.Random(7).shuffle(vals)
    ds = rd.from_items(vals, parallelism=8).sort()
    assert ds.num_blocks() > 1            # NOT one merged block
    assert ds.take_all() == sorted(vals)
    ds_desc = rd.from_items(vals, parallelism=8).sort(descending=True)
    assert ds_desc.take_all() == sorted(vals, reverse=True)


def test_sort_by_key_column(ray8):
    rows = [{"k": i % 13, "v": i} for i in range(200)]
    out = rd.from_items(rows, parallelism=6).sort(key="k").take_all()
    assert [r["k"] for r in out] == sorted(r["k"] for r in rows)


def test_groupby_aggregate(ray8):
    rows = [{"g": i % 3, "x": float(i)} for i in range(60)]
    ds = rd.from_items(rows, parallelism=5)
    out = ds.groupby("g").sum("x").take_all()
    got = {r["g"]: r["sum(x)"] for r in out}
    import collections

    want = collections.defaultdict(float)
    for r in rows:
        want[r["g"]] += r["x"]
    assert got == dict(want)
    # count + mean via the generic aggregate()
    out2 = ds.groupby("g").aggregate(rd.Count(), rd.Mean("x")).take_all()
    for r in out2:
        assert r["count()"] == 20
        assert abs(r["mean(x)"] - want[r["g"]] / 20) < 1e-9


def test_groupby_map_groups(ray8):
    rows = [{"g": i % 4, "x": i} for i in range(40)]
    out = (rd.from_items(rows, parallelism=4)
           .groupby("g")
           .map_groups(lambda grp: {"g": grp[0]["g"], "n": len(grp)})
           .take_all())
    assert sorted((r["g"], r["n"]) for r in out) == [(i, 10)
                                                    for i in range(4)]


def test_zip(ray8):
    a = rd.range(50, parallelism=4)
    b = rd.from_items([i * 10 for i in range(50)], parallelism=3)
    out = a.zip(b).take_all()
    assert out == [(i, i * 10) for i in range(50)]


def test_dataset_pipeline_window_repeat(ray8):
    ds = rd.range(40, parallelism=8)
    pipe = ds.window(blocks_per_window=2).map(lambda x: x * 2)
    rows = list(pipe.iter_rows())
    assert sorted(rows) == [x * 2 for x in range(40)]
    pipe2 = rd.range(10, parallelism=2).repeat(3)
    assert pipe2.count() == 30
    shards = rd.range(20, parallelism=4).window(
        blocks_per_window=2).split(2)
    total = sum(p.count() for p in shards)
    assert total == 20
