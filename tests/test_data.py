"""Data-layer tests (reference pattern: python/ray/data/tests)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import data as rd


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    ray.shutdown()


def test_range_map_filter_count(ray8):
    ds = rd.range(100, parallelism=4)
    assert ds.num_blocks() == 4
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 10 == 0)
    assert out.count() == 20
    assert sorted(out.take_all())[:3] == [0, 10, 20]


def test_map_batches_numpy(ray8):
    ds = rd.from_items([{"x": float(i)} for i in range(32)], parallelism=4)

    def double(batch):
        return {"x": batch["x"] * 2}

    out = ds.map_batches(double, batch_format="numpy")
    rows = out.take_all()
    assert sorted(r["x"] for r in rows)[-1] == 62.0


def test_flat_map_and_union(ray8):
    ds = rd.range(5, parallelism=2).flat_map(lambda x: [x, x])
    assert ds.count() == 10
    u = ds.union(rd.range(3, parallelism=1))
    assert u.count() == 13


def test_random_shuffle_preserves_multiset(ray8):
    ds = rd.range(50, parallelism=5)
    sh = ds.random_shuffle(seed=7)
    assert sorted(sh.take_all()) == list(range(50))
    assert sh.take_all() != list(range(50))


def test_sort(ray8):
    ds = rd.from_items([{"k": i % 7, "v": i} for i in range(21)],
                       parallelism=3)
    out = ds.sort(key="k").take_all()
    assert [r["k"] for r in out] == sorted(i % 7 for i in range(21))


def test_split_for_train_shards(ray8):
    ds = rd.range(64, parallelism=4)
    shards = ds.split(4)
    assert len(shards) == 4
    assert all(s.count() == 16 for s in shards)
    union = sorted(sum((s.take_all() for s in shards), []))
    assert union == list(range(64))


def test_iter_batches(ray8):
    ds = rd.from_items([{"x": i} for i in range(10)], parallelism=2)
    batches = list(ds.iter_batches(batch_size=4))
    assert len(batches) == 3
    assert batches[0]["x"].shape == (4,)
    batches = list(ds.iter_batches(batch_size=4, drop_last=True))
    assert len(batches) == 2


def test_parquet_roundtrip(ray8, tmp_path):
    ds = rd.from_items([{"a": i, "b": str(i)} for i in range(12)],
                       parallelism=3)
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 12
    assert sorted(r["a"] for r in back.take_all()) == list(range(12))


def test_csv_json_roundtrip(ray8, tmp_path):
    ds = rd.from_items([{"a": i} for i in range(6)], parallelism=2)
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 6
    ds.write_json(str(tmp_path / "js"))
    assert rd.read_json(str(tmp_path / "js")).count() == 6


def test_stats_and_schema(ray8):
    ds = rd.from_items([{"x": float(i)} for i in range(10)], parallelism=2)
    assert ds.sum("x") == 45.0
    assert ds.mean("x") == 4.5
    assert ds.schema() == {"x": "float"}
