"""Data-plane fast path: pooled connections, striped byte-range fetch,
zero-copy receive into shm, and the head staying out of the payload path.

Reference analog: the object manager moves objects directly between nodes
in bounded chunks with multiple transfers in flight
(``src/ray/object_manager/object_manager.h:117,206``,
``object_buffer_pool.h``); the control plane brokers locations only.

Covered here:
- striped ``fetch_range`` reassembly is byte-identical across randomized
  sizes around the stripe threshold;
- N concurrent pulls from one peer genuinely stream in parallel
  (deterministic gate, no timing);
- old-verb peer interop: a peer speaking only ``fetch`` still serves a
  pooled puller, and unknown verbs are never sent to it;
- server death mid-stream surfaces a transport error, the broken
  connection is evicted in isolation (later fetches redial), and the
  driver's head-relay fallback engages and is counted;
- the acceptance micro: ≥2x aggregate throughput for 4 concurrent 64 MB
  pulls from one peer vs. the serial single-connection baseline over a
  paced (latency-bound) link — pacing makes the assertion independent of
  this machine's loopback memory bandwidth while still exercising the
  real multiple-transfers-in-flight machinery;
- a two-node-agent cluster where a ≥100 MB result (both node-homed and
  HEAD-homed) reaches remote consumers with the
  ``relayed_segments``/``brokered_parts`` fallback counters flat;
- the concurrency cases re-run under the lockcheck instrumentation
  (the RAY_TPU_LOCKCHECK machinery) with zero lock-order cycles.
"""

import os
import random
import tempfile
import threading
import time

import numpy as np
import pytest

from multiprocessing.connection import Listener

from ray_tpu._private import object_transfer as ot
from ray_tpu._private import protocol, serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.shm_store import ShmStore

AUTH = b"object-transfer-test"


# --------------------------------------------------------------- helpers --

def _make_segment(store: ShmStore, payload: bytes) -> str:
    """A real shm segment holding one bytes buffer; returns its name."""
    res = serialization.dumps_adaptive(
        np.frombuffer(payload, dtype=np.uint8), 0)
    assert res[0] == "parts"
    name, _size = store.create_from_parts(ObjectID.from_random(), res[1],
                                          res[2])
    return name


def _value_of(buf) -> bytes:
    meta, bufs = ot.parse_segment_bytes(buf)
    return serialization.loads(meta, bufs).tobytes()


class _Server:
    """A loopback object server over a real store, with optional
    per-connection wrapping (pacing, gating, chaos)."""

    def __init__(self, store, wrap=None, serve=ot.serve_connection):
        self.store = store
        self._wrap = wrap or (lambda conn: conn)
        self._serve = serve
        self._listener = Listener(("127.0.0.1", 0), "AF_INET",
                                  backlog=16, authkey=AUTH)
        self.addr = f"tcp://127.0.0.1:{self._listener.address[1]}"
        self._stopped = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stopped:
            try:
                conn = self._listener.accept()
            except Exception:
                return
            threading.Thread(target=self._serve,
                             args=(self._wrap(conn), self.store),
                             daemon=True).start()

    def close(self):
        self._stopped = True
        try:
            self._listener.close()
        except Exception:
            pass


@pytest.fixture
def shm_store():
    d = tempfile.mkdtemp(prefix="rtpu-ot-", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    store = ShmStore(shm_dir=d, session_id="ottest")
    yield store
    import shutil

    shutil.rmtree(d, ignore_errors=True)


# ------------------------------------------------- striped reassembly ----

def test_striped_fetch_reassembles_byte_identical(shm_store):
    """Randomized sizes around the stripe threshold: whole-segment fetch,
    striped fetch and the zero-copy pull_to_segment path must all yield
    identical values."""
    thr = 256 * 1024
    rng = random.Random(7)
    sizes = [1, thr // 2, thr - 64, thr - 1, thr, thr + 1, thr + 177,
             2 * thr, 3 * thr + rng.randrange(thr)]
    server = _Server(shm_store)
    puller = ot.ObjectPuller(AUTH, pool_size=4, stripe_threshold=thr)
    local = ShmStore(shm_dir=shm_store._dir, session_id="otlocal")
    try:
        for n in sizes:
            payload = rng.randbytes(n)
            name = _make_segment(shm_store, payload)
            plain = puller.fetch("peer", server.addr, name)
            striped = puller.fetch("peer", server.addr, name,
                                   caps=("fetch_range",))
            assert bytes(striped) == bytes(plain), f"size {n}"
            assert _value_of(striped) == payload, f"size {n}"
            seg = ot.pull_to_segment(puller, local, "peer", server.addr,
                                     name, caps=("fetch_range",))
            meta, bufs = seg.raw_parts()
            assert serialization.loads(meta, bufs).tobytes() == payload
            seg.close()
    finally:
        puller.close()
        server.close()


def test_reserve_over_capacity_falls_back_to_heap(shm_store):
    """A receive that cannot fit under the store's capacity must not
    sparsely overcommit tmpfs: reserve_recv raises MemoryError and
    pull_to_segment completes the transfer into a heap buffer instead."""
    server = _Server(shm_store)
    payload = random.Random(23).randbytes(1 << 20)
    name = _make_segment(shm_store, payload)
    capped = ShmStore(shm_dir=shm_store._dir, session_id="otcap",
                      capacity=64 * 1024)
    with pytest.raises(MemoryError):
        capped.reserve_recv("seg", 1 << 20)
    puller = ot.ObjectPuller(AUTH, pool_size=2, stripe_threshold=0)
    try:
        seg = ot.pull_to_segment(puller, capped, "peer", server.addr, name)
        meta, bufs = seg.raw_parts()
        assert serialization.loads(meta, bufs).tobytes() == payload
        assert isinstance(seg._mm, bytearray)  # heap fallback engaged
        seg.close()
        assert not any(".recv-" in f for f in os.listdir(shm_store._dir))
    finally:
        puller.close()
        server.close()
        capped.cleanup()


def test_reserve_commit_recv_leaves_no_files(shm_store):
    mm = shm_store.reserve_recv("seg-x", 4096)
    assert not any(".recv-" in f for f in os.listdir(shm_store._dir)), \
        "reservation left a linked file"
    mm[:5] = b"hello"
    seg = shm_store.commit_recv("seg-x", mm, 4096)
    assert bytes(seg._mm[:5]) == b"hello"
    seg.close()
    mm2 = shm_store.reserve_recv("seg-y", 4096)
    shm_store.abort_recv(mm2)
    with pytest.raises(ValueError):
        shm_store.reserve_recv("seg-z", 0)


# ------------------------------------------- parallel streams from peer --

class _GateConn:
    """Blocks every payload-sized send until ``need`` distinct
    connections have reached one — a deterministic proof that streams
    overlap in time (a serialized puller would deadlock the gate and
    fail fast instead of flaking on timing)."""

    def __init__(self, conn, gate):
        self._conn = conn
        self._gate = gate

    def send_bytes(self, data):
        if len(data) >= 65536:
            self._gate.arrive(id(self._conn))
        self._conn.send_bytes(data)

    def __getattr__(self, item):
        return getattr(self._conn, item)


class _Gate:
    def __init__(self, need: int):
        self._need = need
        self._seen = set()
        self._lock = threading.Lock()
        self._ev = threading.Event()

    def arrive(self, key):
        with self._lock:
            self._seen.add(key)
            if len(self._seen) >= self._need:
                self._ev.set()
        if not self._ev.wait(10):
            raise RuntimeError("streams did not overlap")


def test_concurrent_pulls_stream_in_parallel(shm_store):
    """Two concurrent fetches of different segments from ONE peer must
    stream simultaneously on separate pooled connections."""
    gate = _Gate(2)
    server = _Server(shm_store, wrap=lambda c: _GateConn(c, gate))
    rng = random.Random(11)
    names = [_make_segment(shm_store, rng.randbytes(2 << 20))
             for _ in range(2)]
    puller = ot.ObjectPuller(AUTH, pool_size=4, stripe_threshold=0)
    results = {}

    def pull(name):
        results[name] = _value_of(puller.fetch("peer", server.addr, name))

    try:
        threads = [threading.Thread(target=pull, args=(n,)) for n in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 2
        pool = puller._pools["peer"]
        assert pool.total >= 2, "pulls shared one connection"
    finally:
        puller.close()
        server.close()


# ------------------------------------------------- old-verb peer interop --

def _old_serve_connection(conn, store):
    """The pre-pool object server, verbatim: speaks ONLY fetch/close and
    silently ignores anything else (which is why new verbs must be gated
    on advertised caps, never probed)."""
    unknown = getattr(store, "_unknown_verbs", None)
    try:
        while True:
            msg = protocol.recv(conn)
            if msg[0] == "fetch":
                name = msg[1]
                try:
                    seg = store.attach(name)
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                try:
                    mv = memoryview(seg._mm)
                    total = ot._true_extent(mv, name)
                    protocol.send(conn, ("ok", total))
                    for off in range(0, total, ot.CHUNK):
                        conn.send_bytes(mv[off:min(off + ot.CHUNK, total)])
                finally:
                    del mv
                    seg.close()
            elif msg[0] == "close":
                return
            elif unknown is not None:
                unknown.append(msg[0])
    except (EOFError, OSError, TypeError):
        return
    finally:
        try:
            conn.close()
        except Exception:
            pass


def test_old_verb_peer_interop(shm_store):
    """A peer that only speaks the original ``fetch`` verb (empty caps)
    serves a pooled puller correctly — and never receives a verb it
    doesn't know."""
    shm_store._unknown_verbs = []
    server = _Server(shm_store, serve=_old_serve_connection)
    payload = random.Random(3).randbytes(600 * 1024)
    name = _make_segment(shm_store, payload)
    # A striping-eager puller whose threshold the segment EXCEEDS: with
    # no advertised caps it must still use plain fetch.
    puller = ot.ObjectPuller(AUTH, pool_size=3, stripe_threshold=128 * 1024)
    try:
        got = puller.fetch("old-peer", server.addr, name, caps=())
        assert _value_of(got) == payload
        local = ShmStore(shm_dir=shm_store._dir, session_id="otlocal2")
        seg = ot.pull_to_segment(puller, local, "old-peer", server.addr,
                                 name, caps=())
        meta, bufs = seg.raw_parts()
        assert serialization.loads(meta, bufs).tobytes() == payload
        seg.close()
        assert shm_store._unknown_verbs == [], \
            f"sent unknown verbs to an old peer: {shm_store._unknown_verbs}"
    finally:
        puller.close()
        server.close()


# ------------------------------------------ failure isolation / recovery --

class _DieAfterFirstChunk:
    """Kills the connection after the first payload chunk of the FIRST
    stream served by this server process."""

    armed = True

    def __init__(self, conn, owner):
        self._conn = conn
        self._owner = owner

    def send_bytes(self, data):
        if len(data) >= ot.CHUNK and self._owner["armed"]:
            self._owner["armed"] = False
            self._conn.close()
            raise OSError("injected mid-stream death")
        self._conn.send_bytes(data)

    def __getattr__(self, item):
        return getattr(self._conn, item)


def test_mid_stream_death_is_isolated_and_recovers(shm_store):
    """A connection dying mid-stream fails that fetch with a transport
    error, evicts ONLY that connection, and a retry on the same pool
    redials and succeeds.  A missing segment surfaces ObjectLostError."""
    from ray_tpu import exceptions as exc

    owner = {"armed": True}
    server = _Server(shm_store,
                     wrap=lambda c: _DieAfterFirstChunk(c, owner))
    payload = random.Random(5).randbytes(3 << 20)
    name = _make_segment(shm_store, payload)
    puller = ot.ObjectPuller(AUTH, pool_size=2, stripe_threshold=0)
    try:
        with pytest.raises((OSError, EOFError)):
            puller.fetch("peer", server.addr, name)
        # Pool evicted just the broken connection; the retry dials a
        # fresh one and completes.
        got = puller.fetch("peer", server.addr, name)
        assert _value_of(got) == payload
        with pytest.raises(exc.ObjectLostError):
            puller.fetch("peer", server.addr, "rtpu-ottest-missing")
    finally:
        puller.close()
        server.close()


# -------------------------------------------------- the acceptance micro --

class _PacedConn:
    """Fixed per-send pacing: emulates a latency/bandwidth-bound link, the
    regime where multiple transfers in flight beat one serial stream —
    and the assertion stays independent of this machine's loopback
    memory bandwidth."""

    def __init__(self, conn, delay):
        self._conn = conn
        self._delay = delay

    def send_bytes(self, data):
        if len(data) >= ot.CHUNK:
            time.sleep(self._delay)
        self._conn.send_bytes(data)

    def __getattr__(self, item):
        return getattr(self._conn, item)


@pytest.mark.slow  # perf A/B (~5s); striped/pooled CORRECTNESS keeps its
# tier-1 reps via the reassembly + concurrency tests above
def test_four_concurrent_64mb_pulls_2x_over_serial(shm_store):
    """Acceptance micro: 4 concurrent 64 MB pulls from one peer over a
    paced link — the pooled + striped puller must show ≥2x aggregate
    throughput over the serial single-connection baseline (the pre-pool
    behavior: one connection per peer, one whole-segment stream at a
    time)."""
    server = _Server(shm_store, wrap=lambda c: _PacedConn(c, 0.012))
    base = np.arange(8_000_000, dtype=np.int64).tobytes()  # 64 MB
    names = [_make_segment(shm_store, base) for _ in range(4)]

    def timed(puller, caps):
        errs = []

        def pull(name):
            try:
                got = puller.fetch("peer", server.addr, name, caps=caps)
                assert _value_of(got) == base
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=pull, args=(n,)) for n in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        return time.perf_counter() - t0

    serial = ot.ObjectPuller(AUTH, pool_size=1, stripe_threshold=0)
    pooled = ot.ObjectPuller(AUTH, pool_size=4,
                             stripe_threshold=16 * 1024 * 1024)
    try:
        best = 0.0
        for _attempt in range(3):  # damp shared-CI scheduling noise
            t_serial = timed(serial, ())
            t_pooled = timed(pooled, ("fetch_range",))
            best = max(best, t_serial / t_pooled)
            if best >= 2.0:
                break
        assert best >= 2.0, (
            f"pooled/striped path only {best:.2f}x over serial baseline")
    finally:
        serial.close()
        pooled.close()
        server.close()


# --------------------------------------------- lockcheck on concurrency --

def test_concurrent_striped_pulls_lockcheck_clean(shm_store):
    """The concurrency cases under the RAY_TPU_LOCKCHECK instrumentation:
    pooled + striped concurrent pulls must record zero lock-order
    cycles."""
    from ray_tpu.devtools import lockcheck

    lockcheck.install(raise_on_cycle=False)
    lockcheck.clear()
    try:
        server = _Server(shm_store)
        rng = random.Random(13)
        payloads = [rng.randbytes(700 * 1024) for _ in range(3)]
        names = [_make_segment(shm_store, p) for p in payloads]
        puller = ot.ObjectPuller(AUTH, pool_size=3,
                                 stripe_threshold=128 * 1024)
        local = ShmStore(shm_dir=shm_store._dir, session_id="otlock")
        results = {}

        def pull(i, name):
            seg = ot.pull_to_segment(puller, local, "peer", server.addr,
                                     name, caps=("fetch_range",))
            meta, bufs = seg.raw_parts()
            results[i] = serialization.loads(meta, bufs).tobytes()
            seg.close()

        threads = [threading.Thread(target=pull, args=(i, n))
                   for i, n in enumerate(names)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert [results[i] for i in range(3)] == payloads
        puller.close()
        server.close()
        assert lockcheck.violations() == [], lockcheck.violations()
        lockcheck.assert_acyclic()
    finally:
        lockcheck.uninstall()


# ------------------------------------------- cluster: head out of the way --

@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_num_cpus=2)
    yield c
    c.shutdown()


def test_big_results_skip_head_payload_path(cluster):
    """A ≥100 MB result reaches remote consumers without the head ever
    relaying payload bytes, whether the segment is homed on a NODE store
    or on the HEAD's own store (the head now runs an object server for
    itself): ``brokered_parts``/``relayed_segments`` stay flat."""
    import ray_tpu as ray
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    n1 = cluster.add_node(num_cpus=2, external=True)
    n2 = cluster.add_node(num_cpus=2, external=True)

    @ray.remote
    def make(n):
        return np.arange(n, dtype=np.int64)

    @ray.remote
    def total(x):
        return int(x.sum())

    n_elems = 13_000_000  # 104 MB of int64
    expect = int(np.arange(n_elems, dtype=np.int64).sum())

    # Warm both nodes' worker pools before baselining the counters.
    ray.get([
        total.options(scheduling_strategy=NA(node_id=nid)).remote(
            make.options(scheduling_strategy=NA(node_id=nid)).remote(8))
        for nid in (n1, n2)
    ])
    base_relay = cluster.rt.relayed_segments
    base_broker = cluster.rt.brokered_parts

    # Node-homed result: produced on node1, consumed on node2 AND by the
    # driver — direct pulls from node1's object server.
    ref = make.options(scheduling_strategy=NA(node_id=n1)).remote(n_elems)
    s = ray.get(
        total.options(scheduling_strategy=NA(node_id=n2)).remote(ref),
        timeout=180)
    assert s == expect
    got = ray.get(ref, timeout=120)
    assert int(got.sum()) == expect
    del got, ref

    # HEAD-homed result: produced by a head-local worker, consumed on an
    # external node — previously a brokered getparts relay through the
    # head's control-plane connection, now a direct pull from the head's
    # own object server.
    head_id = cluster.rt.head_node.node_id.hex()
    head_ref = make.options(
        scheduling_strategy=NA(node_id=head_id)).remote(n_elems)
    ray.wait([head_ref], num_returns=1, timeout=120)
    s2 = ray.get(
        total.options(scheduling_strategy=NA(node_id=n2)).remote(head_ref),
        timeout=180)
    assert s2 == expect
    del head_ref

    assert cluster.rt.relayed_segments == base_relay, \
        "head relayed segment payload bytes"
    assert cluster.rt.brokered_parts == base_broker, \
        "a consumer fell back to head-brokered getparts"


def test_pull_failure_falls_back_to_head_relay(cluster, monkeypatch):
    """When the direct pull path breaks (object server unreachable), the
    driver's get still succeeds via the head relay — and the fallback is
    observable through ``relayed_segments``."""
    import ray_tpu as ray
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    n1 = cluster.add_node(num_cpus=2, external=True)

    @ray.remote
    def make(n):
        return np.arange(n, dtype=np.int64)

    ref = make.options(scheduling_strategy=NA(node_id=n1)).remote(500_000)
    ray.wait([ref], num_returns=1, timeout=60)

    def broken_fetch(*args, **kwargs):
        raise OSError("injected: object server unreachable")

    monkeypatch.setattr(cluster.rt._puller, "fetch", broken_fetch)
    base_relay = cluster.rt.relayed_segments
    got = ray.get(ref, timeout=60)
    assert int(got.sum()) == int(
        np.arange(500_000, dtype=np.int64).sum())
    assert cluster.rt.relayed_segments > base_relay, \
        "broken direct pull did not engage the head relay"
