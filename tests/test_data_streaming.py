"""Streaming execution engine: actor-pool map operator, per-op stats,
bounded in-flight memory (reference:
python/ray/data/_internal/execution/streaming_executor.py:35,
execution/operators/actor_pool_map_operator.py, _internal/stats.py)."""
import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import data


@pytest.fixture
def cluster():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray.shutdown()


def test_actor_pool_map_is_stateful(cluster):
    """compute="actors" with a CLASS fn: ONE instance per pool actor
    carries state across blocks (the point of the actor-pool operator)."""

    class Tagger:
        def __init__(self):
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return [{"v": int(r["v"]), "call": self.calls}
                    for r in _rows(batch)]

    def _rows(batch):
        if isinstance(batch, dict):
            n = len(next(iter(batch.values())))
            return [{k: batch[k][i] for k in batch} for i in range(n)]
        return batch

    ds = data.from_items([{"v": i} for i in range(24)], parallelism=6)
    out = ds.map_batches(Tagger, compute="actors", concurrency=1,
                         batch_format="rows").take_all()
    assert sorted(r["v"] for r in out) == list(range(24))
    # One actor processed all 6 blocks: its call counter reached 6.
    assert max(r["call"] for r in out) == 6


def test_actor_pool_concurrency_spreads_blocks(cluster):
    class Who:
        def __call__(self, batch):
            import os
            return [{"pid": os.getpid()} for _ in batch]

    ds = data.from_items(list(range(32)), parallelism=8)
    out = ds.map_batches(Who, compute="actors", concurrency=2,
                         batch_format="rows").take_all()
    assert len({r["pid"] for r in out}) == 2  # both pool actors used


def test_stats_reports_per_op_accounting(cluster):
    ds = (data.from_items([{"v": i} for i in range(100)], parallelism=4)
          .map(lambda r: {"v": r["v"] * 2})
          .filter(lambda r: r["v"] % 4 == 0))
    assert ds.take_all()  # drives execution
    s = ds.stats()
    assert "map" in s and "filter" in s, s
    assert "4 blocks" in s, s
    summary = ds._stats.summary()
    assert summary["map"]["rows_out"] == 100
    assert summary["filter"]["rows_out"] == 50
    assert summary["map"]["wall_s"] >= 0


def test_mixed_task_and_actor_stages(cluster):
    class AddTen:
        def __call__(self, batch):
            return [r + 10 for r in batch]

    ds = (data.from_items(list(range(20)), parallelism=4)
          .map(lambda x: x * 2)
          .map_batches(AddTen, compute="actors", concurrency=1,
                       batch_format="rows")
          .map(lambda x: x + 1))
    assert sorted(ds.take_all()) == sorted(2 * i + 11 for i in range(20))
    s = ds.stats()
    assert "map_batches(actors)" in s, s


def test_whole_block_batches_are_zero_copy(cluster):
    """iter_batches(batch_size=None) yields native blocks; tensor blocks
    come back as views over the store mapping (no row materialization)."""
    block = {"a": np.arange(4096, dtype=np.float32)}
    ds = data.from_items(list(range(8)), parallelism=4).map_batches(
        lambda b: dict(block))
    batches = list(ds.iter_batches(batch_size=None))
    assert len(batches) == 4
    for b in batches:
        assert isinstance(b, dict)
        np.testing.assert_array_equal(b["a"], block["a"])
        # Zero-copy: the array is a VIEW over the shm mapping — and
        # read-only, so a consumer's in-place mutation cannot corrupt
        # the stored block for later epochs.
        assert not b["a"].flags["OWNDATA"]
        assert not b["a"].flags["WRITEABLE"]
        with pytest.raises(ValueError):
            b["a"][0] = 1.0


def test_windowed_pipeline_bounds_store_usage(cluster):
    """A windowed pipeline over data >> the bound must keep peak store
    usage under a fraction of the total data size (the backpressure
    guarantee the streaming executor exists for)."""
    from ray_tpu._private import api_internal

    rt = api_internal.get_runtime()
    block_bytes = 1 << 20  # 1 MB per block after map_batches
    n_windows, blocks_per_window = 10, 2
    total = n_windows * blocks_per_window * block_bytes

    def inflate(batch):
        return {"a": np.zeros(block_bytes // 8, dtype=np.float64)}

    windows = [
        data.from_items(list(range(blocks_per_window)),
                        parallelism=blocks_per_window)
        .map_batches(inflate)
        for _ in range(n_windows)
    ]
    pipe = data.DatasetPipeline(windows)
    peak = 0
    consumed = 0
    for batch in pipe.iter_batches(batch_size=10**9):
        consumed += batch["a"].nbytes
        peak = max(peak, rt.shm._node_used())
    assert consumed == total
    # Peak in-store bytes must stay well under the full dataset: one
    # window (2 MB) + streaming slack, not 20 MB.
    assert peak <= total * 0.45, (peak, total)
