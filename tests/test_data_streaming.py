"""Streaming execution engine: the backpressured operator-graph executor
(byte-budgeted admission, fusion, failure isolation, legacy-path A/B),
the actor-pool map operator, and per-op stats (reference:
python/ray/data/_internal/execution/streaming_executor.py:35,
execution/operators/actor_pool_map_operator.py, _internal/stats.py)."""
import contextlib
import threading
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import data


@pytest.fixture
def cluster():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray.shutdown()


@contextlib.contextmanager
def _fresh_cluster(**kwargs):
    rt = ray.init(**kwargs)
    try:
        yield rt
    finally:
        ray.shutdown()


def test_actor_pool_map_is_stateful(cluster):
    """compute="actors" with a CLASS fn: ONE instance per pool actor
    carries state across blocks (the point of the actor-pool operator)."""

    class Tagger:
        def __init__(self):
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return [{"v": int(r["v"]), "call": self.calls}
                    for r in _rows(batch)]

    def _rows(batch):
        if isinstance(batch, dict):
            n = len(next(iter(batch.values())))
            return [{k: batch[k][i] for k in batch} for i in range(n)]
        return batch

    ds = data.from_items([{"v": i} for i in range(24)], parallelism=6)
    out = ds.map_batches(Tagger, compute="actors", concurrency=1,
                         batch_format="rows").take_all()
    assert sorted(r["v"] for r in out) == list(range(24))
    # One actor processed all 6 blocks: its call counter reached 6.
    assert max(r["call"] for r in out) == 6


def test_actor_pool_concurrency_spreads_blocks(cluster):
    class Who:
        def __call__(self, batch):
            import os
            return [{"pid": os.getpid()} for _ in batch]

    ds = data.from_items(list(range(32)), parallelism=8)
    out = ds.map_batches(Who, compute="actors", concurrency=2,
                         batch_format="rows").take_all()
    assert len({r["pid"] for r in out}) == 2  # both pool actors used


def test_stats_reports_per_op_accounting(cluster):
    ds = (data.from_items([{"v": i} for i in range(100)], parallelism=4)
          .map(lambda r: {"v": r["v"] * 2})
          .filter(lambda r: r["v"] % 4 == 0))
    assert ds.take_all()  # drives execution
    s = ds.stats()
    assert "map" in s and "filter" in s, s
    assert "4 blocks" in s, s
    summary = ds._stats.summary()
    assert summary["map"]["rows_out"] == 100
    assert summary["filter"]["rows_out"] == 50
    assert summary["map"]["wall_s"] >= 0


def test_mixed_task_and_actor_stages(cluster):
    class AddTen:
        def __call__(self, batch):
            return [r + 10 for r in batch]

    ds = (data.from_items(list(range(20)), parallelism=4)
          .map(lambda x: x * 2)
          .map_batches(AddTen, compute="actors", concurrency=1,
                       batch_format="rows")
          .map(lambda x: x + 1))
    assert sorted(ds.take_all()) == sorted(2 * i + 11 for i in range(20))
    s = ds.stats()
    assert "map_batches(actors)" in s, s


def test_whole_block_batches_are_zero_copy(cluster):
    """iter_batches(batch_size=None) yields native blocks; tensor blocks
    come back as views over the store mapping (no row materialization)."""
    block = {"a": np.arange(4096, dtype=np.float32)}
    ds = data.from_items(list(range(8)), parallelism=4).map_batches(
        lambda b: dict(block))
    batches = list(ds.iter_batches(batch_size=None))
    assert len(batches) == 4
    for b in batches:
        assert isinstance(b, dict)
        np.testing.assert_array_equal(b["a"], block["a"])
        # Zero-copy: the array is a VIEW over the shm mapping — and
        # read-only, so a consumer's in-place mutation cannot corrupt
        # the stored block for later epochs.
        assert not b["a"].flags["OWNDATA"]
        assert not b["a"].flags["WRITEABLE"]
        with pytest.raises(ValueError):
            b["a"][0] = 1.0


def test_windowed_pipeline_bounds_store_usage(cluster):
    """A windowed pipeline over data >> the bound must keep peak store
    usage under a fraction of the total data size (the backpressure
    guarantee the streaming executor exists for)."""
    from ray_tpu._private import api_internal

    rt = api_internal.get_runtime()
    block_bytes = 1 << 20  # 1 MB per block after map_batches
    n_windows, blocks_per_window = 10, 2
    total = n_windows * blocks_per_window * block_bytes

    def inflate(batch):
        return {"a": np.zeros(block_bytes // 8, dtype=np.float64)}

    windows = [
        data.from_items(list(range(blocks_per_window)),
                        parallelism=blocks_per_window)
        .map_batches(inflate)
        for _ in range(n_windows)
    ]
    pipe = data.DatasetPipeline(windows)
    peak = 0
    consumed = 0
    for batch in pipe.iter_batches(batch_size=10**9):
        consumed += batch["a"].nbytes
        peak = max(peak, rt.shm._node_used())
    assert consumed == total
    # Peak in-store bytes must stay well under the full dataset: one
    # window (2 MB) + streaming slack, not 20 MB.
    assert peak <= total * 0.45, (peak, total)


# ---------------------------------------------------------------------------
# Backpressured operator-graph engine (ray_tpu/data/streaming_executor.py)
# ---------------------------------------------------------------------------

_BLK = 2 * 1024 * 1024          # inflated block payload
_BUDGET = 6 * 1024 * 1024       # < 4 blocks: forces backpressure


def _inflate(batch):
    return {"a": np.zeros(_BLK // 8, dtype=np.float64)}


def _slow_block(batch):
    time.sleep(0.3)
    return batch


def _paced_pipeline():
    """Fast read -> inflate -> slow consumer.  The distinct num_cpus
    values are fusion boundaries AND serialize each operator (one task
    at a time on a 6-CPU cluster), so completion order — and therefore
    the engine's byte accounting — is deterministic."""
    return (data.from_items(list(range(10)), parallelism=10)
            .map_batches(_inflate, num_cpus=4)
            .map_batches(_slow_block, num_cpus=5))


def _consume_with_store_sampler(ds, rt):
    """Drain ``ds`` while a sampler thread records peak store usage IN
    EXCESS of what the consumer has already been handed (yielded refs
    stay alive for memoization; only bytes the ENGINE is sitting on
    count against it)."""
    state = {"yielded": 0, "peak": 0, "stop": False}

    def sample():
        while not state["stop"]:
            ex = rt.shm._node_used() - state["yielded"]
            if ex > state["peak"]:
                state["peak"] = ex
            time.sleep(0.005)

    th = threading.Thread(target=sample, daemon=True)
    th.start()
    n = 0
    for _ref in ds._stream_refs():
        state["yielded"] += _BLK
        n += 1
    state["stop"] = True
    th.join(timeout=5)
    return n, state["peak"]


def test_backpressure_peak_bytes_under_budget_legacy_exceeds():
    """Acceptance: a paced two-operator pipeline (slow map behind fast
    read) keeps peak in-flight bytes <= the configured
    data_memory_budget under the streaming engine, while the legacy
    windowed path provably exceeds it (it bounds block COUNT, so the
    window's 2 MB outputs pile up past the budget)."""
    store = {"object_store_memory": 256 << 20}
    with _fresh_cluster(num_cpus=6, _system_config=dict(
            store, data_memory_budget=_BUDGET)) as rt:
        ds = _paced_pipeline()
        n, store_peak = _consume_with_store_sampler(ds, rt)
        assert n == 10
        s = ds._stats.streaming_summary()
        assert s["budget_bytes"] == _BUDGET
        assert s["peak_inflight_bytes"] <= _BUDGET, s
        assert s["backpressure_stalls"] > 0, s
        # Real store bytes corroborate the engine accounting (slack for
        # segment headers/page rounding).
        assert store_peak <= _BUDGET * 1.25, (store_peak, _BUDGET)
        assert "Streaming executor" in ds.stats()

    with _fresh_cluster(num_cpus=6, _system_config=dict(
            store, streaming_executor=False)) as rt:
        # Warm the worker pool so the legacy window runs at full
        # concurrency (the measurement needs its worst case).
        @ray.remote
        def _noop():
            return None

        ray.get([_noop.remote() for _ in range(6)])
        ds = _paced_pipeline()
        n, store_peak = _consume_with_store_sampler(ds, rt)
        assert n == 10
        assert ds._stats.streaming_summary()["peak_inflight_bytes"] == 0
        assert store_peak > _BUDGET, (
            f"legacy path stayed under the budget ({store_peak} <= "
            f"{_BUDGET}); the backpressure scenario proves nothing")


def _overlap_pipeline():
    """3-stage heterogeneous paced pipeline; the equal num_cpus=0
    requests both fuse the stages into ONE task per block and keep the
    paced sleeps off the CPU slots (load-independent timing)."""

    def s1(b):
        time.sleep(0.10)
        return b

    def s2(b):
        time.sleep(0.04)
        return b

    def s3(b):
        time.sleep(0.06)
        return b

    return (data.from_items(list(range(32)), parallelism=32)
            .map_batches(s1, batch_format="rows", num_cpus=0)
            .map_batches(s2, batch_format="rows", num_cpus=0)
            .map_batches(s3, batch_format="rows", num_cpus=0))


def _best_of(n, fn):
    best = None
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


@pytest.mark.slow  # ~20s perf A/B; per the PR 6/7 convention perf
# micros ride the slow tier — engine correctness keeps sub-second/
# few-second tier-1 reps (backpressure, fusion, failure tests below).
def test_streaming_overlap_micro_beats_legacy():
    """Acceptance: >=1.5x on the paced 3-stage pipeline, best-of-3.
    The streaming engine admits by BYTES (tiny blocks -> the whole
    dataset pipelines, capped only by data_max_inflight_tasks = cluster
    CPUs); the legacy path is stuck at its 8-chain window regardless of
    how little memory the blocks need.  Paced sleeps + num_cpus=0 make
    both runs scheduler-bound, not load-bound (calibrated 1.8-2.0x on a
    2-vCPU container)."""
    with _fresh_cluster(num_cpus=16):
        _overlap_pipeline().take_all()  # warm the worker pool
        t_stream = _best_of(3, lambda: _overlap_pipeline().take_all())
    with _fresh_cluster(num_cpus=16,
                        _system_config={"streaming_executor": False}):
        _overlap_pipeline().take_all()
        t_legacy = _best_of(3, lambda: _overlap_pipeline().take_all())
    assert t_legacy >= 1.5 * t_stream, (
        f"streaming {t_stream:.3f}s vs legacy {t_legacy:.3f}s "
        f"({t_legacy / t_stream:.2f}x)")


def test_fusion_single_task_per_block():
    """Acceptance: a fused map+filter+map chain issues ONE task per
    block (counted via the runtime's task events), and the engine
    reports the fused operator."""
    with _fresh_cluster(num_cpus=4) as rt:
        ds = (data.from_items(list(range(60)), parallelism=6)
              .map(lambda x: x + 1)
              .filter(lambda x: x % 2 == 0)
              .map(lambda x: x * 10))
        out = ds.take_all()
        assert sorted(out) == [x * 10 for x in range(2, 62, 2)]
        evs = rt.state_query("tasks")
        stage_tasks = [e for e in evs
                       if e.get("name") == "apply_stage_with_stats"]
        assert len(stage_tasks) == 6, (
            f"{len(stage_tasks)} stage tasks for 6 blocks — fusion "
            f"broke (expected one task per block for the whole chain)")
        s = ds._stats.streaming_summary()
        assert list(s["ops"]) == ["map+filter+map"], s["ops"]
        assert s["ops"]["map+filter+map"]["out_blocks"] == 6


def test_num_cpus_is_a_fusion_boundary():
    """Per-op resources split the chain: same resources fuse, different
    resources become separate pipelined operators."""
    with _fresh_cluster(num_cpus=4):
        ds = (data.from_items(list(range(8)), parallelism=4)
              .map(lambda x: x + 1, num_cpus=0)
              .map(lambda x: x * 2, num_cpus=0)
              .map(lambda x: x - 1, num_cpus=1))
        assert sorted(ds.take_all()) == sorted((x + 1) * 2 - 1
                                               for x in range(8))
        ops = ds._stats.streaming_summary()["ops"]
        assert list(ops) == ["map+map", "map"], ops

        # Fusion compares NORMALIZED requests: an explicit num_cpus=1 is
        # the scheduler's default request, so it fuses with unannotated
        # ops instead of splitting the chain on the raw opts dict.
        ds2 = (data.from_items(list(range(8)), parallelism=4)
               .map(lambda x: x + 1)
               .map(lambda x: x * 2, num_cpus=1))
        assert sorted(ds2.take_all()) == sorted((x + 1) * 2
                                                for x in range(8))
        ops2 = ds2._stats.streaming_summary()["ops"]
        assert list(ops2) == ["map+map"], ops2


def test_operator_failure_surfaces_and_cancels_upstream():
    """Acceptance: a task error mid-stream reaches the consumer as the
    task's error and outstanding upstream work is cancelled instead of
    running the rest of the window to completion."""
    with _fresh_cluster(num_cpus=4):
        def gate(batch):
            # Block 0 sails through instantly; later blocks pace slowly
            # so upstream work is still outstanding at failure time.
            if batch[0] >= 2:
                time.sleep(0.5)
            return batch

        def boom(x):
            if x == 0:
                raise ValueError("boom block")
            return x

        ds = (data.from_items(list(range(8)), parallelism=8)
              .map_batches(gate, batch_format="rows", num_cpus=0)
              .map(boom))
        with pytest.raises(ray.exceptions.TaskError, match="boom block"):
            ds.take_all()
        s = ds._stats.streaming_summary()
        assert s["cancelled_tasks"] >= 1, s
        # The runtime stays healthy after the cancellation storm.
        assert sorted(data.from_items([3, 1, 2]).map(
            lambda x: x * 2).take_all()) == [2, 4, 6]


def test_streaming_off_is_legacy_with_zero_counters():
    """Acceptance: config.streaming_executor=off routes through the
    windowed path — same results, no engine counters, no engine rows in
    stats()."""
    with _fresh_cluster(num_cpus=4,
                        _system_config={"streaming_executor": False}):
        ds = (data.from_items([{"v": i} for i in range(40)],
                              parallelism=4)
              .map(lambda r: {"v": r["v"] * 2})
              .filter(lambda r: r["v"] % 4 == 0))
        out = ds.take_all()
        assert sorted(r["v"] for r in out) == list(range(0, 80, 4))
        from ray_tpu.data.streaming_executor import empty_summary

        assert ds._stats.streaming_summary() == empty_summary()
        assert "Streaming executor" not in ds.stats()
        # Per-op stats still accumulate on the legacy path.
        assert ds._stats.summary()["map"]["rows_out"] == 40


def test_data_config_reaches_workers():
    """Driver _system_config data knobs follow the runtime's env
    namespace into spawned workers (a Dataset consumed INSIDE a worker —
    the Train shard contract — must honor the driver's engine switch and
    byte budget, not the worker host's env defaults)."""
    with _fresh_cluster(num_cpus=2, _system_config={
            "streaming_executor": False,
            "data_memory_budget": 12345}):
        @ray.remote
        def probe():
            from ray_tpu._private.config import GLOBAL_CONFIG
            return (GLOBAL_CONFIG.streaming_executor,
                    GLOBAL_CONFIG.data_memory_budget)

        assert ray.get(probe.remote()) == (False, 12345)


def test_budget_accounting_uses_store_sizes_for_row_blocks():
    """Byte accounting must run on exact store-descriptor sizes, not the
    UDF-side estimate: rows-of-dicts blocks are guessed at 64 B/row, so
    ~2 KB string rows would undercount the engine's ledger ~30x and an
    explicit budget would be enforced against fiction."""
    with _fresh_cluster(num_cpus=4):
        big = "x" * 2048
        ds = (data.from_items([{"s": big} for _ in range(256)],
                              parallelism=4)
              .map(lambda r: {"s": r["s"] + "y"}))
        assert len(ds.take_all()) == 256
        row = ds._stats.streaming_summary()["ops"]["map"]
        # The 64 B/row estimate would report 256 * 64 = 16 KB; the real
        # blocks carry ~512 KB of string payload.
        assert row["out_bytes"] > 200_000, row


def test_streaming_battery_under_lockcheck():
    """Acceptance: the engine's lock usage is clean — the whole battery
    shape (fused tasks, actor stage, tight budget, failure path) under
    the lockdep-style checker records zero lock-order cycles."""
    from ray_tpu.devtools import lockcheck

    lockcheck.install(raise_on_cycle=False)
    lockcheck.clear()
    try:
        with _fresh_cluster(num_cpus=4, _system_config={
                "data_memory_budget": 4 << 20}):
            ds = (data.from_items(list(range(12)), parallelism=6)
                  .map(lambda x: x + 1)
                  .map_batches(lambda b: [v * 2 for v in b],
                               batch_format="rows", num_cpus=0))
            assert sorted(ds.take_all()) == sorted(
                (x + 1) * 2 for x in range(12))

            class Add:
                def __call__(self, batch):
                    return [v + 5 for v in batch]

            ds2 = (data.from_items(list(range(8)), parallelism=4)
                   .map_batches(Add, compute="actors", concurrency=2,
                                batch_format="rows"))
            assert sorted(ds2.take_all()) == sorted(x + 5
                                                    for x in range(8))
            with pytest.raises(ray.exceptions.TaskError):
                data.from_items([1, 0], parallelism=2).map(
                    lambda x: 1 // x).take_all()
        assert lockcheck.violations() == [], lockcheck.violations()
        lockcheck.assert_acyclic()
    finally:
        lockcheck.uninstall()
