# protocheck: role=head
# protocheck-with: good_proto_arity_peer.py
"""RTL502 good fixture: the optional lease_req opts element is read
behind a len() guard, so the companion's short form is safe; kill is
sent at its catalog arity."""

from ray_tpu._private import protocol


class HeadLike:
    def handle(self, msg):
        tag = msg[0]
        if tag == "lease_req":
            rid, res, n = msg[1], msg[2], msg[3]
            opts = msg[4] if len(msg) > 4 else None
            return rid, res, n, opts
        return None

    def stop(self, conn):
        protocol.send(conn, ("kill",))
