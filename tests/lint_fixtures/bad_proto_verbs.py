# protocheck: role=head
# protocheck-with: bad_proto_verbs_peer.py
"""RTL501/RTL500 bad fixture: a typo'd verb, a verb sent from the wrong
role, a reasonless suppression, and a dead handler (the companion worker
module never sends lease_renew)."""

from ray_tpu._private import protocol


class HeadLike:
    def reply(self, conn, rid):
        protocol.send(conn, ("repyl", rid, None))  # EXPECT: RTL501

    def pressure(self, conn):
        protocol.send(conn, ("oom_pressure", 0.5))  # EXPECT: RTL501

    def relay(self, conn):
        protocol.send(conn, ("segment", 1, True, b""))  # noqa: RTL501  # EXPECT: RTL500

    def handle(self, msg):
        tag = msg[0]
        if tag == "lease_renew":  # EXPECT: RTL501
            return msg[1]
        return None
