# protocheck: role=head
# protocheck-with: bad_proto_arity_peer.py
"""RTL502 bad fixture: the two-module sender/handler arity-drift case.
Each side is legal against the catalog in isolation — lease_req allows
4..5 elements — but the handler reads the optional opts element with no
len() guard while the companion worker ships the 4-element form, and a
widened kill tuple exceeds the catalog outright."""

from ray_tpu._private import protocol


class HeadLike:
    def handle(self, msg):
        tag = msg[0]
        if tag == "lease_req":  # EXPECT: RTL502
            rid, res, n = msg[1], msg[2], msg[3]
            opts = msg[4]
            return rid, res, n, opts
        return None

    def stop(self, conn, wid):
        protocol.send(conn, ("kill", wid, 0))  # EXPECT: RTL502
