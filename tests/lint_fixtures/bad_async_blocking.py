"""RTL101/RTL102 bad cases: blocking calls on the event loop."""
import time

import ray_tpu


async def blocking_get_in_handler(ref):
    return ray_tpu.get(ref)  # EXPECT: RTL101


async def blocking_wait_in_handler(refs):
    ready, rest = ray_tpu.wait(refs)  # EXPECT: RTL101
    return ready, rest


async def blocking_ref_get(object_ref):
    return object_ref.get()  # EXPECT: RTL101


async def blocking_get_objects(rt, refs):
    return rt.get_objects(refs)  # EXPECT: RTL101


async def sleepy_handler():
    time.sleep(0.5)  # EXPECT: RTL102
