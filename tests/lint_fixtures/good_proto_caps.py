# protocheck: role=objsrv
"""RTL503 good fixture: the caps membership test lexically guards the
gated verb's send path, and a helper reached only from the gated
function inherits the gate (one level of intra-module call
resolution)."""

from ray_tpu._private import protocol


class PullerLike:
    def fetch(self, conn, name, length, caps):
        if "fetch_range" in caps:
            return self._fetch_striped(conn, name, length)
        return None

    def _fetch_striped(self, conn, name, length):
        protocol.send(conn, ("fetch_range", name, 0, length))
        return protocol.recv(conn)

    def serve(self, conn, store):
        msg = protocol.recv(conn)
        if msg[0] == "fetch_range":
            _tag, name, off, length = msg
            return store.attach(name), off, length
        return None
