"""RTL403 fixture: raw connection/socket receives outside the
deadline-aware protocol core — each can hang forever on a
stalled-but-alive peer (gray failure) because no zero-progress deadline
is ever armed."""


class Puller:
    def pull_header(self, conn):
        return conn.recv_bytes()  # EXPECT: RTL403

    def pull_range(self, conn, view, off, n):
        got = 0
        while got < n:
            got += conn.recv_bytes_into(view, off + got)  # EXPECT: RTL403
        return got

    def pull_nested(self):
        return self._conn.recv_bytes()  # EXPECT: RTL403

    def read_raw(self, sock):
        return sock.recv(4096)  # EXPECT: RTL403
