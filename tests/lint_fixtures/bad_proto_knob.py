# protocheck: stands-for=config.py
# protocheck-with: bad_proto_knob_peer.py
"""RTL504 bad fixture (config half): a worker-relevant knob that rides
neither _worker_config_env nor an exemption marker.  The companion
stands for runtime.py."""

import dataclasses


@dataclasses.dataclass
class Config:
    lease_slots: int = 8  # EXPECT: RTL504
    object_pool_size: int = 4
    # protocheck: head-only -- the idle-worker reaper runs in the head
    idle_worker_timeout_s: float = 300.0
    # protocheck: head-only  # EXPECT: RTL500
    prestart_workers: int = 0
