# protocheck: stands-for=runtime.py
# protocheck-with: bad_proto_knob.py
"""RTL504 bad fixture (runtime half): the agent spawn path stopped
consuming _worker_config_env, and a counter aggregated from worker
deltas never reaches transfer_stats()."""


class RuntimeLike:
    def _worker_config_env(self):
        return {"RAY_TPU_OBJECT_POOL_SIZE": "4"}

    def _spawn_worker(self):
        env = {}
        env.update(self._worker_config_env())
        return env

    def _spawn_worker_via_agent(self):  # EXPECT: RTL504
        overrides = {}
        return overrides

    def _handle(self, msg):
        tag = msg[0]
        if tag == "xfer_stats":
            d = msg[1]
            self.deduped_pulls += d.get("deduped_pulls", 0)
            self.spillbacks += d.get("spillbacks", 0)  # EXPECT: RTL504

    def transfer_stats(self):
        return {"deduped_pulls": self.deduped_pulls}
