"""RTL403 negative space: receives that go through the deadline-aware
protocol core — either the wrapped primitives (``protocol.recv`` /
``protocol.recv_deadline``) or a raw loop explicitly armed with
``set_conn_deadline`` and suppressed with the arming site as the
reason."""

from ray_tpu._private import protocol


class Puller:
    def pull_msg(self, conn):
        return protocol.recv(conn)

    def pull_msg_bounded(self, conn, timeout):
        return protocol.recv_deadline(conn, timeout)

    def pull_range(self, conn, view, off, n):
        protocol.set_conn_deadline(conn, 15.0)
        try:
            got = 0
            while got < n:
                got += conn.recv_bytes_into(view, off + got)  # noqa: RTL403 -- deadline armed two lines up
            return got
        finally:
            protocol.set_conn_deadline(conn, None)

    def drain_queue(self, inbox):
        # Non-socket receivers are not the rule's business.
        return inbox.recv_bytes()
