# protocheck: role=objsrv
"""RTL503 bad fixture: a capability-gated verb sent with no caps
membership test anywhere on the path into the sending function — an old
peer that never advertised fetch_range would silently ignore it and
desync the stream (the PR 3/6/7 "never probe an old peer"
convention)."""

from ray_tpu._private import protocol


class PullerLike:
    def fetch(self, conn, name, length):
        protocol.send(conn, ("fetch_range", name, 0, length))  # EXPECT: RTL503
        return protocol.recv(conn)

    def serve(self, conn, store):
        msg = protocol.recv(conn)
        if msg[0] == "fetch_range":
            _tag, name, off, length = msg
            return store.attach(name), off, length
        return None
