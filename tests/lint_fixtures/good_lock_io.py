"""RTL402 good cases: the IO/pickling happens OUTSIDE the runtime-lock
critical section (or under a send lock, whose whole purpose is guarding
that one socket write), and nested defs under a lock don't count — their
bodies run at call time."""
import pickle
import threading

from ray_tpu._private import protocol, serialization


class Head:
    def __init__(self, conn):
        self.lock = threading.RLock()
        self.send_lock = threading.Lock()  # lock-order: io-guard
        self.conn = conn
        self.table = {}

    def reply_outside_lock(self, rid, payload):
        with self.lock:
            self.table[rid] = payload
        protocol.send(self.conn, ("reply", rid, payload))

    def pickle_then_store(self, rid, value):
        blob = pickle.dumps(value)
        with self.lock:
            self.table[rid] = blob
        return serialization.dumps_inline(rid)

    def send_under_send_lock(self, msg):
        # An io-guard lock guards exactly this socket write: holding it
        # across the send IS the design (declared at the creation site
        # with '# lock-order: io-guard'; shared with lockgraph).
        with self.send_lock:
            protocol.send(self.conn, msg)

    def buffer_under_lock(self, worker, msg):
        with self.lock:
            # Conflation-sender pattern: buffering is lock-cheap; the
            # sender thread does the pickle + write outside.
            worker.queue_msg(msg)

    def nested_def_under_lock(self, conn, blob):
        with self.lock:
            def flush():
                # Runs at CALL time, not under this acquisition.
                protocol.send(conn, blob)

            self.table["flush"] = flush
        return self.table["flush"]

    def lambda_under_lock(self, conn, blob):
        with self.lock:
            # Same as a nested def: the body runs at call time.
            self.table["flush"] = lambda: protocol.send(conn, blob)
        return self.table["flush"]
