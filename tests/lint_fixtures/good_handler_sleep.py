"""RTL103 good cases: sleeps on dedicated/background threads are fine."""
import time


def retry_dial_loop(address):
    for attempt in range(20):
        time.sleep(0.05 * (attempt + 1))


def _memory_monitor_thread():
    while True:
        time.sleep(0.5)


def decref_flusher():
    time.sleep(0.25)


def handle_message(msg):
    # A handler that does NOT sleep must not fire.
    return msg[0]
