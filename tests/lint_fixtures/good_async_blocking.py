"""RTL101/RTL102 good cases: nothing here may fire."""
import asyncio
import time

import ray_tpu


async def awaits_the_ref(ref):
    return await ref


async def pushes_into_executor(ref):
    loop = asyncio.get_event_loop()
    # The blocking get lives in a nested SYNC lambda handed to a worker
    # thread — the event loop never blocks; must not fire.
    return await loop.run_in_executor(None, lambda: ray_tpu.get(ref))


async def async_sleep_is_fine():
    await asyncio.sleep(0.5)


def sync_get_is_fine(ref):
    # Blocking get in a plain function: the caller owns the thread.
    return ray_tpu.get(ref)


async def dict_get_is_not_a_ref(mapping):
    # .get() on a non-ref-ish receiver must not fire.
    return mapping.get("key")


async def ref_map_lookup_is_not_a_blocking_get(self, oid):
    # A POSITIONAL arg means container lookup, not ObjectRef.get() —
    # even on a ref-ish receiver name this must not fire.
    return self._object_refs.get(oid)


def sync_sleep_in_plain_function():
    time.sleep(0.01)
