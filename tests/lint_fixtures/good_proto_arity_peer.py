# protocheck: role=worker
"""Companion worker module for good_proto_arity.py: both legal
lease_req forms, and the kill handler that keeps the head's send
live."""


class WorkerLike:
    def ask(self, rid, opts):
        self._send(("lease_req", rid, {"CPU": 1.0}, 2))
        self._send(("lease_req", rid, {"CPU": 1.0}, 2, opts))

    def _send(self, msg):
        return msg

    def reader(self, msg):
        tag = msg[0]
        if tag == "kill":
            return True
        return None
