"""RTL402 bad cases: blocking socket IO / payload pickling while a
runtime (table) lock is held."""
import pickle
import threading

from ray_tpu._private import protocol, serialization


class Head:
    def __init__(self, conn):
        self.lock = threading.RLock()
        self.conn = conn
        self.table = {}

    def reply_under_lock(self, rid, payload):
        with self.lock:
            self.table[rid] = payload
            protocol.send(self.conn, ("reply", rid, payload))  # EXPECT: RTL402

    def pickle_under_lock(self, value):
        with self.lock:
            return pickle.dumps(value)  # EXPECT: RTL402

    def serialize_under_lock(self, value):
        with self.lock:
            return serialization.dumps_inline(value)  # EXPECT: RTL402


class Owner:
    def __init__(self, worker):
        self._lock = threading.Lock()
        self.worker = worker

    def notify_under_private_lock(self, msg):
        with self._lock:
            self.worker.send(msg)  # EXPECT: RTL402

    def raw_bytes_under_lock(self, conn, blob):
        with self._lock:
            conn.send_bytes(blob)  # EXPECT: RTL402

    def unpickle_under_nested_lock(self, other, blob):
        with self._lock:
            with other.lock:
                return serialization.loads_inline(blob)  # EXPECT: RTL402
