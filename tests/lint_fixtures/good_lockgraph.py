# protocheck: role=worker
"""Good twin of bad_lockgraph.py: the same shapes done right — one
global acquisition order into a declared leaf, the event signaled after
the leaf releases, the pickle hoisted outside the critical section, and
an io-guard lock whose held-across-the-write is the declared design.
All three analyzers (lint, protocheck, lockgraph) must stay silent."""

import pickle
import threading


class Ordered:
    def __init__(self):
        self.outer_lock = threading.Lock()
        self.inner_lock = threading.Lock()  # lock-order: leaf

    def fwd(self):
        # Every path nests outer -> inner; nesting INTO a leaf is the
        # convention (the leaf itself acquires nothing).
        with self.outer_lock:
            self._grab_inner()

    def _grab_inner(self):
        with self.inner_lock:
            pass


class Signals:
    def __init__(self):
        self._stats_lock = threading.Lock()  # lock-order: leaf
        self._ready = threading.Event()

    def publish(self):
        with self._stats_lock:
            count = 1
        # Signal AFTER the leaf releases: a woken waiter that re-enters
        # this class never finds the leaf still held.
        self._ready.set()
        return count


class Thawed:
    def __init__(self):
        self.lock = threading.Lock()

    def snapshot(self, table):
        with self.lock:
            rows = list(table)
        # The serialize runs outside the critical section — other
        # acquirers never stall behind the pickle.
        return pickle.dumps(rows)


class Wire:
    def __init__(self, conn):
        self.conn = conn
        self.send_lock = threading.Lock()  # lock-order: io-guard

    def send(self, payload):
        # Holding an io-guard lock across its socket write IS the
        # design; the annotation is the shared lint/lockgraph opt-out.
        with self.send_lock:
            self.conn.send_bytes(payload)
