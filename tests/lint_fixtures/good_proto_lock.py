# protocheck: role=worker
"""RTL505 good fixture: the leaf registry acquires nothing under its
lock (teardown work happens after release), and the owner's inner lock
is a declared leaf — nesting INTO a leaf is the convention."""

import threading


class PutRegistry:
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: leaf
        self._evict_lock = threading.Lock()

    def write(self, name):
        with self._lock:
            entry = name
        return self._teardown(entry)

    def _teardown(self, name):
        with self._evict_lock:
            return name


class Owner:
    def __init__(self):
        self.lock = threading.Lock()
        self._table_lock = threading.Lock()  # lock-order: leaf

    def release(self):
        with self.lock:
            with self._table_lock:
                return 1
