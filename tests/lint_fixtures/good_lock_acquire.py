"""RTL401 good cases: nothing here may fire."""
import threading

_registry_lock = threading.Lock()


def with_statement(table, key, value):
    with _registry_lock:
        table[key] = value


def try_lock_is_exempt():
    # `with` cannot express a non-blocking or timed acquire.
    if _registry_lock.acquire(False):
        _registry_lock.release()
    if _registry_lock.acquire(blocking=False):
        _registry_lock.release()
    if _registry_lock.acquire(timeout=0.1):
        _registry_lock.release()
    if _registry_lock.acquire(True, 0.1):  # positional timeout form
        _registry_lock.release()


def suppressed_handoff():
    # Cross-function lock handoff (acquired here, released by a callback)
    # cannot use `with`; suppressed with rationale.
    _registry_lock.acquire()  # noqa: RTL401 -- handed off to callback
    return _registry_lock


def resource_accounting_is_not_a_lock(node, req):
    # .acquire() on non-lock-ish receivers (resource accounting) is fine.
    node.acquire(req)
    node.release(req)
