"""RTL103 bad cases: sleeping on a shared dispatch thread."""
import time as _time


def handle_message(msg):
    _time.sleep(0.1)  # EXPECT: RTL103


def _handle_reply(conn, msg):
    _time.sleep(1)  # EXPECT: RTL103


def on_peer_msg(payload):
    _time.sleep(0.05)  # EXPECT: RTL103


def poll_handler(queue):
    _time.sleep(0.25)  # EXPECT: RTL103


def serve_connection(conn, store):
    _time.sleep(0.1)  # EXPECT: RTL103
