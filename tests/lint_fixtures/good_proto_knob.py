# protocheck: stands-for=config.py
# protocheck-with: good_proto_knob_peer.py
"""RTL504 good fixture (config half): every field is plumbed, aliased,
or exempted with a reason."""

import dataclasses


@dataclasses.dataclass
class Config:
    lease_slots: int = 8
    object_pool_size: int = 4
    # protocheck: head-only -- the idle-worker reaper runs in the head
    idle_worker_timeout_s: float = 300.0
    # protocheck: env-alias RAY_TPU_POOL_BYTES -- legacy spelling
    shm_pool_bytes: int = 1
