# protocheck: role=worker
"""Companion worker module for bad_proto_verbs.py: deliberately sends
NOTHING, so the head fixture's lease_renew arm is provably dead."""


class WorkerLike:
    def idle(self):
        return None
