# protocheck: stands-for=runtime.py
# protocheck-with: good_proto_knob.py
"""RTL504 good fixture (runtime half): both spawn paths consume
_worker_config_env, and every aggregated counter is surfaced."""


class RuntimeLike:
    def _worker_config_env(self):
        return {
            "RAY_TPU_LEASE_SLOTS": "8",
            "RAY_TPU_OBJECT_POOL_SIZE": "4",
            "RAY_TPU_POOL_BYTES": "1",
        }

    def _spawn_worker(self):
        env = {}
        env.update(self._worker_config_env())
        return env

    def _spawn_worker_via_agent(self):
        overrides = {}
        overrides.update(self._worker_config_env())
        return overrides

    def _handle(self, msg):
        tag = msg[0]
        if tag == "xfer_stats":
            d = msg[1]
            self.deduped_pulls += d.get("deduped_pulls", 0)
            self.spillbacks += d.get("spillbacks", 0)

    def transfer_stats(self):
        return {"deduped_pulls": self.deduped_pulls,
                "spillbacks": self.spillbacks}
