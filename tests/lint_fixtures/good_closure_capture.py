"""RTL201 good cases: nothing here may fire."""
import numpy as np

import ray_tpu


def pass_as_argument(f):
    ref = f.remote(1)

    @ray_tpu.remote
    def takes_argument(x):
        return x

    return takes_argument.remote(ref)


def benign_closure_capture():
    # Capturing a plain config value is normal closure behavior.
    learning_rate = 0.1

    @ray_tpu.remote
    def step(x):
        return x * learning_rate

    return step


def module_level_np_is_fine():
    @ray_tpu.remote
    def make_locally(n):
        # Array built INSIDE the task — nothing shipped per call.
        return np.zeros((n, n))

    return make_locally


def suppressed_deliberate_capture(f):
    small_ref = f.remote(1)

    @ray_tpu.remote
    def reuses_ref():  # noqa: RTL201 -- tiny ref, resubmitted in a loop
        return small_ref

    return reuses_ref
