"""RTL301 good cases: nothing here may fire."""


def catches_exception_only(queue):
    try:
        return queue.get()
    except Exception:
        return None


def bare_except_that_reraises(conn):
    try:
        return conn.recv()
    except:
        conn.close()
        raise  # re-raise keeps SystemExit/KeyboardInterrupt propagating
