# protocheck: role=worker
"""Companion worker module for bad_proto_arity.py: legally sends the
SHORT 4-element lease_req form (opts is optional in the catalog) — the
drift only exists across the two modules.  Also handles the head's kill
so the widened-send case stays an arity finding, not a liveness one."""


class WorkerLike:
    def ask(self, rid):
        self._send(("lease_req", rid, {"CPU": 1.0}, 2))

    def _send(self, msg):
        return msg

    def reader(self, msg):
        tag = msg[0]
        if tag == "kill":
            return True
        return None
