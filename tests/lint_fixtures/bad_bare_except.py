"""RTL301 bad cases: bare except swallowing SystemExit."""


def worker_loop(queue):
    while True:
        try:
            queue.get()
        except:  # EXPECT: RTL301
            pass


def agent_loop(conn):
    try:
        return conn.recv()
    except:  # EXPECT: RTL301
        return None
