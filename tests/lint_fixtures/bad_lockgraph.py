# protocheck: role=worker
"""RTL6xx bad fixture: a two-lock cycle split across call paths (only
the whole-program graph sees it close), a declared leaf that grew an
outgoing edge through a call, an Event.set reached inside a leaf body,
blocking pickling buried two calls deep under a runtime lock (lexical
RTL402's exact blind spot), and a reasonless RTL6xx suppression.

protocheck's one-level RTL505 fires alongside on the lock-under-lock
call sites — the markers pin the layering: RTL505 is the one-hop
lexical inference, RTL60x the transitive whole-program verdicts."""

import pickle
import threading


class Cycle:
    def __init__(self):
        self.fwd_lock = threading.Lock()
        self.rev_lock = threading.Lock()

    def fwd(self):
        with self.fwd_lock:
            self._grab_rev()  # EXPECT: RTL505  # EXPECT: RTL601

    def _grab_rev(self):
        with self.rev_lock:
            pass

    def rev(self):
        with self.rev_lock:
            self._grab_fwd()  # EXPECT: RTL505

    def _grab_fwd(self):
        with self.fwd_lock:
            pass


class LeafGrowth:
    def __init__(self):
        self._stats_lock = threading.Lock()  # lock-order: leaf
        self._table_lock = threading.Lock()
        self._ready = threading.Event()

    def bump(self):
        with self._stats_lock:
            self._reindex()  # EXPECT: RTL505  # EXPECT: RTL602

    def _reindex(self):
        with self._table_lock:
            pass

    def publish(self):
        with self._stats_lock:
            self._wake()  # EXPECT: RTL603

    def _wake(self):
        self._ready.set()


class Frozen:
    def __init__(self):
        self.lock = threading.Lock()

    def snapshot(self, table):
        with self.lock:
            return self._encode(table)

    def _encode(self, table):
        return self._really_encode(table)

    def _really_encode(self, table):
        return pickle.dumps(table)  # EXPECT: RTL604


class Sloppy:
    def __init__(self):
        self._q_lock = threading.Lock()  # lock-order: leaf
        self._aux_lock = threading.Lock()

    def drain(self):
        with self._q_lock:
            self._flush()  # noqa: RTL602  # EXPECT: RTL505  # EXPECT: RTL600

    def _flush(self):
        with self._aux_lock:
            pass
