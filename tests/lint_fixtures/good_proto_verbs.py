# protocheck: role=head
# protocheck-with: good_proto_verbs_peer.py
"""RTL501 good fixture: catalog verbs from the right role, a live
handler (the companion sends lease_renew), and a suppression that
carries its reason."""

from ray_tpu._private import protocol


class HeadLike:
    def reply(self, conn, rid):
        protocol.send(conn, ("reply", rid, None))

    def relay(self, conn):
        protocol.send(conn, ("segment", 1, True, b""))  # noqa: RTL501 -- interop shim: replays a captured agent frame in the relay test

    def handle(self, msg):
        tag = msg[0]
        if tag == "lease_renew":
            return msg[1]
        return None
