# protocheck: role=worker
"""Companion worker module for good_proto_verbs.py: sends lease_renew
(keeping the head arm live) and handles the head's reply verb."""


class WorkerLike:
    def renew(self, wids):
        self._send(("lease_renew", list(wids)))

    def _send(self, msg):
        return msg

    def reader(self, msg):
        tag = msg[0]
        if tag == "reply":
            return msg[2]
        return None
