"""RTL201 bad cases: @remote functions closure-capturing refs/arrays."""
import numpy as np

import ray_tpu


def build_pipeline(f):
    ref = f.remote(1)

    @ray_tpu.remote
    def uses_captured_ref():  # EXPECT: RTL201
        return ref

    return uses_captured_ref


def build_training_step():
    weights = np.zeros((4096, 4096))

    @ray_tpu.remote(num_cpus=1)
    def train_step(batch):  # EXPECT: RTL201
        return batch @ weights

    return train_step


def capture_from_put():
    dataset = ray_tpu.put([1, 2, 3])

    @ray_tpu.remote
    def consume():  # EXPECT: RTL201
        return dataset

    return consume
