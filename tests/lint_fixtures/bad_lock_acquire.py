"""RTL401 bad cases: lock acquisition outside `with`."""
import threading

_registry_lock = threading.Lock()


def leaky_acquire(table, key, value):
    _registry_lock.acquire()  # EXPECT: RTL401
    table[key] = value  # an exception here leaks the lock
    _registry_lock.release()


class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def leaky_method(self):
        self._lock.acquire()  # EXPECT: RTL401
        try:
            return 1
        finally:
            self._lock.release()
