# protocheck: role=worker
"""RTL505 bad fixture: the historical PutRegistry convention — its
``_lock`` is a documented independent LEAF, so acquiring ANY lock under
it (here through one level of call resolution) is a violation the
runtime lockcheck would only catch if the path executed; plus a plain
undeclared nesting edge between two unannotated locks."""

import threading


class PutRegistry:
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: leaf
        self._evict_lock = threading.Lock()

    def write(self, name):
        with self._lock:
            self._teardown(name)  # EXPECT: RTL505  # EXPECT: RTL602
            return True

    def _teardown(self, name):
        with self._evict_lock:
            return name


class Owner:
    def __init__(self):
        self.lock = threading.Lock()
        self._table_lock = threading.Lock()

    def release(self):
        with self.lock:
            with self._table_lock:  # EXPECT: RTL505
                return 1
