"""OOM memory monitor (reference: src/ray/common/memory_monitor.h +
worker_killing_policy_group_by_owner.cc): under node memory pressure the
newest retriable task's worker is killed and the task retries; exhausted
retries surface a typed OutOfMemoryError."""
import time

import pytest

import ray_tpu as ray
from ray_tpu import exceptions as exc


@pytest.fixture
def pressure_file(tmp_path):
    p = tmp_path / "pressure"
    p.write_text("0.0")
    return p


@pytest.fixture
def oom_cluster(pressure_file):
    ray.init(num_cpus=2, ignore_reinit_error=True, _system_config={
        "memory_monitor_test_file": str(pressure_file),
        "memory_monitor_interval_s": 0.15,
        "memory_monitor_threshold": 0.9,
    })
    yield pressure_file
    ray.shutdown()


def test_task_killed_then_retried(oom_cluster):
    pressure = oom_cluster

    @ray.remote(max_retries=4)
    def slow():
        import time
        time.sleep(1.2)
        return "survived"

    ref = slow.remote()
    time.sleep(0.3)            # task is running
    pressure.write_text("0.97")  # monitor kills its worker
    time.sleep(0.5)
    pressure.write_text("0.1")   # pressure gone; retry must complete
    assert ray.get(ref, timeout=60) == "survived"


def test_exhausted_retries_surface_typed_error(oom_cluster):
    pressure = oom_cluster

    @ray.remote(max_retries=0)
    def victim():
        import time
        time.sleep(30)

    ref = victim.remote()
    time.sleep(0.3)
    pressure.write_text("0.97")
    with pytest.raises(exc.OutOfMemoryError):
        ray.get(ref, timeout=30)
    pressure.write_text("0.0")


def test_actors_are_never_victims(oom_cluster):
    pressure = oom_cluster

    @ray.remote
    class Keeper:
        def ping(self):
            return "alive"

    k = Keeper.remote()
    assert ray.get(k.ping.remote(), timeout=30) == "alive"
    pressure.write_text("0.97")
    time.sleep(0.6)
    pressure.write_text("0.0")
    assert ray.get(k.ping.remote(), timeout=30) == "alive"
