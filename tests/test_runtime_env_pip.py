"""runtime_env pip materialization (reference:
python/ray/_private/runtime_env/pip.py): a task runs inside a venv built
from its requirements, cached by hash.  Zero-egress test: the requirement
is a local setup.py package installed with --no-index."""
import textwrap

import pytest

import ray_tpu as ray


@pytest.fixture
def local_pkg(tmp_path):
    pkg = tmp_path / "r5demo"
    (pkg / "r5demo").mkdir(parents=True)
    (pkg / "r5demo" / "__init__.py").write_text("MAGIC = 'pip-env-works'\n")
    (pkg / "setup.py").write_text(textwrap.dedent("""
        from setuptools import setup, find_packages
        setup(name="r5demo", version="0.0.1", packages=find_packages())
    """))
    return str(pkg)


@pytest.fixture
def cluster():
    ray.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray.shutdown()


def test_task_imports_package_absent_from_driver(cluster, local_pkg):
    with pytest.raises(ImportError):
        import r5demo  # noqa: F401 — must NOT exist in the driver env

    @ray.remote(runtime_env={"pip": {
        "packages": [local_pkg],
        "pip_install_options": ["--no-index", "--no-build-isolation"],
    }})
    def probe():
        import r5demo
        return r5demo.MAGIC

    assert ray.get(probe.remote(), timeout=120) == "pip-env-works"


@pytest.mark.slow  # ~27s (venv build); the basic pip-env path keeps a
                   # tier-1 representative in the test above
def test_venv_cached_across_tasks_and_plain_tasks_unaffected(cluster,
                                                             local_pkg):
    env = {"pip": {"packages": [local_pkg],
                   "pip_install_options": ["--no-index",
                                           "--no-build-isolation"]}}

    @ray.remote(runtime_env=env)
    def probe():
        import sys

        import r5demo
        return r5demo.MAGIC, sys.prefix

    @ray.remote
    def plain():
        try:
            import r5demo  # noqa: F401
            return "leaked"
        except ImportError:
            return "clean"

    (m1, prefix1), (m2, prefix2) = ray.get(
        [probe.remote(), probe.remote()], timeout=120)
    assert m1 == m2 == "pip-env-works"
    assert prefix1 == prefix2          # same cached venv
    assert "ray_tpu_venvs" in prefix1  # actually inside the venv
    # Plain workers never see the venv (separate scheduling class).
    assert ray.get(plain.remote(), timeout=60) == "clean"
