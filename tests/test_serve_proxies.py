"""Per-node Serve proxies (reference: serve.start(proxy_location=
"EveryNode") — one HTTPProxyActor per node, _private/http_proxy.py:415;
routing state shared via the controller's route table)."""
import json
import urllib.request

import pytest

import ray_tpu as ray
from ray_tpu import serve


@pytest.fixture
def two_node_cluster():
    rt = ray.init(num_cpus=2)
    rt.add_node(num_cpus=2)
    yield rt
    serve.shutdown()
    ray.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_every_node_proxies_serve_requests(two_node_cluster):
    @serve.deployment(num_replicas=2, route_prefix="/echo")
    def echo(body):
        return {"echo": body.get("x", 0) * 2}

    urls = serve.start(proxy_location="EveryNode")
    assert len(urls) == 2, urls
    assert len(set(urls)) == 2  # distinct ports (in-process nodes)

    serve.run(echo)
    for i, url in enumerate(urls):
        out = _post(url + "/echo", {"x": 10 + i})
        assert out["result"]["echo"] == (10 + i) * 2, (url, out)

    # Unknown route 404s on every proxy.
    for url in urls:
        try:
            _post(url + "/nope", {})
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404


def test_proxies_land_on_distinct_nodes(two_node_cluster):
    serve.start(proxy_location="EveryNode")
    proxies = serve.api._state["node_proxies"]
    nodes = ray.get([p.node_id.remote() for p in proxies])
    assert len(set(nodes)) == 2, nodes
