"""Per-node Serve proxies (reference: serve.start(proxy_location=
"EveryNode") — one HTTPProxyActor per node, _private/http_proxy.py:415;
routing state shared via the controller's route table) and the
data-plane RequestProxy tier (serve.start(num_proxies=N)): steady-state
serving traffic rides the DirectCaller actor channels, producing zero
head_brokered_submits."""
import json
import os
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

import ray_tpu as ray
from ray_tpu import serve


@pytest.fixture
def two_node_cluster():
    rt = ray.init(num_cpus=2)
    rt.add_node(num_cpus=2)
    yield rt
    serve.shutdown()
    ray.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_every_node_proxies_serve_requests(two_node_cluster):
    @serve.deployment(num_replicas=2, route_prefix="/echo")
    def echo(body):
        return {"echo": body.get("x", 0) * 2}

    urls = serve.start(proxy_location="EveryNode")
    assert len(urls) == 2, urls
    assert len(set(urls)) == 2  # distinct ports (in-process nodes)

    serve.run(echo)
    for i, url in enumerate(urls):
        out = _post(url + "/echo", {"x": 10 + i})
        assert out["result"]["echo"] == (10 + i) * 2, (url, out)

    # Unknown route 404s on every proxy.
    for url in urls:
        try:
            _post(url + "/nope", {})
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404


def test_proxies_land_on_distinct_nodes(two_node_cluster):
    serve.start(proxy_location="EveryNode")
    proxies = serve.api._state["node_proxies"]
    nodes = ray.get([p.node_id.remote() for p in proxies])
    assert len(set(nodes)) == 2, nodes


# -- data-plane RequestProxy tier -------------------------------------------

@pytest.fixture
def ray4():
    rt = ray.init(num_cpus=4)
    yield rt
    serve.shutdown()
    ray.shutdown()


def test_request_proxies_route_and_head_brokered_stays_flat(ray4):
    """THE proxy-tier observable: steady-state serving over
    serve.start(num_proxies=N) adds ZERO head_brokered_submits — every
    proxy→replica call rides the DirectCaller actor channels; the head
    sees only actor resolution (warm-up) and control messages."""
    urls = serve.start(proxy_location="Disabled", num_proxies=2)
    assert urls == []

    @serve.deployment(num_replicas=2, max_concurrency=16)
    class Echo:
        def __call__(self, body):
            return {"echo": body["x"] * 2}

    handle = serve.run(Echo.bind(), name="echo")
    assert isinstance(handle, serve.ProxiedDeploymentHandle)
    # Warm-up: resolve proxy + replica actor channels (first calls may
    # legitimately fall back to the head) and let two reconcile ticks
    # (health checks, metric reports) run so their channels settle too.
    out = ray.get([handle.remote({"x": i}) for i in range(8)],
                  timeout=120)
    assert [o["echo"] for o in out] == [2 * i for i in range(8)]
    time.sleep(2.2)
    before = ray4.transfer_stats()["head_brokered_submits"]
    out = ray.get([handle.remote({"x": i}) for i in range(40)],
                  timeout=120)
    assert [o["echo"] for o in out] == [2 * i for i in range(40)]
    after = ray4.transfer_stats()["head_brokered_submits"]
    assert after == before, (
        f"steady-state serving brokered {after - before} submits "
        f"through the head")
    stats = serve.serving_stats()
    assert stats["_proxies"]["count"] == 2
    assert sum(r or 0 for r in stats["_proxies"]["routed"]) >= 48


def test_proxied_handle_spreads_over_proxies(ray4):
    """Power-of-two-choices at the handle keeps both proxies in play
    (round-robin floor guarantees spread on an idle tier)."""
    serve.start(proxy_location="Disabled", num_proxies=2)

    @serve.deployment(num_replicas=1, max_concurrency=16)
    def hello(body):
        return "hi"

    handle = serve.run(hello.bind(), name="hello")
    assert set(ray.get([handle.remote({}) for _ in range(12)],
                       timeout=120)) == {"hi"}
    proxies = serve.api._state["request_proxies"]
    routed = [ray.get(p.proxy_stats.remote(), timeout=30)["routed"]
              for p in proxies]
    assert all(r > 0 for r in routed), routed
    # method() routing rides the proxy tier too.
    assert ray.get(handle.method("__call__").remote({}), timeout=60) \
        == "hi"


def test_zero_cpu_actor_get_skips_blocked_envelope(ray4):
    """Proxy hot-path satellite: a worker whose actor holds NO positive
    resources (the RequestProxy shape, num_cpus=0) skips the
    blocked/unblocked head envelope around ray.get — it has no lease
    slot to release, so the pair was two head messages per routed
    request of pure chatter.  A CPU-holding actor must keep sending it
    (slot release while blocked is load-bearing)."""

    @ray.remote
    def produce():
        return 41

    @ray.remote(num_cpus=0)
    class ZeroCpu:
        def go(self):
            import ray_tpu as ray
            return ray.get(produce.remote()) + 1

    @ray.remote(num_cpus=1)
    class OneCpu:
        def go(self):
            import ray_tpu as ray
            return ray.get(produce.remote()) + 1

    def blocked_count(rt):
        with rt._handler_stats_lock:
            return {t: s[0] for t, s in rt._handler_stats.items()
                    }.get("blocked", 0)

    rt = ray4
    z = ZeroCpu.remote()
    assert ray.get(z.go.remote(), timeout=60) == 42  # warm (actor boot)
    time.sleep(0.3)
    before = blocked_count(rt)
    assert ray.get([z.go.remote() for _ in range(5)], timeout=60) \
        == [42] * 5
    time.sleep(0.3)
    assert blocked_count(rt) == before, "0-CPU actor sent blocked"

    o = OneCpu.remote()
    assert ray.get(o.go.remote(), timeout=60) == 42
    time.sleep(0.3)
    assert blocked_count(rt) > before, \
        "CPU-holding actor no longer reports blocked"


def test_serve_lockcheck_battery_over_proxies_and_continuous_batcher():
    """Satellite: the concurrent multi-client serving battery — client
    actors fanning requests over the RequestProxy tier into a
    continuous-batching replica — re-run under RAY_TPU_LOCKCHECK=1 with
    zero lock-order cycles, plus the head-brokered-submits-flat
    assertion under the concurrent load."""
    code = textwrap.dedent("""
        import time
        import ray_tpu as ray
        from ray_tpu import serve
        from ray_tpu.devtools import lockcheck
        from ray_tpu._private import api_internal

        assert lockcheck.enabled()
        rt = ray.init(num_cpus=6)

        @serve.deployment(num_replicas=1, max_concurrency=24)
        class Decode:
            @serve.batch(mode="continuous", max_batch_size=4,
                         batch_wait_timeout_s=0.005)
            def step(self, slots):
                time.sleep(0.002)
                for s in slots:
                    if s.state is None:
                        s.state = {"n": 0, "need": s.request["tokens"]}
                    s.state["n"] += 1
                    if s.state["n"] >= s.state["need"]:
                        s.finish(s.state["n"])

            def __call__(self, body):
                return self.step(body)

        serve.start(proxy_location="Disabled", num_proxies=2)
        handle = serve.run(Decode.bind(), name="decode")

        @ray.remote
        class LoadGen:
            def run(self, proxies, n):
                import ray_tpu as ray
                refs = [proxies[i % len(proxies)].handle_request.remote(
                            "decode", ({"tokens": 1 + i % 4},), None)
                        for i in range(n)]
                return ray.get(refs, timeout=120)

        proxies = serve.api._state["request_proxies"]
        # warm every channel, then measure the steady state
        gens = [LoadGen.remote() for _ in range(3)]
        ray.get([g.run.remote(proxies, 4) for g in gens], timeout=120)
        time.sleep(1.5)
        before = rt.transfer_stats()["head_brokered_submits"]
        out = ray.get([g.run.remote(proxies, 16) for g in gens],
                      timeout=180)
        assert [sorted(set(o)) for o in out] == [[1, 2, 3, 4]] * 3
        after = rt.transfer_stats()["head_brokered_submits"]
        assert after == before, (before, after)
        stats = serve.serving_stats("decode")
        assert stats["mode"] == "continuous" and stats["retired"] >= 48
        serve.shutdown()
        ray.shutdown()
        bad = lockcheck.violations()
        assert not bad, "lock-order violations: " + repr(bad)
        print("SERVE_LOCKCHECK_OK")
    """)
    env = dict(os.environ, RAY_TPU_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "SERVE_LOCKCHECK_OK" in proc.stdout
