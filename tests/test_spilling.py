"""Object spilling under store-capacity pressure.

Reference analog: ``src/ray/raylet/local_object_manager.h:41`` — when the
plasma store fills, unpinned primary copies spill to external storage and
restore on access; here the owner (driver) spills LRU unpinned READY
residents to ``spill_dir`` and readers restore transparently (same on-disk
layout as a shm segment, so the read path cannot tell the difference).
"""

import glob
import os

import numpy as np
import pytest

import ray_tpu as ray


CAP = 48 * 1024 * 1024  # 48 MB store
OBJ = 10 * 1024 * 1024  # 10 MB objects


@pytest.fixture
def small_store():
    rt = ray.init(num_cpus=4,
                  _system_config={"object_store_memory": CAP,
                                  "shm_pool_bytes": 0})
    yield rt
    ray.shutdown()


def test_put_past_capacity_spills_and_restores(small_store):
    rt = small_store
    refs = [ray.put(np.full(OBJ, i, dtype=np.uint8)) for i in range(10)]
    # 100 MB of live objects in a 48 MB store: spill files must exist.
    spilled = glob.glob(os.path.join(rt.spill_dir, "rtpu-*"))
    assert spilled, "no spill files created"
    # every object still reads back correctly (resident or restored)
    for i, r in enumerate(refs):
        arr = ray.get(r)
        assert arr[0] == i and arr[-1] == i and arr.shape[0] == OBJ


def test_spilled_object_feeds_task(small_store):
    rt = small_store
    refs = [ray.put(np.full(OBJ, i, dtype=np.uint8)) for i in range(10)]

    @ray.remote
    def head_byte(a):
        return int(a[0])

    # index 0 is the LRU victim — certainly spilled by now
    assert glob.glob(os.path.join(rt.spill_dir, "rtpu-*"))
    assert ray.get([head_byte.remote(r) for r in refs],
                   timeout=120) == list(range(10))


def test_freeing_spilled_object_removes_file(small_store):
    rt = small_store
    refs = [ray.put(np.full(OBJ, i, dtype=np.uint8)) for i in range(10)]
    n_before = len(glob.glob(os.path.join(rt.spill_dir, "rtpu-*")))
    assert n_before > 0
    del refs
    import gc
    import time

    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if not glob.glob(os.path.join(rt.spill_dir, "rtpu-*")):
            break
        time.sleep(0.2)
    assert not glob.glob(os.path.join(rt.spill_dir, "rtpu-*"))


def test_worker_results_spill_too(small_store):
    """Task returns (worker-created segments) participate: the owner spills
    them and notifies the creating worker to drop its pooled mapping."""
    rt = small_store

    @ray.remote
    def make(i):
        return np.full(OBJ, i, dtype=np.uint8)

    refs = [make.remote(i) for i in range(10)]
    vals = ray.get(refs, timeout=120)
    for i, v in enumerate(vals):
        assert v[0] == i


def test_worker_owned_puts_spill(small_store):
    """A worker whose OWN store fills during owner-local puts spills its
    owned objects per-node (local_object_manager.h:41) — the v1 design
    only spilled on the head node."""
    rt = small_store

    @ray.remote
    class Putter:
        def fill(self, n, size):
            import numpy as np

            import ray_tpu as ray

            refs = [ray.put(np.full(size, i, dtype=np.uint8))
                    for i in range(n)]
            # All live simultaneously: 100 MB owned in a 48 MB cap.
            return [int(ray.get(r)[0]) for r in refs]

    p = Putter.remote()
    assert ray.get(p.fill.remote(10, OBJ), timeout=120) == list(range(10))


def test_remote_node_task_returns_overflow():
    """VERDICT #3 'done' criterion: a REMOTE (agent) node overfills its
    store during task returns and the job still completes — returns
    spill on that node and the driver restores them through the
    transfer path."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(head_num_cpus=1)
    try:
        node_id = cluster.add_node(
            num_cpus=2, external=True,
            env_overrides={"RAY_TPU_STORE_BYTES": str(CAP),
                           "RAY_TPU_POOL_BYTES": "0"})

        @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node_id, soft=False))
        def make(i):
            import numpy as np

            return np.full(OBJ, i, dtype=np.uint8)

        # 100 MB of returns against a 48 MB remote store cap.
        refs = [make.remote(i) for i in range(10)]
        vals = ray.get(refs, timeout=180)
        for i, v in enumerate(vals):
            assert v[0] == i and len(v) == OBJ
    finally:
        cluster.shutdown()
