"""Core task API tests (reference model: python/ray/tests/test_basic.py)."""

import os
import time

import numpy as np
import pytest

import ray_tpu as ray


def test_simple_task(ray_start_regular):
    @ray.remote
    def f(a, b):
        return a + b

    assert ray.get(f.remote(1, 2)) == 3


def test_task_kwargs(ray_start_regular):
    @ray.remote
    def f(a, b=10, c=0):
        return a + b + c

    assert ray.get(f.remote(1, c=5)) == 16


def test_chained_dependencies(ray_start_regular):
    @ray.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(4):
        ref = inc.remote(ref)
    assert ray.get(ref, timeout=30) == 5


def test_put_get_roundtrip(ray_start_regular):
    for value in [1, "s", {"a": [1, 2]}, None, (1, 2)]:
        assert ray.get(ray.put(value)) == value


def test_large_object_zero_copy(ray_start_regular):
    arr = np.arange(2_000_000, dtype=np.float32)
    ref = ray.put(arr)
    out = ray.get(ref)
    np.testing.assert_array_equal(arr, out)

    @ray.remote
    def total(x):
        return float(x.sum())

    assert ray.get(total.remote(ref), timeout=30) == float(arr.sum())


def test_large_task_arg_and_return(ray_start_regular):
    @ray.remote
    def make(n):
        return np.ones(n, dtype=np.float64)

    @ray.remote
    def consume(x):
        return float(x.sum())

    big = make.remote(1_000_000)  # 8 MB -> shm
    assert ray.get(consume.remote(big), timeout=60) == 1_000_000.0


def test_multiple_returns(ray_start_regular):
    @ray.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray.get([a, b, c]) == [1, 2, 3]


def test_error_propagation(ray_start_regular):
    @ray.remote
    def boom():
        raise ValueError("nope")

    with pytest.raises(ray.exceptions.TaskError) as ei:
        ray.get(boom.remote(), timeout=30)
    assert isinstance(ei.value.cause, ValueError)


def test_dependency_error_propagates(ray_start_regular):
    @ray.remote
    def boom():
        raise ValueError("upstream")

    @ray.remote
    def consume(x):
        return x

    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(consume.remote(boom.remote()), timeout=30)


def test_wait(ray_start_regular):
    @ray.remote
    def slow(i):
        time.sleep(0.05 * i)
        return i

    refs = [slow.remote(i) for i in range(4)]
    ready, not_ready = ray.wait(refs, num_returns=2, timeout=15)
    assert len(ready) == 2
    assert len(not_ready) == 2
    ready2, _ = ray.wait(refs, num_returns=4, timeout=15)
    assert len(ready2) == 4


def test_wait_timeout(ray_start_regular):
    @ray.remote
    def never():
        time.sleep(60)

    ref = never.remote()
    t0 = time.monotonic()
    ready, not_ready = ray.wait([ref], num_returns=1, timeout=0.5)
    assert time.monotonic() - t0 < 5
    assert ready == [] and not_ready == [ref]
    ray.cancel(ref, force=True)


def test_get_timeout(ray_start_regular):
    @ray.remote
    def never():
        time.sleep(60)

    ref = never.remote()
    with pytest.raises(ray.exceptions.GetTimeoutError):
        ray.get(ref, timeout=0.5)
    ray.cancel(ref, force=True)


def test_nested_tasks(ray_start_regular):
    @ray.remote
    def outer():
        @ray.remote
        def inner(x):
            return x * 2

        return ray.get(inner.remote(21))

    assert ray.get(outer.remote(), timeout=60) == 42


def test_task_retry_on_worker_crash(ray_start_regular):
    marker = f"/tmp/ray_tpu_test_marker_{os.getpid()}"
    if os.path.exists(marker):
        os.remove(marker)

    @ray.remote(max_retries=2)
    def flaky():
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)
        os.remove(marker)
        return "recovered"

    assert ray.get(flaky.remote(), timeout=60) == "recovered"


def test_no_retry_surfaces_crash(ray_start_regular):
    @ray.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(ray.exceptions.WorkerCrashedError):
        ray.get(die.remote(), timeout=60)


def test_cancel_pending(ray_start_regular):
    @ray.remote
    def block():
        time.sleep(60)

    # fill all 4 cpus, then queue one more
    blockers = [block.remote() for _ in range(4)]
    victim = block.remote()
    ray.cancel(victim)
    with pytest.raises(ray.exceptions.TaskCancelledError):
        ray.get(victim, timeout=30)
    for b in blockers:
        ray.cancel(b, force=True)


def test_options_override(ray_start_regular):
    @ray.remote(num_cpus=1)
    def f():
        return ray.get_runtime_context() is not None

    # runs even though it asks for fewer cpus than default
    assert ray.get(f.options(num_cpus=2).remote(), timeout=30)


def test_runtime_env_env_vars(ray_start_regular):
    @ray.remote(runtime_env={"env_vars": {"MY_FLAG": "abc"}})
    def read_env():
        return os.environ.get("MY_FLAG")

    assert ray.get(read_env.remote(), timeout=60) == "abc"


def test_cluster_resources(ray_start_regular):
    total = ray.cluster_resources()
    assert total["CPU"] == 4.0


def test_nested_ref_in_container_arg(ray_start_regular):
    """Refs pickled inside containers are pinned until the task completes
    (regression: serialize-time pins used to leak forever)."""

    @ray.remote
    def consume(lst):
        return ray.get(lst[0]) + 1

    x = ray.put(41)
    assert ray.get(consume.remote([x]), timeout=60) == 42
    # the pin must be released: dropping the last ref frees the object
    rt = ray_start_regular
    oid = x.id()
    del x
    deadline = time.time() + 10
    while time.time() < deadline:
        with rt.lock:
            if oid not in rt.objects:
                break
        time.sleep(0.1)
    with rt.lock:
        assert oid not in rt.objects, "nested-ref pin leaked"


def test_worker_side_get_timeout(ray_start_regular):
    """ray.get(timeout=...) inside a task raises instead of hanging."""

    @ray.remote
    def waiter(refs):
        # refs arrives inside a container, so it is NOT awaited as a task
        # dependency (top-level ref args are; same as the reference).
        try:
            ray.get(refs[0], timeout=0.5)
            return "no-timeout"
        except ray.exceptions.GetTimeoutError:
            return "timed-out"

    @ray.remote
    def never():
        time.sleep(60)

    pending = never.remote()
    assert ray.get(waiter.remote([pending]), timeout=60) == "timed-out"
    ray.cancel(pending, force=True)
