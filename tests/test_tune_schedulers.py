"""HyperBand, median stopping, and the TPE searcher (reference:
tune/schedulers/hyperband.py, median_stopping_rule.py,
search/optuna|hyperopt adapters)."""
import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu import tune
from ray_tpu.tune import (
    HyperBandScheduler, MedianStoppingRule, TPESearcher, Trainable,
)


@pytest.fixture(scope="module")
def cluster():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray.shutdown()


class Converging(Trainable):
    """score -> config['target'] as iterations grow; checkpointable so
    HyperBand's pause/promote round-trips state."""

    def setup(self, config):
        self.target = config["target"]
        self.t = 0

    def step(self):
        self.t += 1
        score = self.target * (1 - 0.5 ** self.t)
        return {"score": score, "training_iteration": self.t}

    def save_checkpoint(self):
        return {"t": self.t}

    def load_checkpoint(self, state):
        self.t = state["t"]


@pytest.mark.slow  # ~38s; early-stopping schedulers keep their tier-1
                   # representative in test_tune.py's ASHA rung-logic +
                   # integration tests; hyperband's pause/promote
                   # specifics stay covered in the slow tier
def test_hyperband_promotes_best_and_stops_losers(cluster):
    targets = [0.1, 0.2, 0.9, 0.4, 0.95, 0.3]
    analysis = tune.run(
        Converging,
        config={"target": tune.grid_search(targets)},
        scheduler=HyperBandScheduler(metric="score", mode="max",
                                     max_t=16, reduction_factor=2.0,
                                     bracket_size=6, grace_period=2),
        stop={"training_iteration": 16},
    )
    iters = {t.config["target"]: t.last_result["training_iteration"]
             for t in analysis.trials}
    best = max(analysis.trials,
               key=lambda t: t.last_result.get("score", -1))
    assert best.config["target"] == 0.95
    # The winner ran to (near) max_t; the worst trial was halted early.
    assert iters[0.95] >= 8
    assert iters[0.1] <= 4, iters
    total = sum(iters.values())
    assert total < len(targets) * 16 * 0.75, iters  # real savings


@pytest.mark.slow  # ~15s; early-stopping coverage rides tier-1's
                   # hyperband test, making this the duplicate
def test_median_stopping_rule_stops_bad_trials(cluster):
    targets = [0.1, 0.15, 0.9, 0.85, 0.8]
    # Reporting order is load-dependent on a small box: if both bad
    # trials race through all 12 iterations before two good trials clear
    # the grace period, nothing gets cut and the run proves nothing
    # about the rule.  Retry (bounded) until the schedule actually
    # interleaved; assertions below stay strict.
    for _attempt in range(3):
        analysis = tune.run(
            Converging,
            config={"target": tune.grid_search(targets)},
            scheduler=MedianStoppingRule(metric="score", mode="max",
                                         grace_period=3,
                                         min_samples_required=2),
            stop={"training_iteration": 12},
        )
        iters = {t.config["target"]: t.last_result["training_iteration"]
                 for t in analysis.trials}
        if min(iters[0.1], iters[0.15]) < 12:
            break
    # The bad trials run below the median of the good cohort; at least
    # one must be cut early.
    assert min(iters[0.1], iters[0.15]) < 12, iters
    assert iters[0.9] == 12, iters         # ran out the budget
    assert iters[0.85] == 12, iters
    best = max(analysis.trials,
               key=lambda t: t.last_result.get("score", -1))
    assert best.config["target"] == 0.9


def test_tpe_searcher_beats_random_on_quadratic():
    space = {"x": tune.uniform(0.0, 1.0)}
    tpe = TPESearcher(space, metric="score", mode="max",
                      num_samples=48, n_startup=10, seed=0)
    xs = []
    for i in range(48):
        tid = f"t{i}"
        cfg = tpe.suggest(tid)
        score = -(cfg["x"] - 0.7) ** 2
        tpe.on_trial_complete(tid, {"score": score})
        xs.append(cfg["x"])
    assert tpe.suggest("extra") is None  # budget respected
    best = max(xs, key=lambda x: -(x - 0.7) ** 2)
    assert abs(best - 0.7) < 0.05
    # The model phase concentrates near the optimum vs the random phase.
    startup_err = np.mean([abs(x - 0.7) for x in xs[:10]])
    model_err = np.mean([abs(x - 0.7) for x in xs[-20:]])
    assert model_err < startup_err, (startup_err, model_err)


@pytest.mark.slow  # ~34s; TPE logic has two fast in-process tests here
                   # and tune.run wiring is covered by test_tune.py
def test_tpe_through_tune_run_receives_observations(cluster):
    """The runner must key suggest() and on_trial_complete() by the SAME
    trial id, or model-based searchers never see an observation."""
    space = {"x": tune.uniform(0.0, 1.0)}
    tpe = TPESearcher(space, metric="score", mode="max",
                      num_samples=14, n_startup=6, seed=2)

    def objective(config):
        return {"score": -(config["x"] - 0.6) ** 2, "done": True}

    tune.run(objective, search_alg=tpe, metric="score", mode="max",
             max_concurrent_trials=2)
    assert len(tpe._observed) == 14, len(tpe._observed)
    assert not tpe._pending  # every suggestion matched a completion


def test_tpe_categorical_picks_good_arm():
    space = {"arm": tune.choice(["a", "b", "c"])}
    tpe = TPESearcher(space, metric="score", mode="max",
                      num_samples=40, n_startup=12, seed=1)
    reward = {"a": 0.1, "b": 1.0, "c": 0.2}
    picks = []
    for i in range(40):
        tid = f"t{i}"
        cfg = tpe.suggest(tid)
        tpe.on_trial_complete(tid, {"score": reward[cfg["arm"]]})
        picks.append(cfg["arm"])
    assert picks[-8:].count("b") >= 6, picks[-8:]
