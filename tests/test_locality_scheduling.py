"""Locality-aware scheduling: the default policy scores candidate nodes
by argument bytes homed in their object store and prefers the
top-locality node, without ever stalling a class or bypassing the
pipeline depth cap.

Reference analog: locality-aware lease selection in
``scheduling/policy/hybrid_scheduling_policy.cc`` through the owner's
object directory — the head holds that directory here (every SHM/SPILLED
descriptor carries ``(size, home store_id)``), so placement can chase
the bytes instead of shipping them.

Covered:
- the acceptance micro: a fan-out whose single large arg is homed on one
  node agent schedules >= 80% of tasks onto that node (``locality_hits``)
  and ``locality_bytes_saved`` records the avoided transfers;
- with ``locality_scheduling`` off, placement is the pre-PR head-first
  order and every locality counter stays zero;
- locality preference never bypasses ``max_tasks_in_flight_per_worker``:
  past the depth cap the spill-over tasks place normally (counted in
  ``locality_misses``);
- scheduler policy edges with no prior coverage: ``node_affinity`` soft
  fallback when the named node is full or dead (and hard affinity
  pending forever on a dead node), a PG task whose bundle can never fit
  staying queued while the PG itself stays usable;
- ``spread`` tie-breaking is deterministic (earliest node in
  ``node_order`` wins among equals).
"""

import os
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy as NA,
)

ARG_MB = 4


@pytest.fixture
def cluster_factory():
    from ray_tpu.cluster_utils import Cluster

    made = []

    def make(**kw):
        c = Cluster(**kw)
        made.append(c)
        return c

    yield make
    for c in made:
        c.shutdown()


def _home_big_arg(n1: str, nbytes: int):
    """A large object homed in node ``n1``'s store (produced there)."""

    @ray.remote
    def make(n):
        return np.ones(n, np.uint8)

    ref = make.options(scheduling_strategy=NA(n1)).remote(nbytes)
    ready, _ = ray.wait([ref], num_returns=1, timeout=60)
    assert ready
    return ref


@ray.remote
def _where(_a):
    return os.environ["RAY_TPU_NODE_ID"]


# ------------------------------------------------------ acceptance micro --

def test_locality_fanout_prefers_home_node(cluster_factory):
    c = cluster_factory(head_num_cpus=4)
    n1 = c.add_node(num_cpus=2, external=True)
    c.add_node(num_cpus=2, external=True)
    ref = _home_big_arg(n1, ARG_MB << 20)

    base_hits = c.rt.locality_hits
    base_saved = c.rt.locality_bytes_saved
    n = 20
    nodes = ray.get([_where.remote(ref) for _ in range(n)], timeout=120)
    frac = nodes.count(n1) / n
    assert frac >= 0.8, f"only {frac:.0%} of tasks ran on the arg's node"
    assert c.rt.locality_hits - base_hits >= int(n * 0.8), \
        (c.rt.locality_hits, base_hits)
    saved = c.rt.locality_bytes_saved - base_saved
    assert saved >= int(n * 0.8) * (ARG_MB << 20), saved


def test_locality_off_is_head_first_and_counters_zero(cluster_factory):
    c = cluster_factory(head_num_cpus=4,
                        _system_config={"locality_scheduling": False})
    n1 = c.add_node(num_cpus=2, external=True)
    ref = _home_big_arg(n1, ARG_MB << 20)

    head_id = c.rt.head_node.node_id.hex()
    # Pre-PR behavior: head-first packing — a burst within the head's
    # capacity lands entirely on the head, args pulled across the wire.
    nodes = ray.get([_where.remote(ref) for _ in range(4)], timeout=120)
    assert nodes.count(head_id) == 4, nodes
    assert c.rt.locality_hits == 0
    assert c.rt.locality_misses == 0
    assert c.rt.locality_bytes_saved == 0


# -------------------------------------------- depth-cap interaction ------

def test_locality_does_not_bypass_pipeline_depth_cap(cluster_factory):
    depth = 2
    c = cluster_factory(
        head_num_cpus=2,
        _system_config={"max_tasks_in_flight_per_worker": depth})
    n1 = c.add_node(num_cpus=1, external=True)
    ref = _home_big_arg(n1, 2 << 20)

    @ray.remote
    def slow(_a):
        # Long enough that all 6 submissions dispatch while every task
        # still runs (submission is milliseconds), short for suite time.
        time.sleep(0.6)
        return os.environ["RAY_TPU_NODE_ID"]

    base_hits = c.rt.locality_hits
    base_miss = c.rt.locality_misses
    # 6 tasks, all preferring n1 (1 CPU): one fresh lease + one pipelined
    # slot reach the depth cap; the other 4 must place on the head even
    # though their bytes live on n1 — locality never queues past the cap.
    nodes = ray.get([slow.remote(ref) for _ in range(6)], timeout=120)
    assert nodes.count(n1) == depth, nodes
    assert c.rt.locality_hits - base_hits == depth
    assert c.rt.locality_misses - base_miss == 6 - depth


# ------------------------------------------------ policy edges ------------

def test_node_affinity_soft_falls_back_when_node_full(ray_start_regular):
    rt = ray_start_regular
    nid = rt.add_node(num_cpus=1)

    @ray.remote
    def hold():
        time.sleep(5)
        return "held"

    @ray.remote
    def quick():
        return os.environ["RAY_TPU_NODE_ID"]

    h = hold.options(scheduling_strategy=NA(nid.hex())).remote()
    time.sleep(0.3)  # let the hard-affinity task take the node's slot
    out = ray.get(
        quick.options(scheduling_strategy=NA(nid.hex(), soft=True)).remote(),
        timeout=30)
    # Soft affinity fell back to another node instead of queueing.
    assert out != nid.hex()
    ray.cancel(h, force=True)


def test_node_affinity_dead_node_soft_vs_hard(ray_start_regular):
    rt = ray_start_regular
    nid = rt.add_node(num_cpus=1)
    rt.remove_node(nid)

    @ray.remote
    def quick():
        return os.environ["RAY_TPU_NODE_ID"]

    out = ray.get(
        quick.options(scheduling_strategy=NA(nid.hex(), soft=True)).remote(),
        timeout=30)
    assert out != nid.hex()
    hard = quick.options(scheduling_strategy=NA(nid.hex())).remote()
    ready, not_ready = ray.wait([hard], num_returns=1, timeout=1.5)
    assert not ready and not_ready == [hard]


def test_pg_task_rejected_when_bundle_cannot_fit(ray_start_regular):
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray.remote(num_cpus=2)
    def too_big():
        return "ran"

    @ray.remote(num_cpus=1)
    def fits():
        return "ran"

    strat = PlacementGroupSchedulingStrategy(pg, 0)
    big_ref = too_big.options(scheduling_strategy=strat).remote()
    ready, _ = ray.wait([big_ref], num_returns=1, timeout=1.5)
    assert ready == []  # 2 CPUs can never fit the 1-CPU bundle
    # The bundle stays usable for correctly-sized work behind it.
    assert ray.get(fits.options(scheduling_strategy=strat).remote(),
                   timeout=30) == "ran"
    remove_placement_group(pg)


# ------------------------------------------------- spread determinism ----

def test_spread_tie_break_is_deterministic(ray_start_regular):
    from ray_tpu._private.runtime import TaskRecord

    rt = ray_start_regular
    rt.add_node(num_cpus=4)
    rt.add_node(num_cpus=4)

    def pick():
        rec = TaskRecord(
            {"scheduling_strategy": ("spread",), "args": [],
             "num_returns": 1, "task_id": b"\0" * 16},
            {"CPU": 1.0}, 0)
        with rt.lock:
            return rt._pick_node_locked(rec)

    # All nodes idle: equal scores on the two equal nodes; the head's
    # score differs (different total resources) but whatever wins must
    # win every time.
    first = pick()
    assert all(pick() is first for _ in range(10))
    # Break the tie by consuming capacity on the winner: the next pick
    # moves to the earliest remaining best node, again deterministically.
    with rt.lock:
        first.acquire({"CPU": 1.0})
    second = pick()
    assert second is not first
    assert all(pick() is second for _ in range(10))
    with rt.lock:
        first.release({"CPU": 1.0})
