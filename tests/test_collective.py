"""Host-collective tests (reference pattern:
python/ray/util/collective/tests)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util import collective as col


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    ray.shutdown()


@ray.remote
class Member:
    def execute(self, fn, *a, **kw):
        return fn(*a, **kw)

    def do_allreduce(self, rank):
        return col.allreduce(np.full(4, rank + 1.0), op="sum")

    def do_allgather(self, rank):
        return col.allgather(np.array([rank], np.float32))

    def do_reducescatter(self, rank):
        return col.reducescatter(np.arange(8, dtype=np.float32), op="sum")

    def do_broadcast(self, rank):
        arr = np.full(3, 42.0) if rank == 0 else np.zeros(3)
        return col.broadcast(arr, src_rank=0)

    def do_sendrecv(self, rank):
        if rank == 0:
            col.send(np.array([7.0, 8.0]), dst_rank=1)
            return None
        return col.recv(src_rank=0)


def _make_group(n):
    members = [Member.options(num_cpus=1).remote() for _ in range(n)]
    col.create_collective_group(members, n, list(range(n)))
    return members


def test_allreduce_sum(ray8):
    members = _make_group(3)
    outs = ray.get([m.do_allreduce.remote(i) for i, m in enumerate(members)])
    for o in outs:
        assert np.allclose(o, np.full(4, 1.0 + 2.0 + 3.0))


def test_allgather(ray8):
    members = _make_group(3)
    outs = ray.get([m.do_allgather.remote(i) for i, m in enumerate(members)])
    for o in outs:
        assert [float(x[0]) for x in o] == [0.0, 1.0, 2.0]


def test_reducescatter(ray8):
    members = _make_group(2)
    outs = ray.get([m.do_reducescatter.remote(i)
                    for i, m in enumerate(members)])
    full = 2 * np.arange(8, dtype=np.float32)
    assert np.allclose(outs[0], full[:4])
    assert np.allclose(outs[1], full[4:])


def test_broadcast(ray8):
    members = _make_group(3)
    outs = ray.get([m.do_broadcast.remote(i)
                    for i, m in enumerate(members)])
    for o in outs:
        assert np.allclose(o, 42.0)


def test_send_recv(ray8):
    members = _make_group(2)
    outs = ray.get([m.do_sendrecv.remote(i) for i, m in enumerate(members)])
    assert outs[0] is None
    assert np.allclose(outs[1], [7.0, 8.0])


def test_actor_pool(ray8):
    @ray.remote
    class Sq:
        def sq(self, x):
            return x * x

    from ray_tpu.util import ActorPool
    pool = ActorPool([Sq.remote() for _ in range(2)])
    out = sorted(pool.map(lambda a, v: a.sq.remote(v), range(6)))
    assert out == [0, 1, 4, 9, 16, 25]


def test_distributed_queue(ray8):
    from ray_tpu.util.queue import Queue, Empty
    q = Queue(maxsize=4)
    q.put({"a": 1})
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == {"a": 1}
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()
