"""Host-collective tests (reference pattern:
python/ray/util/collective/tests)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util import collective as col


@pytest.fixture
def ray8():
    rt = ray.init(num_cpus=8)
    yield rt
    ray.shutdown()


@ray.remote
class Member:
    def execute(self, fn, *a, **kw):
        return fn(*a, **kw)

    def do_allreduce(self, rank):
        return col.allreduce(np.full(4, rank + 1.0), op="sum")

    def do_allgather(self, rank):
        return col.allgather(np.array([rank], np.float32))

    def do_reducescatter(self, rank):
        return col.reducescatter(np.arange(8, dtype=np.float32), op="sum")

    def do_broadcast(self, rank):
        arr = np.full(3, 42.0) if rank == 0 else np.zeros(3)
        return col.broadcast(arr, src_rank=0)

    def do_sendrecv(self, rank):
        if rank == 0:
            col.send(np.array([7.0, 8.0]), dst_rank=1)
            return None
        return col.recv(src_rank=0)


def _make_group(n):
    members = [Member.options(num_cpus=1).remote() for _ in range(n)]
    col.create_collective_group(members, n, list(range(n)))
    return members


def test_allreduce_sum(ray8):
    members = _make_group(3)
    outs = ray.get([m.do_allreduce.remote(i) for i, m in enumerate(members)])
    for o in outs:
        assert np.allclose(o, np.full(4, 1.0 + 2.0 + 3.0))


def test_allgather(ray8):
    members = _make_group(3)
    outs = ray.get([m.do_allgather.remote(i) for i, m in enumerate(members)])
    for o in outs:
        assert [float(x[0]) for x in o] == [0.0, 1.0, 2.0]


def test_reducescatter(ray8):
    members = _make_group(2)
    outs = ray.get([m.do_reducescatter.remote(i)
                    for i, m in enumerate(members)])
    full = 2 * np.arange(8, dtype=np.float32)
    assert np.allclose(outs[0], full[:4])
    assert np.allclose(outs[1], full[4:])


def test_broadcast(ray8):
    members = _make_group(3)
    outs = ray.get([m.do_broadcast.remote(i)
                    for i, m in enumerate(members)])
    for o in outs:
        assert np.allclose(o, 42.0)


def test_send_recv(ray8):
    members = _make_group(2)
    outs = ray.get([m.do_sendrecv.remote(i) for i, m in enumerate(members)])
    assert outs[0] is None
    assert np.allclose(outs[1], [7.0, 8.0])


def test_actor_pool(ray8):
    @ray.remote
    class Sq:
        def sq(self, x):
            return x * x

    from ray_tpu.util import ActorPool
    pool = ActorPool([Sq.remote() for _ in range(2)])
    out = sorted(pool.map(lambda a, v: a.sq.remote(v), range(6)))
    assert out == [0, 1, 4, 9, 16, 25]


def test_distributed_queue(ray8):
    from ray_tpu.util.queue import Queue, Empty
    q = Queue(maxsize=4)
    q.put({"a": 1})
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == {"a": 1}
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


@ray.remote
class RingMember:
    """Large payloads: the ring transport engages (>= 1024 elements)."""

    def execute(self, fn, *a, **kw):
        return fn(*a, **kw)

    def ring_allreduce(self, rank, n):
        out = col.allreduce(np.full(n, rank + 1.0, np.float32), op="sum")
        assert col._group("default").ring is not None, "ring not active"
        return float(out[0]), float(out[-1]), out.shape

    def ring_allgather(self, rank, n):
        outs = col.allgather(np.full(n, float(rank), np.float32))
        return [float(o[0]) for o in outs]

    def ring_reducescatter(self, rank, n, world):
        out = col.reducescatter(np.arange(n, dtype=np.float64), op="sum")
        expect = np.array_split(np.arange(n) * world, world)[rank]
        assert np.allclose(out, expect), (out[:4], expect[:4])
        return len(out)

    def ring_mean(self, rank, n):
        out = col.allreduce(np.full(n, rank + 1.0, np.float32), op="mean")
        return float(out[0])

    def timed(self, rank, n, reps):
        import time

        arr = np.ones(n, np.float32)
        col.allreduce(arr)  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            col.allreduce(arr)
        return time.perf_counter() - t0


def _ring_group(n):
    members = [RingMember.options(num_cpus=1).remote() for _ in range(n)]
    col.create_collective_group(members, n, list(range(n)))
    return members


def test_ring_allreduce(ray8):
    members = _ring_group(4)
    n = 40_000
    outs = ray.get([m.ring_allreduce.remote(i, n)
                    for i, m in enumerate(members)], timeout=120)
    for first, last, shape in outs:
        assert first == last == 1 + 2 + 3 + 4
        assert shape == (n,)


def test_ring_allgather(ray8):
    members = _ring_group(3)
    outs = ray.get([m.ring_allgather.remote(i, 5000)
                    for i, m in enumerate(members)], timeout=120)
    for o in outs:
        assert o == [0.0, 1.0, 2.0]


def test_ring_reducescatter_matches_star_semantics(ray8):
    members = _ring_group(4)
    lens = ray.get([m.ring_reducescatter.remote(i, 10_000, 4)
                    for i, m in enumerate(members)], timeout=120)
    assert sum(lens) == 10_000


def test_ring_mean(ray8):
    members = _ring_group(3)
    outs = ray.get([m.ring_mean.remote(i, 4096)
                    for i, m in enumerate(members)], timeout=120)
    assert all(abs(o - 2.0) < 1e-5 for o in outs)


@pytest.mark.slow  # ~6s perf A/B; ring CORRECTNESS keeps its tier-1
# coverage via the sub-second ring_allreduce/allgather/reducescatter/
# mean tests above — this row only re-measures the speedup.
def test_ring_beats_star_bench(ray8):
    """VERDICT #4 'done': big allreduce through the ring vs the star.
    On multi-core hardware the ring wins >2x (every link busy vs one
    actor's GIL); on a 1-core CI box we only record the numbers."""
    import os

    n = 2_000_000  # 8 MB fp32 per rank
    world = 4
    members = _ring_group(world)
    t_ring = max(ray.get([m.timed.remote(i, n, 3)
                          for i, m in enumerate(members)], timeout=300))

    # Same workload with the ring disabled (star coordinator).
    def _kill_ring():
        g = col._group("default")
        if g.ring is not None:
            g.ring.close()
            g.ring = None
        return True

    ray.get([m.execute.remote(_kill_ring) for m in members])
    t_star = max(ray.get([m.timed.remote(i, n, 3)
                          for i, m in enumerate(members)], timeout=300))
    print(f"ring={t_ring:.3f}s star={t_star:.3f}s "
          f"speedup={t_star / t_ring:.2f}x")
    if (os.cpu_count() or 1) >= 4:
        assert t_star / t_ring > 2.0


def test_ring_reducescatter_multidim_matches_star(ray8):
    """Multi-dim reducescatter splits along axis 0 on BOTH transports."""
    @ray.remote
    class M2:
        def execute(self, fn, *a, **kw):
            return fn(*a, **kw)

        def rs(self, rank):
            out = col.reducescatter(np.ones((400, 8), np.float32) * (rank + 1))
            return out.shape, float(out[0, 0])

    members = [M2.options(num_cpus=1).remote() for _ in range(2)]
    col.create_collective_group(members, 2, [0, 1])
    outs = ray.get([m.rs.remote(i) for i, m in enumerate(members)],
                   timeout=120)
    for shape, v in outs:
        assert shape == (200, 8)
        assert v == 3.0  # 1 + 2
