"""Shared pytest fixtures.

Mirrors the reference's conftest pattern (``python/ray/tests/conftest.py``:
``ray_start_regular`` :305 boots a real single-node runtime in-process;
``ray_start_cluster`` :386 boots a multi-node cluster on one machine).

JAX-level tests run on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``), the standard way to test TPU
sharding logic without TPU hardware.
"""

import os

# Must be set before jax backend init anywhere in the test process.  The
# image's sitecustomize registers a real-TPU 'axon' backend at interpreter
# start, so the CPU override must additionally go through jax.config (env
# vars alone are read before conftest runs).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
# Tests never own the real TPU tunnel.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import sys

if "jax" in sys.modules:
    # sitecustomize (axon TPU tunnel) already imported jax and snapshotted
    # JAX_PLATFORMS=axon — override through config.  Otherwise the env var
    # above suffices and we skip paying the jax import for runtime-only
    # test files.
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Single-node runtime with 4 CPUs (reference: ray_start_regular)."""
    import ray_tpu as ray

    rt = ray.init(num_cpus=4, num_tpus=0, ignore_reinit_error=False)
    yield rt
    ray.shutdown()


@pytest.fixture
def chaos_controller():
    """Chaos-injection harness bound to the current runtime (list this
    fixture AFTER the fixture that boots the runtime, e.g.
    ``ray_start_regular``).  Arms the process's syncpoints for the
    test's duration and disarms + cancels schedules on teardown, so the
    whole battery can run under ``RAY_TPU_LOCKCHECK=1``.

    ``kill_head``/``restart_head`` are exposed too: attach an external
    head first (``ctl.attach_head(Cluster(external_head=True))``) —
    an in-process head shares the test's pid, so there is nothing
    survivable to kill and the methods raise."""
    from ray_tpu.chaos import ChaosController

    ctl = ChaosController()
    yield ctl
    ctl.stop()


@pytest.fixture
def ray_start_cluster():
    """Multi-node-on-one-host cluster handle (reference:
    ray_start_cluster / cluster_utils.Cluster)."""
    import ray_tpu as ray

    class Cluster:
        def __init__(self):
            self.rt = ray.init(num_cpus=2, num_tpus=0)

        def add_node(self, **kw):
            return self.rt.add_node(**kw)

        def remove_node(self, node_id):
            return self.rt.remove_node(node_id)

    c = Cluster()
    yield c
    ray.shutdown()
