"""LearnerGroup dp-sharding: the N-device mesh update must match the
single-device update numerically (reference: learner_group.py:51 scaling
config; here scaling = batch sharding + XLA gradient psum)."""
import numpy as np
import pytest

from ray_tpu.rllib import ActorCriticMLP, Learner, LearnerGroup, SampleBatch
from ray_tpu.rllib.ppo import ppo_loss
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ADVANTAGES, LOGP, OBS, VALUE_TARGETS,
)


def _batch(n=64, obs_dim=6, num_actions=3, seed=0):
    rng = np.random.default_rng(seed)
    return SampleBatch({
        OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        ACTIONS: rng.integers(num_actions, size=n).astype(np.int32),
        LOGP: rng.normal(scale=0.1, size=n).astype(np.float32),
        ADVANTAGES: rng.normal(size=n).astype(np.float32),
        VALUE_TARGETS: rng.normal(size=n).astype(np.float32),
    })


def test_dp_sharded_update_matches_single_device():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    module = ActorCriticMLP(6, 3, hidden=(16,))

    def loss(params, mod, batch):
        return ppo_loss(params, mod, batch)

    single = Learner(module, loss, seed=3)
    group = LearnerGroup(
        lambda mesh=None: Learner(module, loss, seed=3, mesh=mesh),
        num_learners=8)

    batch = _batch()
    for step in range(3):
        m1 = single.update(batch)
        m8 = group.update(batch)
        assert m1["total_loss"] == pytest.approx(m8["total_loss"],
                                                 rel=1e-4), step
    p1 = single.get_weights()
    p8 = group.get_weights()
    flat1 = jax.tree_util.tree_leaves(p1)
    flat8 = jax.tree_util.tree_leaves(p8)
    for a, b in zip(flat1, flat8):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_mesh_unaware_factory_gets_rehomed():
    """A factory without a ``mesh`` kwarg still shards: the group re-homes
    its params and spec onto the dp mesh."""
    module = ActorCriticMLP(4, 2, hidden=(8,))

    def loss(params, mod, batch):
        return ppo_loss(params, mod, batch)

    group = LearnerGroup(lambda: Learner(module, loss, seed=1),
                         num_learners=4)
    m = group.update(_batch(n=32, obs_dim=4, num_actions=2))
    assert np.isfinite(m["total_loss"])
    lr = group._learner
    assert lr._mesh is not None and lr._mesh.shape["dp"] == 4
