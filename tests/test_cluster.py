"""Multi-node scheduling, placement groups, failure handling
(reference model: python/ray/tests/test_multinode_failures.py,
test_placement_group.py, test_scheduling.py — exercised via the in-process
multi-node Cluster pattern, python/ray/cluster_utils.py:99)."""

import time

import pytest

import ray_tpu as ray
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


def test_spillback_to_second_node(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)

    @ray.remote(num_cpus=1)
    def where():
        return ray.get_runtime_context().node_id

    # 4 concurrent tasks across 2+2 cpus must use both nodes
    @ray.remote(num_cpus=1)
    def busy():
        time.sleep(1.0)
        return ray.get_runtime_context().node_id

    refs = [busy.remote() for _ in range(4)]
    nodes = set(ray.get(refs, timeout=60))
    assert len(nodes) == 2


def test_infeasible_task_queues_until_node_added(ray_start_cluster):
    cluster = ray_start_cluster

    @ray.remote(num_cpus=8)
    def big():
        return "ran"

    ref = big.remote()
    ready, _ = ray.wait([ref], num_returns=1, timeout=1)
    assert ready == []
    cluster.add_node(num_cpus=8)
    assert ray.get(ref, timeout=60) == "ran"


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    nid = cluster.add_node(num_cpus=2)

    @ray.remote(num_cpus=1)
    def where():
        return ray.get_runtime_context().node_id

    strat = NodeAffinitySchedulingStrategy(nid.hex())
    out = ray.get(where.options(scheduling_strategy=strat).remote(),
                  timeout=60)
    assert out == nid.hex()


def test_node_death_fails_or_retries_tasks(ray_start_cluster):
    cluster = ray_start_cluster
    nid = cluster.add_node(num_cpus=2)

    @ray.remote(num_cpus=1, max_retries=2)
    def slow():
        time.sleep(2)
        return ray.get_runtime_context().node_id

    strat = NodeAffinitySchedulingStrategy(nid.hex(), soft=True)
    refs = [slow.options(scheduling_strategy=strat).remote()
            for _ in range(2)]
    time.sleep(0.5)
    cluster.remove_node(nid)
    # retried on the surviving node
    out = ray.get(refs, timeout=90)
    head = ray.nodes()[0]["node_id"]
    assert all(o == head for o in out)


def test_placement_group_pack_and_task(ray_start_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray.remote(num_cpus=1)
    def inside():
        return ray.get_runtime_context().node_id

    refs = [
        inside.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, i)).remote()
        for i in range(2)
    ]
    nodes = ray.get(refs, timeout=60)
    assert nodes[0] == nodes[1]  # PACK → same node
    remove_placement_group(pg)


def test_placement_group_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(30)
    table = placement_group_table(pg)
    assert table["state"] == "CREATED"
    nodes = table["bundle_nodes"]
    assert nodes[0] != nodes[1]
    remove_placement_group(pg)


def test_placement_group_blocks_until_resources(ray_start_cluster):
    # head has 2 CPUs; a 3-bundle pg cannot fit until a node is added
    pg = placement_group([{"CPU": 1}] * 3, strategy="PACK")
    assert not pg.wait(0.5)
    ray_start_cluster.add_node(num_cpus=4)
    assert pg.wait(30)
    remove_placement_group(pg)


def test_placement_group_actor(ray_start_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray.remote(num_cpus=1)
    class A:
        def where(self):
            return ray.get_runtime_context().node_id

    a = A.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
        pg, 0)).remote()
    node = ray.get(a.where.remote(), timeout=60)
    assert node == placement_group_table(pg)["bundle_nodes"][0]
    remove_placement_group(pg)


def test_custom_resources(ray_start_cluster):
    cluster = ray_start_cluster
    nid = cluster.add_node(num_cpus=1, resources={"special": 2})

    @ray.remote(num_cpus=0, resources={"special": 1})
    def needs_special():
        return ray.get_runtime_context().node_id

    assert ray.get(needs_special.remote(), timeout=60) == nid.hex()


def test_tpu_resource_env(ray_start_cluster):
    """TPU chips flow to workers as TPU_VISIBLE_CHIPS — the TPU analog of
    CUDA_VISIBLE_DEVICES plumbing (reference: backend_executor.py:205)."""
    import os as _os

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, num_tpus=4)

    @ray.remote(num_cpus=0, num_tpus=2)
    def chips():
        import os

        return (os.environ.get("TPU_VISIBLE_CHIPS"),
                ray.get_runtime_context().tpu_chips)

    env_val, ctx_chips = ray.get(chips.remote(), timeout=60)
    assert env_val is not None and len(env_val.split(",")) == 2
    assert len(ctx_chips) == 2


def test_pg_bundle_capacity_enforced(ray_start_cluster):
    """A 1-CPU bundle must not run two 1-CPU tasks concurrently
    (regression: PG tasks used to bypass admission)."""
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)
    strat = PlacementGroupSchedulingStrategy(pg, 0)

    @ray.remote(num_cpus=1)
    def stamp():
        t0 = time.monotonic()
        time.sleep(0.4)
        return (t0, time.monotonic())

    a, b = [stamp.options(scheduling_strategy=strat).remote()
            for _ in range(2)]
    (s1, e1), (s2, e2) = ray.get([a, b], timeout=60)
    # serialized execution: one interval must start after the other ends
    assert s2 >= e1 - 0.05 or s1 >= e2 - 0.05
    remove_placement_group(pg)
