"""Cleanliness gates: the shipped tree must carry zero un-suppressed
framework-lint findings AND zero un-suppressed protocheck findings, so a
regression fails plain `pytest tests/` without a separate CI job (the
`python -m ray_tpu.devtools.lint` / `...protocheck` CLIs are the same
engines; `python -m ray_tpu.devtools.check` runs all three analyzers —
lockgraph's gate lives in test_lockgraph_clean.py)."""

import os
import time

import ray_tpu
from ray_tpu.devtools import check, lint, protocheck

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _format(findings):
    return "\n".join(repr(f) for f in findings)


def test_ray_tpu_tree_is_lint_clean():
    findings = lint.lint_paths([PKG_DIR])
    assert findings == [], (
        "ray_tpu/ has un-suppressed lint findings (fix them, or add "
        "'# noqa: <RULE-ID> -- reason' where the pattern is deliberate):\n"
        + _format(findings))


def test_test_tree_is_lint_clean():
    # lint_paths' directory walk already skips lint_fixtures/ (the
    # linter's own deliberately-bad corpus), so the whole tests/ tree —
    # the documented `lint ray_tpu/ tests/` invocation — must be clean.
    findings = lint.lint_paths([TESTS_DIR])
    assert findings == [], _format(findings)


def test_tree_is_protocheck_clean_within_budget():
    """The whole-program conformance gate: `python -m
    ray_tpu.devtools.protocheck ray_tpu/ tests/` must exit 0 on the
    shipped tree (every suppression carrying a reason — a reasonless one
    is itself a finding, RTL500), and the analysis must stay inside its
    10 s budget so the gate is cheap enough to keep in tier-1."""
    start = time.monotonic()
    findings = protocheck.check_paths([PKG_DIR, TESTS_DIR])
    elapsed = time.monotonic() - start
    assert findings == [], (
        "protocheck found un-suppressed whole-program findings (fix "
        "them, or suppress with '# noqa: <RULE-ID> -- reason'):\n"
        + _format(findings))
    assert elapsed < 10.0, (
        f"protocheck took {elapsed:.1f}s over ray_tpu/ + tests/ — the "
        f"tier-1 gate budget is 10s")


def test_merged_check_entry_point_is_clean():
    """The one-stop `python -m ray_tpu.devtools.check` gate: all three
    analyzers over its default path set (ray_tpu/ + tests/) merge to a
    clean exit — the exact command CI and pre-push hooks run."""
    findings = check.check_paths([PKG_DIR, TESTS_DIR])
    assert findings == [], "\n".join(
        f"[{name}] {f!r}" for name, f in findings)
    assert check.main([PKG_DIR, TESTS_DIR]) == 0
