"""Lint-cleanliness gate: the shipped tree must carry zero un-suppressed
framework-lint findings, so a regression fails plain `pytest tests/`
without a separate CI job (the `python -m ray_tpu.devtools.lint ray_tpu/`
CLI is the same engine)."""

import os

import ray_tpu
from ray_tpu.devtools import lint

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _format(findings):
    return "\n".join(repr(f) for f in findings)


def test_ray_tpu_tree_is_lint_clean():
    findings = lint.lint_paths([PKG_DIR])
    assert findings == [], (
        "ray_tpu/ has un-suppressed lint findings (fix them, or add "
        "'# noqa: <RULE-ID> -- reason' where the pattern is deliberate):\n"
        + _format(findings))


def test_test_tree_is_lint_clean():
    # lint_paths' directory walk already skips lint_fixtures/ (the
    # linter's own deliberately-bad corpus), so the whole tests/ tree —
    # the documented `lint ray_tpu/ tests/` invocation — must be clean.
    findings = lint.lint_paths([TESTS_DIR])
    assert findings == [], _format(findings)
