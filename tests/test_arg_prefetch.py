"""Pipelined argument prefetch + singleflight pull dedup (worker side).

Reference analog: raylets pull a task's dependencies *before* the worker
starts so transfer overlaps compute (dependency_manager.h), and the pull
manager issues ONE pull per object no matter how many queued tasks need
it (pull_manager.h).

These tests drive the REAL worker-runtime code (``_WorkerRuntime``,
``_load_args``, ``_ArgPrefetcher``, ``PullRegistry``) against a paced
loopback object server — the same 8-12 ms/chunk pacing technique as
``tests/test_object_transfer.py``, which makes the wall-clock assertions
latency-bound instead of loopback-bandwidth-bound:

- N concurrent materializations of one remote segment perform exactly
  one pull (``deduped_pulls == N-1``);
- pipelined tasks with remote args complete >= 1.5x faster wall-clock
  than the serial-materialize baseline (prefetch overlaps transfer with
  compute);
- a failed leader pull wakes every waiter into the fallback path and
  leaves no stuck registry entries;
- retained prefetched segments evicted unconsumed count as waste;
- an end-to-end cluster run records ``prefetch_hit_bytes`` at the head.
"""

import os
import random
import tempfile
import threading
import time

import numpy as np
import pytest

from multiprocessing.connection import Listener

from ray_tpu._private import object_transfer as ot
from ray_tpu._private import serialization, worker_main
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.shm_store import ShmStore

AUTH = b"arg-prefetch-test"
PEER = "peer-store"


# --------------------------------------------------------------- helpers --

class _NullConn:
    """Head connection stand-in: the harness prepopulates the store
    address cache, so nothing should ever be sent."""

    def send_bytes(self, data):
        pass

    def fileno(self):
        raise OSError("no fd")

    def close(self):
        pass


class _PacedConn:
    def __init__(self, conn, delay):
        self._conn = conn
        self._delay = delay

    def send_bytes(self, data):
        if len(data) >= ot.CHUNK:
            time.sleep(self._delay)
        self._conn.send_bytes(data)

    def __getattr__(self, item):
        return getattr(self._conn, item)


class _CountingStore:
    """Store proxy counting attach() calls == fetch verbs served."""

    def __init__(self, store):
        self._store = store
        self.attaches = []

    def attach(self, name):
        self.attaches.append(name)
        return self._store.attach(name)


class _Server:
    def __init__(self, store, wrap=None):
        self.store = store
        self._wrap = wrap or (lambda conn: conn)
        self._listener = Listener(("127.0.0.1", 0), "AF_INET",
                                  backlog=16, authkey=AUTH)
        self.addr = f"tcp://127.0.0.1:{self._listener.address[1]}"
        self._stopped = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stopped:
            try:
                conn = self._listener.accept()
            except Exception:
                return
            threading.Thread(target=ot.serve_connection,
                             args=(self._wrap(conn), self.store),
                             daemon=True).start()

    def close(self):
        self._stopped = True
        try:
            self._listener.close()
        except Exception:
            pass


def _make_segment(store: ShmStore, payload: bytes) -> tuple:
    """A real shm segment holding one buffer; returns its SHM descriptor
    as a remote consumer would see it."""
    res = serialization.dumps_adaptive(
        np.frombuffer(payload, dtype=np.uint8), 0)
    name, size = store.create_from_parts(ObjectID.from_random(), res[1],
                                         res[2])
    return ("shm", name, size, PEER)


@pytest.fixture
def peer_store():
    d = tempfile.mkdtemp(prefix="rtpu-pf-", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    store = ShmStore(shm_dir=d, session_id="pfpeer")
    yield store
    import shutil

    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture
def make_rt(peer_store, monkeypatch):
    """Real _WorkerRuntime instances wired to the loopback server, no
    cluster: the head conn is inert and store addresses are pre-cached,
    so every pull runs the genuine singleflight/prefetch machinery."""
    monkeypatch.setenv("RAY_TPU_AUTHKEY", AUTH.hex())
    monkeypatch.setenv("RAY_TPU_STORE_ID", "local-store")
    made = []

    def make(addr, depth=2, caps=()):
        monkeypatch.setenv("RAY_TPU_ARG_PREFETCH_DEPTH", str(depth))
        local = ShmStore(shm_dir=peer_store._dir,
                         session_id=f"pflocal{len(made)}")
        rt = worker_main._WorkerRuntime(_NullConn(), threading.Lock(),
                                        local, 1 << 20)
        rt._store_addrs[PEER] = (addr, tuple(caps))
        made.append(rt)
        return rt

    yield make
    for rt in made:
        rt._puller.close()


def _task(descrs) -> dict:
    return {"task_id": os.urandom(16), "args": list(descrs), "kwargs": {},
            "num_returns": 1, "name": "t"}


# ------------------------------------------------------ singleflight -----

def test_concurrent_consumers_share_one_pull(peer_store, make_rt):
    """N concurrent materializations of the same remote segment perform
    exactly ONE pull; the others attach to the leader's result."""
    counting = _CountingStore(peer_store)
    server = _Server(counting, wrap=lambda c: _PacedConn(c, 0.05))
    payload = random.Random(3).randbytes(2 << 20)
    descr = _make_segment(peer_store, payload)
    rt = make_rt(server.addr)
    n = 4
    barrier = threading.Barrier(n)
    out, errs = {}, []

    def consume(i):
        try:
            barrier.wait(timeout=10)
            out[i] = rt.materialize(descr).tobytes()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=consume, args=(i,))
               for i in range(n)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        assert all(out[i] == payload for i in range(n))
        assert len(counting.attaches) == 1, counting.attaches
        assert rt._pull_registry.deduped_pulls == n - 1
    finally:
        server.close()


def test_failed_leader_wakes_waiters_to_fallback(make_rt):
    """A dead peer fails the leader's pull; every waiter gets None (the
    caller's existing fallback path) and the registry holds no stuck
    entries."""
    rt = make_rt("tcp://127.0.0.1:1")  # nothing listens here
    # The failed pull deliberately FORGETS the cached store address
    # (restarted peers re-resolve), so a thread arriving after the
    # leader's entry is popped becomes a new leader and re-asks the
    # HEAD for the address.  This fixture's head conn is inert — answer
    # the store_addr lookup with "no server" (None) instead of letting
    # the late leader block forever on a reply that never comes (the
    # real head always replies; a scheduling-dependent hang here made
    # the test flaky in-suite).
    rt._request = lambda build: None
    descr = ("shm", "rtpu-pfpeer-missing", 1 << 20, PEER)
    results = []
    barrier = threading.Barrier(4)

    def pull():
        barrier.wait(timeout=10)
        results.append(rt._pull_remote_segment(descr))

    threads = [threading.Thread(target=pull) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == [None] * 4
    assert rt._pull_registry._inflight == {}


def test_prefetch_waste_counted_on_eviction():
    """Retained prefetched segments evicted unconsumed count their bytes
    as waste (the task never ran here — e.g. stolen back)."""

    class _Seg:
        def __init__(self, size):
            self.size = size
            self.closed = False

        def close(self):
            self.closed = True

    reg = ot.PullRegistry()
    segs = []
    for i in range(reg.RETAIN_CAP + 3):
        ent, leader = reg.begin(("s", f"seg{i}"), prefetch=True)
        assert leader
        seg = _Seg(100)
        segs.append(seg)
        reg.finish(("s", f"seg{i}"), ent, seg, retain=True)
    assert reg.prefetch_waste_bytes == 300
    assert all(s.closed for s in segs[:3])
    # A consumed entry credits hits, not waste.
    ent, leader = reg.begin(("s", "seg5"))
    assert not leader and ent.event.is_set()
    assert reg.take(("s", "seg5"), ent) is segs[5]
    assert reg.prefetch_hit_bytes == 100


# ------------------------------------------------ the acceptance micro ---

def test_pipelined_prefetch_1_5x_over_serial(peer_store, make_rt):
    """4 pipelined tasks, each with one remote 6 MB arg, over a paced
    link: prefetching queued tasks' args while the current task computes
    must be >= 1.5x faster wall-clock than serial materialization."""
    server = _Server(peer_store, wrap=lambda c: _PacedConn(c, 0.012))
    rng = random.Random(7)
    compute_s = 0.07

    def run(prefetch: bool) -> float:
        descrs = [_make_segment(peer_store, rng.randbytes(6 << 20))
                  for _ in range(4)]
        tasks = [_task([d]) for d in descrs]
        rt = make_rt(server.addr, depth=2)
        t0 = time.perf_counter()
        if prefetch:
            # What the worker's enqueue hook does when tasks land behind
            # a running one.
            for t in tasks[1:]:
                rt.prefetcher.offer(t)
        for t in tasks:
            args, _ = worker_main._load_args(rt, t)
            assert args[0].nbytes == 6 << 20
            time.sleep(compute_s)  # the "compute" the transfer hides
        dt = time.perf_counter() - t0
        if prefetch:
            assert rt._pull_registry.prefetch_hit_bytes > 0
        return dt

    try:
        best = 0.0
        for _attempt in range(3):  # damp shared-CI scheduling noise
            t_serial = run(prefetch=False)
            t_pipelined = run(prefetch=True)
            best = max(best, t_serial / t_pipelined)
            if best >= 1.5:
                break
        assert best >= 1.5, (
            f"prefetch pipeline only {best:.2f}x over serial baseline")
    finally:
        server.close()


def test_multi_arg_load_pulls_concurrently(peer_store, make_rt):
    """A single task with several remote args materializes them through
    concurrent pulls instead of one blocking stream at a time."""
    server = _Server(peer_store, wrap=lambda c: _PacedConn(c, 0.012))
    rng = random.Random(11)
    try:
        ok = False
        for _attempt in range(3):  # damp shared-CI scheduling noise
            payloads = [rng.randbytes(4 << 20) for _ in range(3)]
            descrs = [_make_segment(peer_store, p) for p in payloads]

            serial_rt = make_rt(server.addr, depth=0)  # pre-PR behavior
            t0 = time.perf_counter()
            args, _ = worker_main._load_args(serial_rt, _task(descrs))
            t_serial = time.perf_counter() - t0
            assert [a.tobytes() for a in args] == payloads

            par_rt = make_rt(server.addr, depth=3)
            t0 = time.perf_counter()
            args, _ = worker_main._load_args(par_rt, _task(descrs))
            t_par = time.perf_counter() - t0
            assert [a.tobytes() for a in args] == payloads
            if t_par < t_serial:
                ok = True
                break
        assert ok, (t_par, t_serial)
    finally:
        server.close()


# --------------------------------------------- lockcheck on concurrency --

def test_prefetch_singleflight_lockcheck_clean(peer_store, monkeypatch):
    """The new concurrency (prefetcher threads + singleflight waiters)
    under the RAY_TPU_LOCKCHECK instrumentation: zero lock-order
    cycles."""
    from ray_tpu.devtools import lockcheck

    lockcheck.install(raise_on_cycle=False)
    lockcheck.clear()
    try:
        monkeypatch.setenv("RAY_TPU_AUTHKEY", AUTH.hex())
        monkeypatch.setenv("RAY_TPU_STORE_ID", "local-store")
        monkeypatch.setenv("RAY_TPU_ARG_PREFETCH_DEPTH", "2")
        server = _Server(peer_store, wrap=lambda c: _PacedConn(c, 0.02))
        rng = random.Random(13)
        descrs = [_make_segment(peer_store, rng.randbytes(2 << 20))
                  for _ in range(3)]
        local = ShmStore(shm_dir=peer_store._dir, session_id="pflock")
        rt = worker_main._WorkerRuntime(_NullConn(), threading.Lock(),
                                        local, 1 << 20)
        rt._store_addrs[PEER] = (server.addr, ())
        tasks = [_task([d]) for d in descrs]
        for t in tasks[1:]:
            rt.prefetcher.offer(t)
        threads = [
            threading.Thread(
                target=lambda t=t: worker_main._load_args(rt, t))
            for t in tasks
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        rt._puller.close()
        server.close()
        assert lockcheck.violations() == [], lockcheck.violations()
        lockcheck.assert_acyclic()
    finally:
        lockcheck.uninstall()


# ----------------------------------------------- end-to-end (cluster) ----

def test_cluster_prefetch_hits_reach_head_counters():
    """Full wiring: pipelined tasks on a 1-CPU head consume node-homed
    args; the worker's prefetcher fetches them ahead of execution and
    the deltas aggregate into the head's transfer_stats."""
    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    c = Cluster(head_num_cpus=1)
    try:
        n1 = c.add_node(num_cpus=2, external=True)

        @ray.remote
        def make(n):
            return np.ones(n, np.uint8)

        @ray.remote
        def crunch(a):
            time.sleep(0.15)
            return int(a[0])

        refs = [make.options(scheduling_strategy=NA(n1)).remote(2 << 20)
                for _ in range(4)]
        ray.wait(refs, num_returns=len(refs), timeout=60)
        head_id = c.rt.head_node.node_id.hex()
        out = ray.get([crunch.options(
            scheduling_strategy=NA(head_id)).remote(r) for r in refs],
            timeout=120)
        assert out == [1] * 4
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if c.rt.transfer_stats()["prefetch_hit_bytes"] > 0:
                break
            time.sleep(0.2)
        assert c.rt.transfer_stats()["prefetch_hit_bytes"] > 0
    finally:
        c.shutdown()
