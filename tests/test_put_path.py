"""Put-path parity: striped zero-copy writes, direct-to-store ingest,
and the head's control plane out of the put payload path.

Reference analog: the plasma store takes writes through
``CreateObject``/``Seal`` on a dedicated store socket
(``src/ray/object_manager/plasma/store.h``) — never through a GCS RPC.
Here a client/worker put of a value destined for another store reserves
the destination mapping (``reserve_put``), streams concurrent byte-range
stripes straight into it (``put_range``; socket -> mmap, one copy),
seals it (``commit_put``) and sends the head only an O(1)
``("put_commit", ...)`` control message.

Covered here:
- striped push reassembly is byte-identical across randomized sizes
  around the stripe threshold (the destination segment deserializes to
  the original value);
- old-verb peer interop: a pusher never engages (no wire traffic at
  all) against a peer that does not advertise the put verbs — the
  caller keeps the legacy ``put_parts`` path;
- failure hygiene: a pusher dying between ``reserve_put`` and
  ``commit_put`` triggers the abort cleanup (no leaked reservation,
  store accounting restored); a mid-push connection death evicts ONLY
  the broken pooled connection and a retry on the same pool succeeds;
- spill-aware admission: an over-capacity reservation degrades to the
  spill path instead of overcommitting tmpfs;
- the acceptance micro: 4 concurrent large puts over a paced
  (latency-bound) link complete ≥2x faster striped/pooled than the
  legacy whole-value-through-one-control-message baseline;
- cluster: one large client put produces O(1) control-plane messages at
  the head (exactly one ``put_commit``, zero ``put_parts``) with
  ``direct_puts``/``direct_put_bytes`` counted; ``direct_puts=off``
  reproduces the legacy path with every new counter zero, and the knobs
  follow ``_system_config`` into spawned workers;
- the concurrent multi-client put battery re-run under the lockcheck
  instrumentation with zero lock-order cycles.
"""

import os
import random
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from multiprocessing.connection import Client, Listener

from ray_tpu._private import object_transfer as ot
from ray_tpu._private import protocol, serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.shm_store import ShmStore

AUTH = b"put-path-test"


# --------------------------------------------------------------- helpers --

class _Server:
    """A loopback object server over a real store, with optional
    per-connection wrapping (pacing, chaos)."""

    def __init__(self, store, wrap=None, serve=ot.serve_connection):
        self.store = store
        self._wrap = wrap or (lambda conn: conn)
        self._serve = serve
        self._listener = Listener(("127.0.0.1", 0), "AF_INET",
                                  backlog=16, authkey=AUTH)
        self.addr = f"tcp://127.0.0.1:{self._listener.address[1]}"
        self.port = self._listener.address[1]
        self._stopped = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stopped:
            try:
                conn = self._listener.accept()
            except Exception:
                return
            threading.Thread(target=self._serve,
                             args=(self._wrap(conn), self.store),
                             daemon=True).start()

    def close(self):
        self._stopped = True
        try:
            self._listener.close()
        except Exception:
            pass


@pytest.fixture
def shm_store():
    d = tempfile.mkdtemp(prefix="rtpu-put-", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    store = ShmStore(shm_dir=d, session_id="puttest")
    yield store
    import shutil

    store.cleanup()
    shutil.rmtree(d, ignore_errors=True)


def _parts_of(payload: bytes):
    res = serialization.dumps_adaptive(
        np.frombuffer(payload, dtype=np.uint8), 0)
    assert res[0] == "parts"
    return res[1], res[2]


def _push_value(pusher, server, payload: bytes, caps=ot.CAPS):
    meta, views = _parts_of(payload)
    oid = ObjectID.for_put()
    return pusher.push("peer", server.addr, oid.binary(), meta, views,
                       caps=caps)


def _read_back(store: ShmStore, kind: str, ident: str) -> bytes:
    seg = (store.attach_path(ident) if kind == "spilled"
           else store.attach(ident))
    try:
        return bytes(seg.deserialize().tobytes())
    finally:
        seg.close()


# ------------------------------------------------- striped reassembly ----

def test_striped_put_reassembles_byte_identical(shm_store):
    """Randomized sizes around the stripe threshold: the pushed segment
    must deserialize to the original value whether it streamed whole or
    as concurrent byte-range stripes."""
    thr = 256 * 1024
    rng = random.Random(7)
    sizes = [1, thr // 2, thr - 64, thr - 1, thr, thr + 1, thr + 177,
             2 * thr, 3 * thr + rng.randrange(thr)]
    server = _Server(shm_store)
    striped = ot.ObjectPusher(AUTH, pool_size=4, stripe_threshold=thr)
    whole = ot.ObjectPusher(AUTH, pool_size=4, stripe_threshold=0)
    try:
        for n in sizes:
            payload = rng.randbytes(n)
            for pusher in (striped, whole):
                kind, ident, total = _push_value(pusher, server, payload)
                assert kind == "shm"
                assert _read_back(shm_store, kind, ident) == payload, n
                shm_store.unlink(ident, total)
    finally:
        striped.close()
        whole.close()
        server.close()


def test_meta_only_value_pushes(shm_store):
    """A big pickle with no out-of-band buffers (pure meta) still pushes
    and round-trips."""
    value = {"k": "v" * (2 << 20)}
    res = serialization.dumps_adaptive(value, 1024)
    assert res[0] == "parts" and res[2] == []
    server = _Server(shm_store)
    pusher = ot.ObjectPusher(AUTH, pool_size=2,
                             stripe_threshold=512 * 1024)
    try:
        kind, ident, _total = pusher.push(
            "peer", server.addr, ObjectID.for_put().binary(), res[1],
            res[2], caps=ot.CAPS)
        seg = shm_store.attach(ident)
        try:
            assert seg.deserialize() == value
        finally:
            seg.close()
    finally:
        pusher.close()
        server.close()


# ------------------------------------------------- old-verb peer interop --

def _old_serve_connection(conn, store):
    """The pre-put object server, verbatim: speaks ONLY fetch/close and
    records anything else (which is why the put verbs must be gated on
    advertised caps, never probed)."""
    unknown = getattr(store, "_unknown_verbs", None)
    try:
        while True:
            msg = protocol.recv(conn)
            if msg[0] == "fetch":
                try:
                    seg = store.attach(msg[1])
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                try:
                    mv = memoryview(seg._mm)
                    protocol.send(conn, ("ok", len(mv)))
                    for off in range(0, len(mv), ot.CHUNK):
                        conn.send_bytes(mv[off:off + ot.CHUNK])
                finally:
                    del mv
                    seg.close()
            elif msg[0] == "close":
                return
            elif unknown is not None:
                unknown.append(msg[0])
    except (EOFError, OSError, TypeError):
        return
    finally:
        try:
            conn.close()
        except Exception:
            pass


def test_old_verb_peer_never_sees_put_verbs(shm_store):
    """Against a peer whose advertised caps lack the put verbs, the
    pusher refuses WITHOUT any wire traffic (the caller then keeps the
    legacy ``put_parts`` control-plane path) — and partial caps do not
    slip through the gate either."""
    shm_store._unknown_verbs = []
    server = _Server(shm_store, serve=_old_serve_connection)
    pusher = ot.ObjectPusher(AUTH, pool_size=2, stripe_threshold=0)
    payload = random.Random(3).randbytes(64 * 1024)
    try:
        for caps in ((), ("fetch_range",), ("reserve_put",),
                     ("reserve_put", "put_range", "commit_put")):
            with pytest.raises(ot.PutUnsupportedError):
                _push_value(pusher, server, payload, caps=caps)
        assert not pusher._pools, "refused push still dialed the peer"
        assert shm_store._unknown_verbs == []
        assert ot.peer_accepts_puts(ot.CAPS)
    finally:
        pusher.close()
        server.close()


# ------------------------------------------ failure hygiene / admission --

def test_reservation_aborted_when_pusher_dies(shm_store):
    """A reservation whose connection closes before commit_put is torn
    down by the server: no leaked segment file, accounting restored."""
    server = _Server(shm_store)
    used0 = shm_store._used
    conn = Client(("127.0.0.1", server.port), authkey=AUTH)
    try:
        protocol.send(conn, ("reserve_put", ObjectID.for_put().binary(),
                             1 << 20))
        reply = protocol.recv(conn)
        assert reply[0] == "ok"
        name = reply[1]
        path = os.path.join(shm_store._dir, name)
        assert os.path.exists(path)
        assert shm_store._used == used0 + (1 << 20)
    finally:
        conn.close()  # pusher "dies" between reserve and commit
    deadline = time.monotonic() + 10
    while os.path.exists(path) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not os.path.exists(path), "reservation segment leaked"
    assert shm_store._used == used0, "store accounting not restored"
    server.close()


def test_explicit_abort_put_cleans_up(shm_store):
    server = _Server(shm_store)
    used0 = shm_store._used
    conn = Client(("127.0.0.1", server.port), authkey=AUTH)
    try:
        protocol.send(conn, ("reserve_put", ObjectID.for_put().binary(),
                             1 << 20))
        reply = protocol.recv(conn)
        assert reply[0] == "ok"
        name = reply[1]
        protocol.send(conn, ("abort_put", name))
        assert protocol.recv(conn) == ("ok",)
        assert not os.path.exists(os.path.join(shm_store._dir, name))
        assert shm_store._used == used0
        # Stripes/commits for the aborted put are refused in sync (the
        # payload is drained, the connection stays usable).
        protocol.send(conn, ("put_range", name, 0, ot.CHUNK))
        conn.send_bytes(b"\0" * ot.CHUNK)
        assert protocol.recv(conn)[0] == "err"
        protocol.send(conn, ("commit_put", name))
        assert protocol.recv(conn)[0] == "err"
        # ...and a fresh reserve on the SAME connection still works.
        protocol.send(conn, ("reserve_put", ObjectID.for_put().binary(),
                             4096))
        assert protocol.recv(conn)[0] == "ok"
    finally:
        conn.close()
    server.close()


class _DieOnNthRecv:
    """Kills the server side of a connection on the Nth payload recv —
    the pusher observes a mid-stripe transport failure."""

    def __init__(self, conn, owner):
        self._conn = conn
        self._owner = owner

    def recv_bytes_into(self, *a, **kw):
        if self._owner["fuse"] > 0:
            self._owner["fuse"] -= 1
            if self._owner["fuse"] == 0:
                self._conn.close()
                raise OSError("injected mid-put death")
        return self._conn.recv_bytes_into(*a, **kw)  # noqa: RTL403 -- fault-injection wrapper delegating to the real conn

    def __getattr__(self, item):
        return getattr(self._conn, item)


def test_mid_push_death_evicts_only_broken_conn_and_recovers(shm_store):
    """A connection dying mid-push fails that push, evicts ONLY the
    broken pooled connection, aborts the reservation (server cleanup),
    and a retry on the same pool redials and succeeds."""
    owner = {"fuse": 2}
    server = _Server(shm_store, wrap=lambda c: _DieOnNthRecv(c, owner))
    pusher = ot.ObjectPusher(AUTH, pool_size=2, stripe_threshold=0)
    payload = random.Random(5).randbytes(3 << 20)
    used0 = shm_store._used
    try:
        with pytest.raises((OSError, EOFError)):
            _push_value(pusher, server, payload)
        pool = pusher._pools["peer"]
        assert pool.total == 0, "broken connection not evicted"
        # Reservation cleanup restores accounting (async on conn close).
        deadline = time.monotonic() + 10
        while shm_store._used != used0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert shm_store._used == used0
        kind, ident, total = _push_value(pusher, server, payload)
        assert _read_back(shm_store, kind, ident) == payload
    finally:
        pusher.close()
        server.close()


def test_over_capacity_reservation_degrades_to_spill(tmp_path):
    """Admission gates on node capacity: a reservation that cannot fit
    degrades to a spill-file destination (readable via attach_path, like
    any spilled segment) instead of overcommitting tmpfs — and with no
    spill_dir configured it refuses outright."""
    d = tempfile.mkdtemp(prefix="rtpu-putcap-", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    store = ShmStore(shm_dir=d, session_id="putcap", capacity=256 * 1024)
    store.spill_dir = str(tmp_path / "spill")
    server = _Server(store)
    pusher = ot.ObjectPusher(AUTH, pool_size=2, stripe_threshold=0)
    payload = random.Random(9).randbytes(1 << 20)
    try:
        meta, views = _parts_of(payload)
        kind, ident, total = pusher.push(
            "peer", server.addr, ObjectID.for_put().binary(), meta,
            views, caps=ot.CAPS)
        assert kind == "spilled"
        assert ident.startswith(str(tmp_path / "spill"))
        assert _read_back(store, kind, ident) == payload
        assert store._used == 0  # spill bytes are not tmpfs-accounted
        store.spill_dir = ""
        with pytest.raises(OSError):
            _push_value(pusher, server, payload)
    finally:
        pusher.close()
        server.close()
        store.cleanup()
        import shutil

        shutil.rmtree(d, ignore_errors=True)


# -------------------------------------------------- the acceptance micro --

class _PacedIngestConn:
    """Fixed pacing per received payload chunk: emulates a latency/
    bandwidth-bound link on the ingest direction, the regime where
    multiple stripes in flight beat one serial stream — independent of
    this machine's loopback memory bandwidth."""

    def __init__(self, conn, delay):
        self._conn = conn
        self._delay = delay

    def recv_bytes_into(self, *a, **kw):
        n = self._conn.recv_bytes_into(*a, **kw)  # noqa: RTL403 -- slow-link wrapper delegating to the real conn
        if n >= ot.CHUNK // 2:
            time.sleep(self._delay)
        return n

    def __getattr__(self, item):
        return getattr(self._conn, item)


def _legacy_put_server(store, delay):
    """The pre-PR shape: the whole value arrives as ONE pickled
    control-plane message per put and the receiver assembles it into the
    store — paced per CHUNK-equivalent of the message size over the same
    link."""
    listener = Listener(("127.0.0.1", 0), "AF_INET", backlog=16,
                        authkey=AUTH)
    stopped = [False]

    def serve(conn):
        try:
            while True:
                raw = conn.recv_bytes()  # noqa: RTL403 -- minimal legacy-server stub for one test
                time.sleep(delay * max(1, len(raw) // ot.CHUNK))
                msg = serialization.loads_inline(raw)
                assert msg[0] == "put_parts"
                _tag, oid_bin, meta, bufs = msg
                store.create_from_parts(
                    ObjectID(oid_bin), meta,
                    [memoryview(b) for b in bufs])
                conn.send_bytes(b"ok")
        except (EOFError, OSError):
            return

    def accept():
        while not stopped[0]:
            try:
                conn = listener.accept()
            except Exception:
                return
            threading.Thread(target=serve, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=accept, daemon=True).start()
    return listener, stopped


@pytest.mark.slow  # ~8s perf A/B — the put-side twin of the pull-side
# 4x64MB A/B already in the slow lane (PR 9); striped-put CORRECTNESS
# (byte-identical reassembly, O(1) control messages, counters) keeps
# sub-second tier-1 reps in this file.  Buys back the new protocheck
# gate + seeded-mutation battery's tier-1 time.
def test_four_concurrent_puts_2x_over_legacy_baseline(shm_store):
    """Acceptance micro: 4 concurrent 48 MB puts over a paced link —
    the striped/pooled direct-put path must complete ≥2x faster than the
    legacy baseline (whole value as one control message per put, one
    connection each), best-of-3."""
    import pickle

    delay = 0.012
    values = [np.arange(6_000_000, dtype=np.int64) for _ in range(4)]
    parts = [serialization.dumps_adaptive(v, 0) for v in values]

    def timed(fn):
        errs = []

        def run(i):
            try:
                fn(i)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        return time.perf_counter() - t0

    # Legacy baseline: its own connection per client, whole value in one
    # pickled message (the payload copies through the pickle stream).
    listener, stopped = _legacy_put_server(shm_store, delay)
    legacy_conns = [Client(("127.0.0.1", listener.address[1]),
                           authkey=AUTH) for _ in range(4)]

    def legacy_put(i):
        res = parts[i]
        msg = ("put_parts", ObjectID.for_put().binary(), res[1],
               [pickle.PickleBuffer(b) for b in res[2]])
        legacy_conns[i].send_bytes(
            pickle.dumps(msg, protocol=5))
        assert legacy_conns[i].recv_bytes() == b"ok"

    # Direct path: one pusher per client, stripes over pooled conns.
    server = _Server(shm_store,
                     wrap=lambda c: _PacedIngestConn(c, delay))
    pushers = [ot.ObjectPusher(AUTH, pool_size=4,
                               stripe_threshold=12 * 1024 * 1024)
               for _ in range(4)]

    def direct_put(i):
        res = parts[i]
        kind, ident, total = pushers[i].push(
            "peer", server.addr, ObjectID.for_put().binary(), res[1],
            res[2], caps=ot.CAPS)
        assert kind == "shm"

    try:
        best = 0.0
        for _attempt in range(3):  # damp shared-CI scheduling noise
            t_legacy = timed(legacy_put)
            t_direct = timed(direct_put)
            best = max(best, t_legacy / t_direct)
            if best >= 2.0:
                break
        assert best >= 2.0, (
            f"direct striped puts only {best:.2f}x over the legacy "
            f"put_parts baseline")
    finally:
        for c in legacy_conns:
            c.close()
        stopped[0] = True
        listener.close()
        for p in pushers:
            p.close()
        server.close()


def test_put_parts_fallback_clears_stale_direct_push_remnant():
    """A failed direct push can strand the oid's canonical segment (the
    server committed but the ack was lost); the put_parts FALLBACK for
    the same oid must clear the remnant and assemble cleanly instead of
    colliding on O_EXCL or double-counting the bytes."""
    import ray_tpu as ray
    from ray_tpu._private import api_internal

    ray.init(num_cpus=1)
    try:
        rt = api_internal.get_runtime()
        oid = ObjectID.for_put()
        payload = random.Random(21).randbytes(2 << 20)
        meta, views = _parts_of(payload)
        # Simulate the remnant: a committed direct-push reservation for
        # this oid whose commit ack the client never saw.
        res = rt.shm.reserve_put(oid.binary(), 4 << 20)
        memoryview(res.mm)[:8] = b"garbage!"
        res.commit()
        used_with_remnant = rt.shm._used
        descr = rt._store_parts_locally(oid, bytes(meta),
                                        [bytes(v) for v in views])
        assert descr[0] == protocol.SHM
        seg = rt.shm.attach(descr[1])
        try:
            assert bytes(seg.deserialize().tobytes()) == payload
        finally:
            seg.close()
        # The remnant's 4 MB left the accounting; only the fresh
        # segment's bytes remain on top of the pre-remnant base.
        assert rt.shm._used <= used_with_remnant - (4 << 20) + descr[2]
    finally:
        ray.shutdown()


# --------------------------------------------- lockcheck on concurrency --

def test_concurrent_multi_client_puts_lockcheck_clean(shm_store):
    """The multi-client put battery under the RAY_TPU_LOCKCHECK
    instrumentation: concurrent striped pushes from several pushers into
    one destination must record zero lock-order cycles."""
    from ray_tpu.devtools import lockcheck

    lockcheck.install(raise_on_cycle=False)
    lockcheck.clear()
    try:
        server = _Server(shm_store)
        rng = random.Random(13)
        payloads = [rng.randbytes(700 * 1024) for _ in range(3)]
        pushers = [ot.ObjectPusher(AUTH, pool_size=3,
                                   stripe_threshold=128 * 1024)
                   for _ in range(3)]
        results = {}

        def push(i):
            kind, ident, _total = _push_value(pushers[i], server,
                                              payloads[i])
            results[i] = _read_back(shm_store, kind, ident)

        threads = [threading.Thread(target=push, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert [results[i] for i in range(3)] == payloads
        for p in pushers:
            p.close()
        server.close()
        assert lockcheck.violations() == [], lockcheck.violations()
        lockcheck.assert_acyclic()
    finally:
        lockcheck.uninstall()


# --------------------------------------------- cluster: O(1) control plane --

def _client_env(rt):
    env = dict(os.environ)
    env["RAY_TPU_CLIENT_ADDRESS"] = rt.tcp_address
    env["RAY_TPU_CLIENT_AUTHKEY"] = rt._authkey.hex()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep
        + env.get("PYTHONPATH", ""))
    return env


_CLIENT_PUT_SCRIPT = """
import numpy as np
import ray_tpu as ray
ray.init()
big = np.arange(3_000_000, dtype=np.int64)  # 24 MB

@ray.remote
def total(a):
    return int(a.sum())

ref = ray.put(big)
assert ray.get(total.remote(ref), timeout=90) == int(big.sum())
assert int(ray.get(ref, timeout=90).sum()) == int(big.sum())
ray.shutdown()
print("CLIENT_PUT_OK")
"""


def test_one_direct_put_is_o1_control_messages():
    """One large client put reaches the head as exactly ONE control-
    plane message (the put_commit) — zero put_parts — with the payload
    counted in direct_puts/direct_put_bytes and the legacy fallback
    counter flat.  A worker still consumes the pushed segment."""
    import ray_tpu as ray
    from ray_tpu._private import api_internal

    ray.init(num_cpus=2)
    try:
        rt = api_internal.get_runtime()
        p = subprocess.run([sys.executable, "-c", _CLIENT_PUT_SCRIPT],
                           env=_client_env(rt), capture_output=True,
                           text=True, timeout=180)
        assert p.returncode == 0, p.stderr[-3000:]
        assert "CLIENT_PUT_OK" in p.stdout
        stats = rt.transfer_stats()
        assert stats["direct_puts"] == 1, stats
        assert stats["direct_put_bytes"] >= 24_000_000, stats
        assert stats["brokered_put_parts"] == 0, stats
        with rt._handler_stats_lock:
            counts = {tag: s[0] for tag, s in rt._handler_stats.items()}
        assert counts.get("put_commit", 0) == 1, counts
        assert counts.get("put_parts", 0) == 0, counts
    finally:
        ray.shutdown()


def test_direct_puts_off_restores_legacy_with_zero_counters():
    """Master switch off: the client put rides the legacy put_parts
    path (the head never advertises the put verbs, so the client never
    sends one), completes, and EVERY new counter stays zero.  The knobs
    follow _system_config into spawned workers via the env namespace."""
    import ray_tpu as ray
    from ray_tpu._private import api_internal

    ray.init(num_cpus=2, _system_config={
        "direct_puts": False,
        "object_put_stripe_threshold": 12345,
        "object_put_pool_size": 7,
    })
    try:
        rt = api_internal.get_runtime()

        @ray.remote
        def probe():
            import os

            return (os.environ.get("RAY_TPU_DIRECT_PUTS"),
                    os.environ.get("RAY_TPU_OBJECT_PUT_STRIPE_THRESHOLD"),
                    os.environ.get("RAY_TPU_OBJECT_PUT_POOL_SIZE"))

        assert ray.get(probe.remote(), timeout=60) == \
            ("0", "12345", "7")
        p = subprocess.run([sys.executable, "-c", _CLIENT_PUT_SCRIPT],
                           env=_client_env(rt), capture_output=True,
                           text=True, timeout=180)
        assert p.returncode == 0, p.stderr[-3000:]
        assert "CLIENT_PUT_OK" in p.stdout
        stats = rt.transfer_stats()
        assert stats["direct_puts"] == 0, stats
        assert stats["direct_put_bytes"] == 0, stats
        assert stats["brokered_put_parts"] == 0, stats
        with rt._handler_stats_lock:
            counts = {tag: s[0] for tag, s in rt._handler_stats.items()}
        assert counts.get("put_parts", 0) >= 1, counts
        assert counts.get("put_commit", 0) == 0, counts
    finally:
        ray.shutdown()


def test_small_put_coalescing_one_write_per_burst():
    """Many tiny client puts ride out as few ("batch", ...) frames (one
    pickle+write per burst) instead of one frame per put — message
    ORDER (put before its addref, both before any decref) preserved."""
    from multiprocessing.connection import Pipe

    from ray_tpu._private import object_ref as object_ref_mod
    from ray_tpu._private.client import ClientRuntime

    here, there = Pipe()
    d = tempfile.mkdtemp(prefix="rtpu-coal-")
    rt = ClientRuntime(there, threading.Lock(), ShmStore(shm_dir=d),
                       1024 * 1024)
    old_accessor = object_ref_mod._runtime_accessor
    object_ref_mod._set_runtime_accessor(lambda: rt)
    try:
        refs = [rt.put_object(i) for i in range(20)]
        rt.flush_puts()
        frames = []
        while here.poll(0.1):
            frames.append(serialization.loads_inline(here.recv_bytes()))
        assert len(frames) <= 3, f"{len(frames)} writes for 20 tiny puts"
        msgs = []
        for f in frames:
            msgs.extend(f[1] if protocol.is_batch(f) else [f])
        puts = [m for m in msgs if m[0] == "put"]
        addrefs = [m for m in msgs if m[0] == "addref"]
        assert len(puts) == 20 and len(addrefs) == 20
        for i, ref in enumerate(refs):
            put_at = next(j for j, m in enumerate(msgs)
                          if m[0] == "put" and m[1] == ref.id().binary())
            add_at = next(j for j, m in enumerate(msgs)
                          if m[0] == "addref"
                          and m[1] == ref.id().binary())
            assert put_at < add_at, "addref overtook its put"
    finally:
        # Drop the refs while the accessor still routes to THIS client
        # runtime (their __del__ decrefs land in its buffer, never
        # sent), then restore.
        refs = None
        object_ref_mod._set_runtime_accessor(old_accessor)
        import shutil

        shutil.rmtree(d, ignore_errors=True)
        here.close()
        there.close()
