"""Direct task push + caller-side ownership (reference:
direct_task_transport.cc:568, reference_count.h:61).

Worker-submitted eligible tasks bypass the head entirely: the caller
leases executors, pushes specs over direct connections, owns the returns,
and resolves dependencies locally.  These tests drive that machinery
through worker-resident "client" actors (the shape of the reference's
multi-client microbenchmarks).
"""

import time

import pytest

import ray_tpu as ray


@pytest.fixture
def rt():
    from ray_tpu._private import api_internal

    ray.init(num_cpus=8)
    yield api_internal.get_runtime()
    ray.shutdown()


@ray.remote
def _noop():
    return None


@ray.remote
def _add(a, b):
    return a + b


@ray.remote
class _Client:
    def burst(self, n):
        import ray_tpu as ray

        return len(ray.get([_noop.remote() for _ in range(n)]))

    def chain(self):
        import ray_tpu as ray

        a = _add.remote(1, 2)
        b = _add.remote(a, 10)      # depends on a caller-owned pending ref
        c = _add.remote(b, 100)
        return ray.get(c)

    def put_roundtrip(self):
        import numpy as np

        import ray_tpu as ray

        x = np.arange(4096)
        r = ray.put(x)
        return int(ray.get(r).sum())

    def make_ref(self):
        import ray_tpu as ray

        return ray.put({"k": 7})    # owned ref escapes to the driver

    def pass_owned_to_task(self):
        import ray_tpu as ray

        r = ray.put(5)
        return ray.get(_add.remote(r, 1))

    def container_arg(self):
        import ray_tpu as ray

        r = ray.put(3)
        # Ref nested inside a list arg: the executor resolves it through
        # the head (export path).
        @ray.remote
        def unpack(lst):
            import ray_tpu as ray

            return ray.get(lst[0]) + 1

        return ray.get(unpack.remote([r]))

    def wait_some(self):
        import ray_tpu as ray

        refs = [_noop.remote() for _ in range(8)]
        ready, not_ready = ray.wait(refs, num_returns=3, timeout=30)
        done = len(ready)
        ready2, _ = ray.wait(refs, num_returns=8, timeout=30)
        return done, len(ready2)

    def error_prop(self):
        import ray_tpu as ray

        @ray.remote
        def boom():
            raise ValueError("direct boom")

        try:
            ray.get(boom.remote())
            return "no error"
        except ray.exceptions.TaskError as e:
            return "caught" if "direct boom" in str(e) else str(e)


def test_direct_burst(rt):
    c = _Client.remote()
    assert ray.get(c.burst.remote(40)) == 40
    # The burst ran OUTSIDE the head's task table: the head saw only the
    # actor call itself (plus lease traffic).
    assert len(rt.tasks) <= 2


def test_direct_dependency_chain(rt):
    c = _Client.remote()
    assert ray.get(c.chain.remote()) == 113


def test_owner_local_put(rt):
    c = _Client.remote()
    assert ray.get(c.put_roundtrip.remote()) == 4096 * 4095 // 2


def test_owned_ref_escapes_to_driver(rt):
    c = _Client.remote()
    inner = ray.get(c.make_ref.remote())
    assert ray.get(inner) == {"k": 7}


def test_owned_ref_as_task_arg(rt):
    c = _Client.remote()
    assert ray.get(c.pass_owned_to_task.remote()) == 6


def test_owned_ref_in_container_arg(rt):
    c = _Client.remote()
    assert ray.get(c.container_arg.remote()) == 4


def test_direct_wait(rt):
    c = _Client.remote()
    done, total = ray.get(c.wait_some.remote())
    assert done == 3 and total == 8


def test_direct_error_propagation(rt):
    c = _Client.remote()
    assert ray.get(c.error_prop.remote()) == "caught"


def test_multi_client_concurrency(rt):
    clients = [_Client.remote() for _ in range(3)]
    t0 = time.monotonic()
    counts = ray.get([c.burst.remote(30) for c in clients])
    assert counts == [30, 30, 30]
    assert time.monotonic() - t0 < 60


def test_lease_released_after_idle(rt):
    c = _Client.remote()
    assert ray.get(c.burst.remote(10)) == 10
    # After the linger window the leases go back to the idle pool: all
    # CPUs usable by the head scheduler again.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        leased = [w for n in rt.nodes.values()
                  for w in n.all_workers.values()
                  if w.client_lease is not None]
        if not leased:
            break
        time.sleep(0.1)
    assert not leased
    # Head scheduling still works at full width afterwards.
    assert ray.get([_noop.remote() for _ in range(16)]) == [None] * 16


def test_executor_death_resubmit(rt):
    @ray.remote
    class Killer:
        def run(self):
            import os

            import ray_tpu as ray

            @ray.remote(max_retries=2)
            def die_once(path):
                import os as _os

                if not _os.path.exists(path):
                    with open(path, "w") as f:
                        f.write("x")
                    _os._exit(1)
                return "survived"

            path = f"/tmp/ray_tpu_die_{os.getpid()}"
            try:
                return ray.get(die_once.remote(path))
            finally:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    k = Killer.remote()
    assert ray.get(k.run.remote(), timeout=60) == "survived"


@ray.remote
class _Target:
    def __init__(self):
        self.n = 0

    def m(self):
        self.n += 1
        return self.n

    def get_n(self):
        return self.n


def test_direct_actor_calls(rt):
    @ray.remote
    class Caller:
        def run(self, target, n):
            import ray_tpu as ray

            return ray.get([target.m.remote() for _ in range(n)])[-1]

    t = _Target.remote()
    callers = [Caller.remote() for _ in range(3)]
    res = ray.get([c.run.remote(t, 25) for c in callers])
    assert sorted(res)[-1] == 75
    assert ray.get(t.get_n.remote()) == 75


def test_direct_actor_ordering(rt):
    @ray.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, x):
            self.log.append(x)

        def get_log(self):
            return self.log

    @ray.remote
    class Caller:
        def run(self, s):
            import ray_tpu as ray

            for i in range(30):
                s.add.remote(i)
            # The final get rides the same FIFO channel: it observes
            # every prior call.
            return ray.get(s.get_log.remote())

    s = Seq.remote()
    assert ray.get(Caller.remote().run.remote(s)) == list(range(30))


def test_direct_actor_death(rt):
    @ray.remote
    class Fragile:
        def die(self):
            import os

            os._exit(1)

        def ok(self):
            return 1

    @ray.remote
    class Caller:
        def run(self, f):
            import ray_tpu as ray

            assert ray.get(f.ok.remote()) == 1
            f.die.remote()
            try:
                ray.get(f.ok.remote(), timeout=30)
                return "alive"
            except ray.exceptions.RayActorError:
                return "died"
            except ray.exceptions.RayTpuError:
                return "died"

    f = Fragile.remote()
    assert ray.get(Caller.remote().run.remote(f), timeout=60) == "died"
