"""Numerics tests for ops/ kernels vs the XLA reference implementation.

Pattern follows the reference's per-component unit suites (SURVEY.md §4):
every kernel is tested against an oracle, fwd and bwd, causal and not.
Pallas kernels run in interpret mode on the CPU backend — same code path
that compiles for TPU.
"""

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.ops import (
    flash_attention, mha_reference, ring_attention, ulysses_attention,
    rms_norm, rope, apply_rope,
)
from ray_tpu.ops.moe import moe_ffn
from ray_tpu.parallel import MeshConfig, make_mesh, use_mesh

B, S, H, D = 2, 128, 4, 32


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    return tuple(jax.random.normal(k, (B, S, H, D), jnp.float32)
                 for k in jax.random.split(key, 3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_fwd(qkv, causal):
    q, k, v = qkv
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_bwd(qkv, causal):
    q, k, v = qkv
    f = lambda *a: (flash_attention(*a, causal=causal, block_q=64,
                                    block_k=64) ** 2).sum()
    g = lambda *a: (mha_reference(*a, causal=causal) ** 2).sum()
    got = jax.grad(f, (0, 1, 2))(q, k, v)
    want = jax.grad(g, (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-3


def test_flash_attention_gqa(qkv):
    q, _, _ = qkv
    key = jax.random.PRNGKey(7)
    k2, v2 = (jax.random.normal(k, (B, S, 2, D), jnp.float32)
              for k in jax.random.split(key, 2))
    out = flash_attention(q, k2, v2, causal=True)
    ref = mha_reference(q, jnp.repeat(k2, 2, 2), jnp.repeat(v2, 2, 2),
                        causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_attention(qkv, impl, causal):
    from jax.sharding import NamedSharding, PartitionSpec as P
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(dp=1, sp=4, tp=2))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    fn = ring_attention if impl == "ring" else ulysses_attention
    kw = {} if impl == "ring" else {"use_flash": False}
    ref = mha_reference(q, k, v, causal=causal)
    with use_mesh(mesh):
        out = fn(qs, ks, vs, causal=causal, mesh=mesh, **kw)
        assert jnp.max(jnp.abs(out - ref)) < 1e-4
        # grads through the ring/all-to-all
        loss = jax.jit(jax.grad(
            lambda a, b, c: (fn(a, b, c, causal=causal, mesh=mesh,
                                **kw) ** 2).sum(), (0, 1, 2)))
        got = loss(qs, ks, vs)
    want = jax.grad(
        lambda a, b, c: (mha_reference(a, b, c, causal=causal) ** 2).sum(),
        (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-3


def test_rms_norm():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    w = jnp.ones(16) * 2.0
    out = rms_norm(x, w)
    expected = x / jnp.sqrt(jnp.mean(x ** 2, -1, keepdims=True) + 1e-6) * 2.0
    assert jnp.allclose(out, expected, atol=1e-5)


def test_rope_offset_consistency():
    """Slicing full-range tables == computing with an offset (the 'sp'
    invariant ring attention relies on)."""
    cos_full, sin_full = rope(64, 32)
    cos_off, sin_off = rope(32, 32, offset=32)
    assert jnp.allclose(cos_full[32:], cos_off, atol=1e-6)
    assert jnp.allclose(sin_full[32:], sin_off, atol=1e-6)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 64, 2, 32))
    full = apply_rope(x, cos_full, sin_full)
    part = apply_rope(x[:, 32:], cos_off, sin_off)
    assert jnp.allclose(full[:, 32:], part, atol=1e-5)


def test_moe_routing_mass_conservation():
    """Every kept token's combine weights sum to its top-k gate mass."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (64, 16))
    rw = jax.random.normal(jax.random.PRNGKey(1), (16, 4)) * 0.1
    wg = jax.random.normal(jax.random.PRNGKey(2), (4, 16, 32)) * 0.1
    wu = jax.random.normal(jax.random.PRNGKey(3), (4, 16, 32)) * 0.1
    wd = jax.random.normal(jax.random.PRNGKey(4), (4, 32, 16)) * 0.1
    out = moe_ffn(x, rw, wg, wu, wd, num_selected=2, capacity_factor=4.0)
    assert out.out.shape == x.shape
    assert jnp.isfinite(out.out).all()
    assert float(out.aux_loss) > 0
    # generous capacity => no token dropped => output is differentiable
    # and gradient flows to every expert weight
    g = jax.grad(lambda w: (moe_ffn(x, rw, w, wu, wd, num_selected=2,
                                    capacity_factor=4.0).out ** 2).sum())(wg)
    assert float(jnp.abs(g).sum()) > 0
