"""Chaos-injection harness: kill workers/agents/connections on schedule
or at named syncpoints.

Reference analog: ``python/ray/_private/test_utils.py`` ``kill_raylet``/
``NodeKillerActor`` + the chaos-testing release jobs (``ray/release/
chaos_test``) — fault tolerance that is not exercised does not exist.

Opt-in twice over: nothing in this module runs unless (a) a test/driver
constructs a :class:`ChaosController`, or (b) ``RAY_TPU_CHAOS`` env
rules arm a spawned worker/agent process for deterministic self-kills
(see ``recovery.maybe_arm_env_chaos``; grammar ``role:point:n`` — e.g.
``worker:pull_chunk:3`` hard-kills the first worker to receive its 3rd
pull chunk).  Steady-state cost with chaos off is one module-global
``is None`` check per syncpoint.

Driver-side controller::

    chaos = ChaosController(rt)
    chaos.schedule(0.5, chaos.kill_worker)      # wall-clock schedule
    chaos.at_syncpoint("dispatch", chaos.kill_agent, n=10)
    ...
    chaos.stop()

Every kill increments the runtime's ``chaos_kills`` counter
(``transfer_stats()``), so tests can assert the injected faults actually
happened — a chaos test whose kill silently missed proves nothing.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

from ray_tpu._private import recovery

# Re-export: framework code fires syncpoints through recovery (no import
# cycle); tests and user code may import them from here.
syncpoint = recovery.syncpoint
parse_chaos_rules = recovery.parse_chaos_rules


def enabled() -> bool:
    """Whether env-driven chaos is requested (``RAY_TPU_CHAOS`` set)."""
    return bool(os.environ.get("RAY_TPU_CHAOS"))


# ---------------------------------------------------------- net chaos ----
# Gray-failure injection at the ``protocol.py`` send/recv seam: where
# the kill rules produce CLEAN failures (a process dies, its peer sees
# EOF), these produce the failures that announce nothing — full stalls
# (paused VM, wedged switch), silent drops (one-way partition), added
# latency, duplicates.  The failure-detection plane (deadlines,
# heartbeat suspicion) exists to survive exactly this class, and these
# rules are what make it testable.

def parse_net_rules(raw: str) -> List[Tuple[str, str, str, float, int]]:
    """``RAY_TPU_CHAOS_NET`` grammar: comma-separated
    ``role:point:action:n`` rules — in processes of ``role``
    ("worker"/"agent"/"driver"), the ``n``-th operation hitting net
    point ``point`` ("send", "recv", "chunk_send", or ``*`` for any)
    triggers ``action``:

    - ``stall``      — that operation and every later matching one
                       blocks forever (the alive-but-hung peer),
    - ``drop``       — sends are silently discarded from then on (the
                       outbound half of a partition),
    - ``delay-<ms>`` — every later matching operation sleeps first
                       (the saturated link),
    - ``dup``        — every later matching send goes out twice.

    Returns (role, point, action, param, n) tuples; unparseable rules
    are ignored (chaos must never break a production boot that
    inherited a stray env var)."""
    rules = []
    for part in (raw or "").split(","):
        bits = part.strip().split(":")
        if len(bits) != 4:
            continue
        role, point, action, n = bits
        param = 0.0
        if action.startswith("delay-"):
            try:
                param = float(action[len("delay-"):])
            except ValueError:
                continue
            action = "delay"
        if action not in ("stall", "drop", "delay", "dup"):
            continue
        try:
            rules.append((role, point, action, param, max(1, int(n))))
        except ValueError:
            continue
    return rules


class ChaosNet:
    """Per-process net-fault injector installed at the protocol seam.

    Two users: RAY_TPU_CHAOS_NET env rules armed at worker/agent entry
    (one-shot per cluster via the same O_EXCL claim-file convention as
    the kill rules, so a retried operation does not re-hit the fault
    elsewhere and the cluster converges), and the driver-side
    :class:`ChaosController` link methods (``stall_link``/
    ``partition``/``restore_link``), which scope rules to ONE peer
    connection in this process.

    The hook cost is one module-global ``is None`` check per send/recv
    until installed.  A ``stall`` parks the calling thread on the
    rule's resume event — ``restore`` (or controller stop) releases it;
    env-rule stalls are deliberately permanent for the process, the
    paused-VM semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[dict] = []
        self.net_faults = 0  # rules that actually fired

    # ------------------------------------------------------- install --
    def install(self) -> "ChaosNet":
        from ray_tpu._private import protocol

        protocol.set_net_hook(self._hook)
        return self

    def uninstall(self):
        from ray_tpu._private import protocol

        protocol.set_net_hook(None)
        self.restore()

    # --------------------------------------------------------- rules --
    def add_rule(self, point: str, action: str, conn=None,
                 param: float = 0.0, after: int = 1,
                 claim: Optional[str] = None) -> dict:
        rule = {
            "point": point, "action": action, "conn": conn,
            "param": param, "countdown": max(1, after),
            "claim": claim, "armed": False, "dead": False,
            "resume": threading.Event(),
        }
        with self._lock:
            self._rules.append(rule)
        return rule

    def restore(self, conn=None):
        """Lift rules (all, or just one connection's): stalled threads
        resume, drops/delays stop."""
        with self._lock:
            keep = []
            for r in self._rules:
                if conn is None or r["conn"] is conn:
                    r["dead"] = True
                    r["resume"].set()
                else:
                    keep.append(r)
            self._rules = keep

    # ---------------------------------------------------------- hook --
    def _hook(self, point: str, conn) -> Optional[str]:
        verdict = None
        fire = []
        with self._lock:
            for r in self._rules:
                if r["dead"]:
                    continue
                if r["point"] != "*" and r["point"] != point:
                    continue
                if r["conn"] is not None and r["conn"] is not conn:
                    continue
                if not r["armed"]:
                    r["countdown"] -= 1
                    if r["countdown"] > 0:
                        continue
                    if r["claim"] and not _claim_once(r["claim"]):
                        # Another process already owns this one-shot
                        # cluster-wide rule: this process sails through.
                        r["dead"] = True
                        continue
                    r["armed"] = True
                    self.net_faults += 1
                fire.append(r)
        for r in fire:
            act = r["action"]
            if act == "delay":
                time.sleep(r["param"] / 1000.0)
            elif act == "stall":
                # Park until restored: the gray failure itself.  The
                # socket stays open — no EOF ever announces this.
                r["resume"].wait()
            elif act == "drop":
                if point == "recv":
                    # Inbound drop = never deliver: equivalent to not
                    # reading (the bytes sit in the kernel buffer).
                    r["resume"].wait()
                else:
                    verdict = "drop"
            elif act == "dup":
                verdict = "dup"
        return verdict

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"net_faults": self.net_faults,
                    "net_rules": len(self._rules)}


def _claim_once(claim_path: str) -> bool:
    """O_EXCL one-shot claim (the kill rules' convention): the first
    process to trigger a cluster-wide env rule owns it."""
    try:
        fd = os.open(claim_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except OSError:
        return False
    os.write(fd, str(os.getpid()).encode())
    os.close(fd)
    return True


def maybe_arm_env_net_chaos(role: str) -> bool:
    """Arm ``RAY_TPU_CHAOS_NET`` rules for this process (worker/agent
    entry points call this next to ``recovery.maybe_arm_env_chaos``).
    Each rule fires in AT MOST ONE process per cluster via the claim
    file.  Zero cost when the env var is unset."""
    rules = [r for r in parse_net_rules(
        os.environ.get("RAY_TPU_CHAOS_NET", "")) if r[0] == role]
    if not rules:
        return False
    session = os.environ.get("RAY_TPU_SESSION", "nosession")
    chaos_dir = os.environ.get("RAY_TPU_CHAOS_DIR", "/tmp")
    net = ChaosNet()
    for r_role, point, action, param, n in rules:
        claim = os.path.join(
            chaos_dir,
            f"ray_tpu_chaos_net_{session}_{r_role}_{point}_{action}_{n}")
        net.add_rule(point, action, param=param, after=n, claim=claim)
    net.install()
    return True


class ChaosController:
    """Drives fault injection against one driver runtime.

    Kill primitives take the runtime lock only long enough to pick a
    victim and bump ``chaos_kills``; the actual kill (SIGKILL / conn
    close) runs outside it.  Syncpoint-triggered actions execute on a
    dedicated thread — the firing site may hold framework locks, and a
    kill that re-enters the runtime from under them would deadlock."""

    def __init__(self, rt=None, arm_syncpoints: bool = True, head=None):
        if rt is None:
            from ray_tpu._private.api_internal import require_runtime

            rt = require_runtime()
        self._rt = rt
        # Head manager for kill_head/restart_head: anything exposing
        # those two methods — canonically cluster_utils.Cluster with
        # external_head=True.  None = in-process head (killing it would
        # kill ourselves; the methods then raise).
        self._head = head
        self._head_kills = 0
        self._net: Optional[ChaosNet] = None  # lazy gray-failure seam
        self._lock = threading.Lock()
        self._timers: List[threading.Timer] = []
        # name -> list of [countdown, action, args] triples
        self._sync_actions: Dict[str, List[list]] = {}
        self._pending: List[tuple] = []
        self._pending_ev = threading.Event()
        self._stopped = False
        self._runner = threading.Thread(target=self._run_loop, daemon=True,
                                        name="ray_tpu-chaos")
        self._runner.start()
        if arm_syncpoints:
            recovery.set_chaos_hook(self._fire)

    # ------------------------------------------------------ scheduling --
    def schedule(self, delay_s: float, action: Callable, *args, **kwargs):
        """Run ``action`` after ``delay_s`` wall-clock seconds."""
        t = threading.Timer(delay_s,
                            lambda: self._enqueue(action, args, kwargs))
        t.daemon = True
        with self._lock:
            self._timers.append(t)
        t.start()
        return t

    def at_syncpoint(self, name: str, action: Callable, *args,
                     n: int = 1, **kwargs):
        """Run ``action`` when syncpoint ``name`` fires for the n-th
        time (counted from registration)."""
        with self._lock:
            self._sync_actions.setdefault(name, []).append(
                [max(1, n), action, args, kwargs])

    def _fire(self, name: str, _info: dict):
        todo = []
        with self._lock:
            lst = self._sync_actions.get(name)
            if not lst:
                return
            for item in list(lst):
                item[0] -= 1
                if item[0] <= 0:
                    lst.remove(item)
                    todo.append(item)
        for _n, action, args, kwargs in todo:
            self._enqueue(action, args, kwargs)

    def _enqueue(self, action, args, kwargs):
        with self._lock:
            if self._stopped:
                return
            self._pending.append((action, args, kwargs))
        self._pending_ev.set()

    def _run_loop(self):
        while not self._stopped:
            self._pending_ev.wait()
            self._pending_ev.clear()
            while True:
                with self._lock:
                    if not self._pending:
                        break
                    action, args, kwargs = self._pending.pop(0)
                try:
                    action(*args, **kwargs)
                except Exception:
                    pass  # a missed kill must not crash the harness

    # ------------------------------------------------------------ kills --
    def _count_kill(self):
        with self._rt.lock:
            self._rt.chaos_kills += 1

    def kill_worker(self, node_id: Optional[str] = None,
                    actor: Optional[bool] = None,
                    mid_task: bool = True) -> Optional[str]:
        """SIGKILL one worker process.  ``node_id`` scopes the pick to a
        node (hex); ``actor`` True/False filters actor vs plain workers;
        ``mid_task`` prefers a worker with in-flight work (the
        interesting case).  Returns the victim's worker id hex, or None
        when nothing matched."""
        victim = None
        with self._rt.lock:
            candidates = []
            for node in self._rt.nodes.values():
                if node_id is not None and node.node_id.hex() != node_id:
                    continue
                for w in node.all_workers.values():
                    if w.dead:
                        continue
                    if actor is True and w.actor_id is None:
                        continue
                    if actor is False and w.actor_id is not None:
                        continue
                    busy = bool(w.inflight) or (
                        w.actor_id is not None and w.conn is not None)
                    candidates.append((busy, w))
            for busy, w in candidates:
                if busy or not mid_task:
                    victim = w
                    break
            if victim is None:
                return None
            self._rt.chaos_kills += 1
        wid = victim.worker_id.hex()
        if victim.proc is not None:
            try:
                victim.proc.kill()
            except Exception:
                pass
        else:
            agent = (victim.node.agent
                     if victim.node is not None else None)
            if agent is not None and not agent.dead:
                try:
                    agent.send(("kill_worker_hard", wid))
                except Exception:
                    pass
        return wid

    def _pick_agent_locked(self, node_id: Optional[str]):
        """First live agent (optionally scoped to a node hex) — the ONE
        selection rule for kill_agent and preempt_node, so the two
        faults always aim at the same target for the same scope."""
        for agent in self._rt._agents.values():
            if agent.dead or agent.node is None:
                continue
            if node_id is not None \
                    and agent.node.node_id.hex() != node_id:
                continue
            return agent
        return None

    def kill_agent(self, node_id: Optional[str] = None) -> Optional[str]:
        """SIGKILL a node agent process (no graceful shutdown — its
        workers are orphaned exactly as on real node loss).  Returns the
        node id hex, or None."""
        with self._rt.lock:
            target = self._pick_agent_locked(node_id)
            if target is None:
                return None
            self._rt.chaos_kills += 1
        pid = target.info.get("pid")
        nid = target.node.node_id.hex()
        if pid:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        # Don't wait for the conn EOF: drive death handling now, like
        # remove_node does — chaos tests need deterministic discovery.
        try:
            target.conn.close()
        except Exception:
            pass
        self._rt._on_agent_death(target)
        return nid

    def preempt_node(self, node_id: Optional[str] = None,
                     notice: bool = True) -> Optional[str]:
        """Preempt one agent-backed node — the spot/preemptible-slice
        fault.  With ``notice`` (the provider's warning window) the
        agent gets SIGUSR1 and self-drains through the head
        (``preempt_notice`` → drain → clean exit); without, this is the
        no-warning variant — a straight ``kill_agent`` SIGKILL.
        Returns the node id hex, or None when nothing matched."""
        if not notice:
            return self.kill_agent(node_id)
        with self._rt.lock:
            target = self._pick_agent_locked(node_id)
        if target is None or not target.info.get("pid"):
            # kill_agent can still take a pid-less agent down (conn
            # close drives death handling); a NOTICE needs the pid.
            return None
        try:
            os.kill(target.info["pid"], signal.SIGUSR1)
        except OSError:
            return None  # pid already gone: no fault was injected
        # Counted only after the signal landed — unlike kill_agent,
        # which always drives death handling, a failed notice here is
        # no event at all and must not burn the exact-count asserts.
        self._count_kill()
        return target.node.node_id.hex()

    def drop_worker_connection(self,
                               worker_id: Optional[str] = None,
                               stall: bool = False) -> Optional[str]:
        """Take a worker's control connection away WITHOUT killing the
        process.  Default (``stall=False``): close it — the half-death
        case whose EOF the head discovers immediately and reroutes.
        ``stall=True`` is the GRAY variant: the socket stays open but
        the head stops reading it (and the worker's results rot in the
        kernel buffer) — no EOF ever fires, and only the heartbeat
        suspicion machinery can discover it.  One API, A/B-able clean
        vs gray."""
        victim = None
        with self._rt.lock:
            for node in self._rt.nodes.values():
                for w in node.all_workers.values():
                    if w.dead or w.conn is None:
                        continue
                    if worker_id is not None \
                            and w.worker_id.hex() != worker_id:
                        continue
                    victim = w
                    break
                if victim is not None:
                    break
            if victim is None:
                return None
            self._rt.chaos_kills += 1
        if stall:
            # Hold the socket open, stop reading: the head-side reader
            # parks inside the net hook; sends to the worker are
            # swallowed so its gets/waits starve too.  net_faults
            # counts it as an injected gray fault.
            net = self._ensure_net()
            net.add_rule("recv", "stall", conn=victim.conn)
            net.add_rule("send", "drop", conn=victim.conn)
        else:
            try:
                victim.conn.close()
            except Exception:
                pass
        return victim.worker_id.hex()

    # ------------------------------------------------------ net faults --
    def _ensure_net(self) -> ChaosNet:
        with self._lock:
            if self._net is None:
                self._net = ChaosNet().install()
            return self._net

    def stall_link(self, node_id: Optional[str] = None) -> Optional[str]:
        """Full gray stall of the head<->agent link of one node: the
        head stops reading the agent's messages (heartbeats included)
        and its sends are silently swallowed — both processes stay
        alive, nothing EOFs.  The suspicion machine is what must notice.
        Returns the node id hex, or None."""
        with self._rt.lock:
            target = self._pick_agent_locked(node_id)
        if target is None:
            return None
        net = self._ensure_net()
        net.add_rule("recv", "stall", conn=target.conn)
        net.add_rule("send", "drop", conn=target.conn)
        return target.node.node_id.hex()

    def partition(self, node_id: Optional[str] = None,
                  direction: str = "in") -> Optional[str]:
        """One-way partition of a node's head link: ``direction="in"``
        drops everything the agent sends (the head goes deaf to it —
        heartbeat silence with a perfectly healthy agent process);
        ``"out"`` silently swallows the head's sends instead.  Returns
        the node id hex, or None."""
        with self._rt.lock:
            target = self._pick_agent_locked(node_id)
        if target is None:
            return None
        net = self._ensure_net()
        if direction == "in":
            net.add_rule("recv", "stall", conn=target.conn)
        else:
            net.add_rule("send", "drop", conn=target.conn)
        return target.node.node_id.hex()

    def restore_link(self, node_id: Optional[str] = None):
        """Lift controller-installed link faults (one node's, or all)."""
        if self._net is None:
            return
        if node_id is None:
            self._net.restore()
            return
        with self._rt.lock:
            target = self._pick_agent_locked(node_id)
        if target is not None:
            self._net.restore(target.conn)

    def attach_head(self, head) -> None:
        """Late-bind the head manager (the pytest fixture constructs the
        controller before a test decides to boot an external head)."""
        self._head = head

    def kill_head(self) -> Optional[int]:
        """SIGKILL the HEAD process — the last single point of failure.
        Requires an external head (``Cluster(external_head=True)``
        passed as ``head=``/``attach_head``); an in-process head shares
        our pid, so there is nothing survivable to kill.  Counted
        locally (``stats()["head_kills"]``) because the head's own
        counter dies with it."""
        if self._head is None:
            raise RuntimeError(
                "kill_head needs an external head: pass head="
                "Cluster(external_head=True) (or attach_head it)")
        pid = self._head.kill_head()
        with self._lock:
            self._head_kills += 1
        return pid

    def restart_head(self) -> Optional[int]:
        """Re-run the killed head with gcs_restore on the same
        port/authkey; surviving agents/workers/clients reconnect-and-
        replay on their own."""
        if self._head is None:
            raise RuntimeError(
                "restart_head needs an external head: pass head="
                "Cluster(external_head=True) (or attach_head it)")
        return self._head.restart_head()

    # ------------------------------------------------------------ admin --
    def stats(self) -> Dict[str, int]:
        out = {"chaos_kills": 0, "net_faults": 0}
        try:
            with self._rt.lock:
                out["chaos_kills"] = self._rt.chaos_kills
        except AttributeError:
            # Client-runtime controller (external head): the cluster
            # counter lives server-side — transfer_stats() has it.
            pass
        with self._lock:
            out["head_kills"] = self._head_kills
            if self._net is not None:
                out["net_faults"] = self._net.stats()["net_faults"]
        return out

    def stop(self):
        with self._lock:
            self._stopped = True
            timers, self._timers = self._timers, []
            self._sync_actions.clear()
            self._pending.clear()
            net, self._net = self._net, None
        for t in timers:
            t.cancel()
        recovery.set_chaos_hook(None)
        if net is not None:
            net.uninstall()  # stalled threads resume; rules lift
        self._pending_ev.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.stop()
        return False
