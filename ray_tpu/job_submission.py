"""Job submission: run driver scripts against the cluster, supervised.

Reference: ``dashboard/modules/job/job_manager.py`` (jobs are driver
processes run by a supervisor on the cluster, logs streamed, status
tracked) + ``sdk.py:40 JobSubmissionClient``.  Condensed: the head hosts
a JobManager; each job is a subprocess whose environment carries the
cluster's client address (RAY_TPU_CLIENT_ADDRESS/AUTHKEY), so
``ray_tpu.init()`` inside the entrypoint attaches to THIS cluster in
client mode.  ``JobSubmissionClient`` works in-process against the local
runtime or remotely over a client connection (the CLI path).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

JOB_STATUSES = ("PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED")


class JobInfo:
    def __init__(self, job_id: str, entrypoint: str, runtime_env: dict):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.status = "PENDING"
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = ""
        self.start_time = time.time()
        self.end_time: Optional[float] = None

    def snapshot(self) -> Dict[str, Any]:
        return {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": self.status, "start_time": self.start_time,
                "end_time": self.end_time, "log_path": self.log_path}


class JobManager:
    """Head-side supervisor (reference: JobManager, job_manager.py)."""

    def __init__(self, runtime):
        self._rt = runtime
        self._jobs: Dict[str, JobInfo] = {}
        self._lock = threading.Lock()
        self._log_dir = tempfile.mkdtemp(
            prefix=f"ray_tpu_jobs_{runtime.session_id}_")

    def submit(self, entrypoint: str, runtime_env: Optional[dict] = None,
               submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"job_{uuid.uuid4().hex[:12]}"
        info = JobInfo(job_id, entrypoint, runtime_env or {})
        info.log_path = os.path.join(self._log_dir, f"{job_id}.log")
        env = dict(os.environ)
        env.update((runtime_env or {}).get("env_vars", {}))
        env["RAY_TPU_CLIENT_ADDRESS"] = self._rt.tcp_address
        env["RAY_TPU_CLIENT_AUTHKEY"] = self._rt._authkey.hex()
        env["RAY_TPU_JOB_ID"] = job_id
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + env.get("PYTHONPATH", ""))
        cwd = (runtime_env or {}).get("working_dir") or os.getcwd()
        log = open(info.log_path, "wb")
        info.proc = subprocess.Popen(
            entrypoint if os.name == "nt" else shlex.split(entrypoint),
            env=env, cwd=cwd, stdout=log, stderr=subprocess.STDOUT)
        log.close()
        info.status = "RUNNING"
        with self._lock:
            self._jobs[job_id] = info
        self._rt._gcs_dirty += 1
        threading.Thread(target=self._wait, args=(info,), daemon=True,
                         name=f"job-{job_id}").start()
        return job_id

    def _wait(self, info: JobInfo):
        rc = info.proc.wait()
        with self._lock:
            if info.status == "RUNNING":
                info.status = "SUCCEEDED" if rc == 0 else "FAILED"
            info.end_time = time.time()
        self._rt._gcs_dirty += 1

    def status(self, job_id: str) -> str:
        with self._lock:
            info = self._jobs.get(job_id)
        return info.status if info else "NOT_FOUND"

    def logs(self, job_id: str) -> str:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            return ""
        try:
            with open(info.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None or info.status != "RUNNING":
                return False
            info.status = "STOPPED"
        try:
            info.proc.terminate()
        except Exception:
            pass
        return True

    def list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [i.snapshot() for i in self._jobs.values()]

    def snapshot_rows(self) -> List[Dict[str, Any]]:
        """Rows for the head's GCS snapshot (persistence across head
        restarts; reference: the GCS job table survives failover)."""
        return self.list()

    def adopt_rows(self, rows: List[Dict[str, Any]]):
        """Re-adopt job records from a pre-restart snapshot.  Their
        driver processes died with the old head: RUNNING/PENDING rows
        become FAILED with a restart note."""
        with self._lock:
            for row in rows:
                if row["job_id"] in self._jobs:
                    continue
                info = JobInfo(row["job_id"], row["entrypoint"], {})
                info.status = ("FAILED"
                               if row["status"] in ("PENDING", "RUNNING")
                               else row["status"])
                info.start_time = row.get("start_time", 0.0)
                info.end_time = row.get("end_time")
                info.log_path = row.get("log_path", "")
                self._jobs[row["job_id"]] = info


def _get_manager(runtime) -> JobManager:
    mgr = getattr(runtime, "_job_manager", None)
    if mgr is None:
        mgr = runtime._job_manager = JobManager(runtime)
        restored = getattr(runtime, "_restored_jobs", None)
        if restored:
            mgr.adopt_rows(restored)
    return mgr


class JobSubmissionClient:
    """reference: dashboard/modules/job/sdk.py:40 — same method names.
    With no address: drives the in-process runtime's JobManager.  With an
    address: sends job_* control messages over a client connection."""

    def __init__(self, address: Optional[str] = None,
                 _authkey: Optional[str] = None):
        from ray_tpu._private import api_internal

        self._client = None
        if address is not None:
            from ray_tpu._private.client import client_connect

            key = _authkey or os.environ.get("RAY_TPU_CLIENT_AUTHKEY")
            if not key:
                raise ValueError("remote JobSubmissionClient needs _authkey")
            self._client = client_connect(address, bytes.fromhex(key))
            self._mgr = None
        else:
            rt = api_internal.require_runtime()
            if getattr(rt, "is_client", False):
                self._client = rt
                self._mgr = None
            else:
                self._mgr = _get_manager(rt)

    def _req(self, builder):
        out = self._client.request(builder)
        if isinstance(out, Exception):
            raise out
        return out

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None) -> str:
        if self._mgr is not None:
            return self._mgr.submit(entrypoint, runtime_env, submission_id)
        return self._req(lambda rid: ("job_submit", rid, entrypoint,
                                      runtime_env, submission_id))

    def get_job_status(self, job_id: str) -> str:
        if self._mgr is not None:
            return self._mgr.status(job_id)
        return self._req(lambda rid: ("job_status", rid, job_id))

    def get_job_logs(self, job_id: str) -> str:
        if self._mgr is not None:
            return self._mgr.logs(job_id)
        return self._req(lambda rid: ("job_logs", rid, job_id))

    def stop_job(self, job_id: str) -> bool:
        if self._mgr is not None:
            return self._mgr.stop(job_id)
        return self._req(lambda rid: ("job_stop", rid, job_id))

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self._mgr is not None:
            return self._mgr.list()
        return self._req(lambda rid: ("job_list", rid))

    def tail_job_logs(self, job_id: str, timeout: float = 60.0):
        """Generator of log chunks until the job finishes."""
        seen = 0
        deadline = time.time() + timeout
        while time.time() < deadline:
            text = self.get_job_logs(job_id)
            if len(text) > seen:
                yield text[seen:]
                seen = len(text)
            if self.get_job_status(job_id) not in ("PENDING", "RUNNING"):
                text = self.get_job_logs(job_id)
                if len(text) > seen:
                    yield text[seen:]
                return
            time.sleep(0.3)
