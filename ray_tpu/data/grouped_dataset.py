"""Grouped aggregation over Datasets.

Reference: ``python/ray/data/grouped_dataset.py`` (GroupedDataset with
count/sum/min/max/mean/std + AggregateFn) and ``aggregate.py`` — same API
surface, re-built on this Dataset's hash-partition shuffle: map tasks
bucket rows by group-key hash, one reduce task per bucket folds its
groups with the AggregateFns.  No driver materialization; the output is
one block per reducer of ``{key, agg_name: value}`` rows.
"""

from __future__ import annotations

import builtins
import itertools
import math
from typing import Any, Callable, List, Optional, Union

import ray_tpu as ray
from ray_tpu.data.dataset import (
    Dataset, _block_rows, _hash_partition, _keyfn_of,
)
from ray_tpu.remote_function import _bulk_submit


class AggregateFn:
    """reference: aggregate.py AggregateFn — init/accumulate/merge/
    finalize fold protocol."""

    def __init__(self, init: Callable[[], Any],
                 accumulate: Callable[[Any, Any], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Callable[[Any], Any] = lambda a: a,
                 name: str = "agg"):
        self.init = init
        self.accumulate = accumulate
        self.merge = merge
        self.finalize = finalize
        self.name = name


def _value_getter(on: Optional[Union[str, Callable]]):
    if on is None:
        return lambda r: r
    if isinstance(on, str):
        return lambda r: r[on]
    return on


def Count() -> AggregateFn:
    return AggregateFn(lambda: 0, lambda a, _r: a + 1,
                       lambda a, b: a + b, name="count()")


def Sum(on=None) -> AggregateFn:
    get = _value_getter(on)
    return AggregateFn(lambda: 0, lambda a, r: a + get(r),
                       lambda a, b: a + b,
                       name=f"sum({on if isinstance(on, str) else ''})")


def Min(on=None) -> AggregateFn:
    get = _value_getter(on)
    return AggregateFn(lambda: None,
                       lambda a, r: get(r) if a is None
                       else min(a, get(r)),
                       lambda a, b: b if a is None
                       else (a if b is None else min(a, b)),
                       name=f"min({on if isinstance(on, str) else ''})")


def Max(on=None) -> AggregateFn:
    get = _value_getter(on)
    return AggregateFn(lambda: None,
                       lambda a, r: get(r) if a is None
                       else max(a, get(r)),
                       lambda a, b: b if a is None
                       else (a if b is None else max(a, b)),
                       name=f"max({on if isinstance(on, str) else ''})")


def Mean(on=None) -> AggregateFn:
    get = _value_getter(on)
    return AggregateFn(lambda: (0, 0),
                       lambda a, r: (a[0] + get(r), a[1] + 1),
                       lambda a, b: (a[0] + b[0], a[1] + b[1]),
                       lambda a: a[0] / a[1] if a[1] else None,
                       name=f"mean({on if isinstance(on, str) else ''})")


def Std(on=None, ddof: int = 1) -> AggregateFn:
    get = _value_getter(on)

    def fin(a):
        s, s2, n = a
        if n <= ddof:
            return None
        var = (s2 - s * s / n) / (n - ddof)
        return math.sqrt(max(0.0, var))

    return AggregateFn(lambda: (0.0, 0.0, 0),
                       lambda a, r: (a[0] + get(r),
                                     a[1] + get(r) ** 2, a[2] + 1),
                       lambda a, b: (a[0] + b[0], a[1] + b[1],
                                     a[2] + b[2]),
                       fin,
                       name=f"std({on if isinstance(on, str) else ''})")


@ray.remote
def _agg_reduce(key, aggs: List[AggregateFn], *parts):
    """One reducer: fold its bucket's rows per group, emit result rows."""
    keyfn = _keyfn_of(key)
    accs = {}  # group key -> [acc per agg]
    for r in itertools.chain(*parts):
        k = keyfn(r)
        acc = accs.get(k)
        if acc is None:
            acc = accs[k] = [a.init() for a in aggs]
        for i, a in enumerate(aggs):
            acc[i] = a.accumulate(acc[i], r)
    key_col = key if isinstance(key, str) else "key"
    out = []
    for k in sorted(accs, key=lambda x: (x is None, x)):
        row = {key_col: k}
        for a, acc in zip(aggs, accs[k]):
            row[a.name] = a.finalize(acc)
        out.append(row)
    return out


@ray.remote
def _map_groups_task(key, fn, *parts):
    keyfn = _keyfn_of(key)
    groups = {}
    for r in itertools.chain(*parts):
        groups.setdefault(keyfn(r), []).append(r)
    out = []
    for k in sorted(groups, key=lambda x: (x is None, x)):
        res = fn(groups[k])
        out.extend(res if isinstance(res, list) else [res])
    return out


class GroupedDataset:
    """reference: grouped_dataset.py:GroupedDataset."""

    def __init__(self, ds: Dataset, key: Union[str, Callable]):
        self._ds = ds
        self._key = key

    def _shuffled_parts(self):
        """Hash-partition every (engine-executed) block; both the map
        and reduce fan-outs go through the bulk submission path — one
        dispatch pass per side instead of one per block/reducer."""
        blocks = self._ds._executed_refs()
        n = max(1, len(blocks))
        mapper = _hash_partition.options(num_returns=n)
        parts = _bulk_submit([(mapper, (b, self._key, n), None)
                              for b in blocks])
        if n == 1:
            parts = [[p] for p in parts]
        return n, parts

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        if not aggs:
            raise ValueError("aggregate() needs at least one AggregateFn")
        pushed = self._ds._try_push_shuffle(
            "groupby", key=self._key, aggs=list(aggs))
        if pushed is not None:
            return pushed
        n, parts = self._shuffled_parts()
        out = _bulk_submit([
            (_agg_reduce,
             (self._key, list(aggs),
              *[parts[i][j] for i in builtins.range(len(parts))]), None)
            for j in builtins.range(n)])
        return Dataset(out)

    def map_groups(self, fn: Callable[[List[Any]], Any]) -> Dataset:
        """reference: grouped_dataset.py map_groups — fn sees the full
        row list of one group."""
        pushed = self._ds._try_push_shuffle(
            "map_groups", key=self._key, fn=fn)
        if pushed is not None:
            return pushed
        n, parts = self._shuffled_parts()
        out = _bulk_submit([
            (_map_groups_task,
             (self._key, fn,
              *[parts[i][j] for i in builtins.range(len(parts))]), None)
            for j in builtins.range(n)])
        return Dataset(out)

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on=None) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on=None) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on=None) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on=None) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on=None, ddof: int = 1) -> Dataset:
        return self.aggregate(Std(on, ddof))
