"""Streaming-execution internals: staged plans, the actor-pool map
operator, and per-operator stats.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py:35``
(operator graph with resource-budgeted admission),
``execution/operators/actor_pool_map_operator.py`` (stateful UDFs on a
pool of long-lived actors), and ``_internal/stats.py`` (per-op wall/rows
accounting behind ``ds.stats()``).

Design here: a fused op chain splits into STAGES at actor-compute ops —
task stages run as one task per block (whole fused sub-chain), actor
stages run on a lazily-created pool with least-loaded dispatch.  Every
stage returns ``(block, stats)`` as two objects, so the tiny stats dicts
can be collected without pulling blocks to the driver.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit

ACTOR_OP = "map_batches_actor"


def split_stages(ops: tuple) -> List[Tuple[str, Any]]:
    """Fused chain -> [("tasks", sub_ops) | ("actors", actor_op), ...]."""
    stages: List[Tuple[str, Any]] = []
    cur: list = []
    for op in ops:
        if op[0] == ACTOR_OP:
            if cur:
                stages.append(("tasks", tuple(cur)))
                cur = []
            stages.append(("actors", op))
        else:
            cur.append(op)
    if cur:
        stages.append(("tasks", tuple(cur)))
    return stages


def _est_bytes(block) -> int:
    try:
        import numpy as _np

        if isinstance(block, dict):
            return sum(v.nbytes if isinstance(v, _np.ndarray)
                       else len(v) * 8 for v in block.values())
        if isinstance(block, _np.ndarray):
            return block.nbytes
        if hasattr(block, "nbytes"):  # pyarrow.Table
            return int(block.nbytes)
        return len(block) * 64  # rows-of-dicts rough estimate
    except Exception:
        return 0


@ray.remote(num_returns=2)
def apply_stage_with_stats(ops: tuple, block):
    """Run a fused task-stage over one block; second return is the per-op
    stats list (kept tiny so stats collection never moves block data)."""
    from ray_tpu.data.dataset import _apply_op, _block_len

    stats = []
    for op in ops:
        t0 = time.perf_counter()
        block = _apply_op(op, block)
        stats.append({"op": op[0], "wall_s": time.perf_counter() - t0,
                      "rows_out": _block_len(block),
                      "bytes_out": _est_bytes(block)})
    return block, stats


@ray.remote
class _MapWorker:
    """One actor of the pool (reference: actor_pool_map_operator.py's
    MapWorker).  A CLASS fn is instantiated once here — that instance
    carries the user's state (model weights, connections) across
    blocks, which is the entire point of compute="actors"."""

    def __init__(self, fn, batch_format: str):
        self._fn = fn() if isinstance(fn, type) else fn
        self._batch_format = batch_format

    def ready(self):
        return True

    def apply(self, prior_ops: tuple, block):
        from ray_tpu.data.dataset import _apply_op, _block_len

        stats = []
        for op in prior_ops:
            t0 = time.perf_counter()
            block = _apply_op(op, block)
            stats.append({"op": op[0],
                          "wall_s": time.perf_counter() - t0,
                          "rows_out": _block_len(block),
                          "bytes_out": _est_bytes(block)})
        t0 = time.perf_counter()
        block = _apply_op(("map_batches", self._fn, self._batch_format),
                          block)
        stats.append({"op": "map_batches(actors)",
                      "wall_s": time.perf_counter() - t0,
                      "rows_out": _block_len(block),
                      "bytes_out": _est_bytes(block)})
        return block, stats


class ActorPoolMapOperator:
    """Least-loaded dispatch over ``size`` map workers (reference:
    actor_pool_map_operator.py + the autoscaling ActorPool — fixed size
    here; blocks queue on the least-busy worker).

    Dispatch only targets actors whose __init__ completed: on a cluster
    with fewer free CPUs than ``size``, the unscheduled actors simply
    never receive blocks (the reference's pool likewise scales to what
    actually got placed) — statically round-robining onto a never-
    scheduled actor would hang the stream."""

    _STRAGGLER_GRACE_S = 10.0

    def __init__(self, fn, batch_format: str, size: int):
        # Never reserve the whole cluster: upstream task stages need at
        # least one slot or the stream deadlocks (pool actors waiting on
        # input refs whose producing tasks can never schedule).
        try:
            total_cpu = int(ray.cluster_resources().get("CPU", size + 1))
            size = max(1, min(size, total_cpu - 1))
        except Exception:
            pass
        self._actors = [
            _MapWorker.options(num_cpus=1).remote(fn, batch_format)
            for _ in range(max(1, size))]
        self._inflight = [0] * len(self._actors)
        self._ready_refs = _bulk_submit([(a.ready, (), None)
                                         for a in self._actors])
        self._ready = [False] * len(self._actors)
        # Unscheduled actors get this long to come up while the ready
        # ones are busy; after that, dispatch permanently ignores them.
        self._grace_deadline = time.monotonic() + self._STRAGGLER_GRACE_S

    def _ready_indices(self) -> List[int]:
        pending = [(i, r) for i, r in enumerate(self._ready_refs)
                   if not self._ready[i]]
        if pending:
            done, _ = ray.wait([r for _, r in pending],
                               num_returns=len(pending), timeout=0)
            done_set = set(done)
            for i, r in pending:
                if r in done_set:
                    self._ready[i] = True
        out = [i for i, ok in enumerate(self._ready) if ok]
        if not out:
            # No actor placed yet: block for the FIRST one (at least one
            # must eventually schedule or the workload is infeasible).
            ray.wait(self._ready_refs, num_returns=1, timeout=None)
            return self._ready_indices()
        return out

    def submit(self, prior_ops: tuple, block_ref):
        ready = self._ready_indices()
        while (len(ready) < len(self._actors)
               and min(self._inflight[i] for i in ready) > 0
               and time.monotonic() < self._grace_deadline):
            # The placed actors are all busy and stragglers may still
            # schedule: give them a beat instead of piling onto one.
            pending = [r for i, r in enumerate(self._ready_refs)
                       if not self._ready[i]]
            ray.wait(pending, num_returns=1, timeout=0.2)
            ready = self._ready_indices()
        i = min(ready, key=self._inflight.__getitem__)
        self._inflight[i] += 1
        block, stats = self._actors[i].apply.options(num_returns=2).remote(
            prior_ops, block_ref)
        return block, stats, i

    def done(self, i: int):
        self._inflight[i] = max(0, self._inflight[i] - 1)

    def shutdown(self):
        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        self._actors = []


class DatasetStats:
    """Aggregated per-operator accounting behind ``ds.stats()``
    (reference: _internal/stats.py DatasetStatsSummary).  When the
    streaming engine ran, its ``StreamingStats`` snapshot attaches as
    ``self.streaming`` and ``__str__`` gains per-operator
    queued/in-flight/peak-bytes rows (surfaced like
    ``Runtime.transfer_stats()``); on the legacy windowed path it stays
    ``None`` and every streaming counter reads zero."""

    def __init__(self):
        self._ops: Dict[str, Dict[str, float]] = {}
        self._stats_refs: List[Any] = []
        self._wall_start: Optional[float] = None
        self._wall_end: Optional[float] = None
        self.streaming = None  # StreamingStats of the last streaming run
        # Push-shuffle summary dict of the last shuffle (None when the
        # legacy pull shuffle ran or push_shuffle is off — then every
        # shuffle counter in shuffle_summary() reads zero).
        self.shuffle = None

    def note_start(self):
        if self._wall_start is None:
            self._wall_start = time.perf_counter()

    def note_end(self):
        self._wall_end = time.perf_counter()

    def add_ref(self, stats_ref):
        self._stats_refs.append(stats_ref)

    def add_stats(self, per_block: List[dict]):
        """Fold one block's per-op stats list directly (the streaming
        executor materializes stats at task completion, so there is no
        ref to drain later)."""
        for s in per_block or ():
            agg = self._ops.setdefault(
                s["op"], {"blocks": 0, "wall_s": 0.0, "rows_out": 0,
                          "bytes_out": 0})
            agg["blocks"] += 1
            agg["wall_s"] += s["wall_s"]
            agg["rows_out"] += s["rows_out"]
            agg["bytes_out"] += s["bytes_out"]

    def streaming_summary(self) -> Dict[str, Any]:
        """Engine counters of the last run; all-zero when the legacy
        windowed path executed (config.streaming_executor=off)."""
        from ray_tpu.data import streaming_executor as _se

        if self.streaming is None:
            return _se.empty_summary()
        return self.streaming.summary()

    def shuffle_summary(self) -> Dict[str, Any]:
        """Push-shuffle counters of the last run; all-zero when the
        legacy pull shuffle executed (config.push_shuffle=off, non-head
        driver, or a single-block dataset)."""
        if self.shuffle is None:
            return {"maps": 0, "reducers": 0, "shuffle_pushed_bytes": 0,
                    "shuffle_merges": 0, "shuffle_spills": 0,
                    "shuffle_hedges": 0}
        return dict(self.shuffle)

    def _drain(self):
        if not self._stats_refs:
            return
        refs, self._stats_refs = self._stats_refs, []
        for per_block in ray.get(refs):
            self.add_stats(per_block)

    def summary(self) -> Dict[str, Dict[str, float]]:
        self._drain()
        return {k: dict(v) for k, v in self._ops.items()}

    def __str__(self) -> str:
        self._drain()
        lines = []
        if self._wall_start is not None and self._wall_end is not None:
            lines.append(
                f"Dataset execution: "
                f"{self._wall_end - self._wall_start:.3f}s wall")
        for op, agg in self._ops.items():
            mb = agg["bytes_out"] / 1e6
            lines.append(
                f"  {op}: {agg['blocks']} blocks, "
                f"{agg['wall_s'] * 1e3:.1f}ms task time, "
                f"{int(agg['rows_out'])} rows out, {mb:.2f}MB out")
        if self.shuffle is not None:
            sh = self.shuffle
            lines.append(
                f"Push shuffle: {sh['maps']} maps -> "
                f"{sh['reducers']} reducers, "
                f"{sh['shuffle_pushed_bytes'] / 1e6:.2f}MB pushed, "
                f"{sh['shuffle_merges']} merges, "
                f"{sh['shuffle_spills']} spills, "
                f"{sh['shuffle_hedges']} hedges")
        if self.streaming is not None:
            s = self.streaming.summary()
            lines.append(
                f"Streaming executor: peak in-flight "
                f"{s['peak_inflight_bytes'] / 1e6:.2f}MB of "
                f"{s['budget_bytes'] / 1e6:.2f}MB budget, "
                f"{s['admitted_tasks']} tasks "
                f"({s['cancelled_tasks']} cancelled, "
                f"{s['backpressure_stalls']} backpressure stalls)")
            for name, row in s["ops"].items():
                lines.append(
                    f"  [op {name}] queued {row['queued_blocks']} blocks"
                    f"/{row['queued_bytes'] / 1e6:.2f}MB "
                    f"(peak {row['peak_queued_bytes'] / 1e6:.2f}MB), "
                    f"in-flight peak {row['peak_inflight']}, "
                    f"out {row['out_blocks']} blocks"
                    f"/{row['out_bytes'] / 1e6:.2f}MB")
        return "\n".join(lines) or "Dataset: no execution recorded"
