"""Backpressured streaming operator-graph execution engine.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py:35``
— the physical plan is a DAG of operators, each owning input/output
queues and an in-flight task set, driven by a dispatch loop that admits
work under a global resource budget — plus
``execution/operators/map_operator.py`` (fusion of compatible map-like
transforms into one physical operator) and the bounded-memory
pipelined-operator argument of the Exoshuffle / Ownership (NSDI'21)
papers (PAPERS.md).

The legacy windowed path (``Dataset._stream_refs_windowed``) keeps a
window of ``max_in_flight`` whole block CHAINS in flight: memory is
bounded only in block *count*, a slow operator's backlog is invisible
(everything upstream keeps running until the window fills), and
heterogeneous per-operator resources cannot be expressed.  This engine
replaces that with:

- **Compilation + fusion** — the logical ``ops`` tuple compiles into a
  chain of physical operators.  Consecutive task-compute ops
  (map / filter / flat_map / map_batches) whose resource requests match
  fuse into a single ``_MapOperator`` — one task per block per fused
  chain instead of one per op.  ``compute="actors"`` ops become
  ``_ActorOperator`` stages over a lazily-created actor pool and never
  fuse across the boundary.
- **Byte-budgeted admission** — every completed block's size rides the
  per-op stats the task already returns (cross-checked against the
  ``("shm", name, size, store_id)`` descriptor when the driver's object
  table is reachable); the dispatch loop admits a new task only while
  *queued intermediate bytes + estimated in-flight output bytes* stay
  under ``config.data_memory_budget`` (default: a fraction of the
  object-store capacity; env ``RAY_TPU_DATA_MEMORY_BUDGET``).
- **Backpressure by construction** — on every completion the loop picks
  the runnable operator with the *smallest queued output bytes* (ties
  to the deeper operator), so a fast upstream operator stalls when its
  consumer lags instead of flooding the store, while independent
  operators (different chains of a ``union``, different pipeline
  stages) pipeline freely.
- **Failure isolation** — a task error surfaces to the consumer
  immediately and every outstanding task is cancelled (the legacy path
  left the rest of the window running).

The executor is *driven entirely by the consuming generator's thread*:
operator queues, in-flight maps and byte accounting are single-threaded
state and need no locks.

``ShuffleOperator`` (bottom of this module) is the driver-side
coordinator of the push-based all-to-all shuffle (``data/shuffle.py``):
it plans reducer placement, fans the map wave out through
``_bulk_submit``, forwards partition *descriptors* to reducer actors as
each map completes (merge-on-arrival — reducers never wait for the full
map wave), and rebuilds a lost reducer from per-partition re-maps.  It
is driven entirely by the consuming thread and holds no locks; the only
shuffle lock is the counter leaf in ``data/shuffle.py``.

LOCK ORDER: ``StreamingStats._lock`` is an independent LEAF — it guards
only the counter snapshot read by ``Dataset.stats()`` (potentially from
another thread, mid-stream); no other lock is ever acquired while
holding it and it is never held across task submission, ``ray.wait`` or
``ray.get``.  Pinned in tests/test_lockcheck.py alongside the
object_transfer / shm_store leaves.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu as ray
from ray_tpu.data import execution as _ex

# Per-op resource opts dict appended to task-compute op tuples by
# Dataset.map/.filter/.flat_map/.map_batches(num_cpus=...).  Absent on
# ops built by older call sites — `_op_opts` treats both the same.
def _op_opts(op) -> dict:
    return op[-1] if isinstance(op[-1], dict) else {}


def _strip_opts(op) -> tuple:
    return op[:-1] if isinstance(op[-1], dict) else op


class StreamingStats:
    """Engine-level counters behind ``Dataset.stats()`` (surfaced like
    ``Runtime.transfer_stats()``: a flat snapshot dict plus per-operator
    rows).  All mutation happens on the executor's driving thread; the
    leaf ``_lock`` only makes snapshots consistent for concurrent
    readers."""

    def __init__(self, budget_bytes: int, inflight_cap: int):
        self._lock = threading.Lock()  # lock-order: leaf (see module docstring)
        self.budget_bytes = budget_bytes
        self.inflight_cap = inflight_cap
        self.peak_inflight_bytes = 0
        self.admitted_tasks = 0
        self.completed_tasks = 0
        self.cancelled_tasks = 0
        self.backpressure_stalls = 0
        self.ops: Dict[str, Dict[str, int]] = {}

    def op_row(self, name: str) -> Dict[str, int]:
        with self._lock:
            return self.ops.setdefault(name, {
                "queued_blocks": 0, "queued_bytes": 0,
                "peak_queued_bytes": 0, "inflight": 0,
                "peak_inflight": 0, "out_blocks": 0, "out_bytes": 0,
            })

    def note_live_bytes(self, live: int):
        with self._lock:
            if live > self.peak_inflight_bytes:
                self.peak_inflight_bytes = live

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "inflight_cap": self.inflight_cap,
                "peak_inflight_bytes": self.peak_inflight_bytes,
                "admitted_tasks": self.admitted_tasks,
                "completed_tasks": self.completed_tasks,
                "cancelled_tasks": self.cancelled_tasks,
                "backpressure_stalls": self.backpressure_stalls,
                "ops": {k: dict(v) for k, v in self.ops.items()},
            }


def empty_summary() -> Dict[str, Any]:
    """The all-zero snapshot the legacy path reports (acceptance: with
    ``streaming_executor=off`` every new counter is zero).  Derived from
    a fresh ``StreamingStats`` so the two paths can never diverge in
    shape."""
    return StreamingStats(0, 0).summary()


# ------------------------------------------------------------- operators --
class _MapOperator:
    """A fused chain of task-compute ops: one ``apply_stage_with_stats``
    task per block, honoring the chain's (shared) resource request."""

    kind = "tasks"

    def __init__(self, ops: Tuple[tuple, ...], opts: dict):
        self.ops = tuple(_strip_opts(op) for op in ops)
        self.opts = dict(opts)
        self.name = "+".join(op[0] for op in self.ops)
        self._handle = (_ex.apply_stage_with_stats.options(**self.opts)
                        if self.opts else _ex.apply_stage_with_stats)

    def submit(self, block_ref):
        bref, sref = self._handle.remote(self.ops, block_ref)
        return bref, sref, None

    def on_done(self, note):
        pass

    def shutdown(self):
        pass


class _ActorOperator:
    """An actor-pool stage (``compute="actors"``); the pool is created on
    first admission so empty datasets never spawn actors."""

    kind = "actors"

    def __init__(self, op: tuple):
        self._op = op
        self.name = "map_batches(actors)"
        self._pool: Optional[_ex.ActorPoolMapOperator] = None

    def submit(self, block_ref):
        if self._pool is None:
            self._pool = _ex.ActorPoolMapOperator(
                self._op[1], self._op[2], self._op[3])
        bref, sref, idx = self._pool.submit((), block_ref)
        return bref, sref, idx

    def on_done(self, note):
        if self._pool is not None and note is not None:
            self._pool.done(note)

    def shutdown(self):
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None


def compile_chain(ops: tuple, pools: Dict[int, _ActorOperator]) -> List[Any]:
    """Logical op tuple -> physical operator chain.  Fusion rule:
    consecutive task ops with the same NORMALIZED resource request fuse
    (the scheduler's `_normalize_resources`, so an explicit ``num_cpus=1``
    and the unannotated 1-CPU default are one chain); actor ops are
    their own stage (shared across segments carrying the identical op
    object, e.g. after ``union`` of one transformed dataset).  Actor
    boundaries come from the legacy path's ``split_stages`` — ONE
    boundary-splitting implementation for both engines — and only the
    resource-key subdivision of task stages is engine-specific."""
    from ray_tpu.remote_function import _normalize_resources

    operators: List[Any] = []
    for kind, stage in _ex.split_stages(ops):
        if kind == "actors":
            shared = pools.get(id(stage))
            if shared is None:
                shared = pools[id(stage)] = _ActorOperator(stage)
            operators.append(shared)
            continue
        cur: List[tuple] = []
        cur_opts: dict = {}
        cur_key: Optional[tuple] = None
        for op in stage:
            opts = _op_opts(op)
            key = tuple(sorted(_normalize_resources(opts).items()))
            if cur and key != cur_key:
                operators.append(_MapOperator(tuple(cur), cur_opts))
                cur, cur_opts = [], {}
            cur.append(op)
            cur_key = key
            # The sub-chain submits under the first annotated op's opts
            # (all members normalize identically, so any one is the
            # request).
            cur_opts = cur_opts or dict(opts)
        if cur:
            operators.append(_MapOperator(tuple(cur), cur_opts))
    return operators


class _OpState:
    """Runtime state of one physical operator instance within one chain:
    input queue, in-flight task set, queued-output accounting."""

    __slots__ = ("op", "row", "depth", "prev", "next", "inq", "inq_bytes",
                 "inflight", "queued_out_bytes", "out_sum", "out_n")

    def __init__(self, op, row, depth):
        self.op = op
        self.row = row          # StreamingStats row dict
        self.depth = depth
        self.prev: Optional[_OpState] = None
        self.next: Optional[_OpState] = None
        # (seq, ref, nbytes, counted) — `counted` marks executor-produced
        # blocks, whose bytes are charged to the budget until their
        # consuming task completes; source blocks (which exist whether or
        # not the executor runs) are sized for estimates/stats only.
        self.inq: deque = deque()
        self.inq_bytes = 0
        # head block ref -> (seq, stats_ref, input_ref, in_bytes,
        #                    in_counted, est_out, pool_note)
        self.inflight: Dict[Any, tuple] = {}
        self.queued_out_bytes = 0
        self.out_sum = 0        # completed output bytes (for estimates)
        self.out_n = 0

    def est_out_bytes(self) -> int:
        """Expected output size of the next admitted task: the running
        mean of completed outputs, else the input block's size (all we
        know before the first completion)."""
        if self.out_n:
            return self.out_sum // self.out_n
        return self.inq[0][2] if self.inq else 0


# --------------------------------------------------------------- budgets --
def resolve_budget(rt, cfg) -> int:
    if cfg.data_memory_budget:
        return int(cfg.data_memory_budget)
    shm = getattr(rt, "shm", None)
    cap = int(getattr(shm, "_capacity", 0) or 0) if shm is not None else 0
    if not cap and shm is not None:
        try:
            st = os.statvfs(shm._dir)
            cap = st.f_frsize * st.f_blocks
        except (OSError, AttributeError):
            cap = 0
    if not cap:
        cap = 1 << 32  # no readable store bound: 4 GB stand-in
    return max(1, int(cap * cfg.data_memory_budget_fraction))


def resolve_inflight_cap(rt, cfg) -> int:
    if cfg.data_max_inflight_tasks:
        return int(cfg.data_max_inflight_tasks)
    try:
        total = rt.cluster_resources().get("CPU", 0)
    except Exception:
        total = 0
    return max(1, int(total)) if total else 8


def _descr_nbytes_many(rt, refs) -> List[int]:
    """Block sizes from the driver's object table (the size every
    shm/spilled descriptor carries) for all ``refs`` under ONE
    acquisition of the driver-wide runtime lock — stream setup sizes
    every source block and every completion round settles in one pass,
    so a 10k-block dataset never takes the contended lock 10k times.
    All-zero when unreadable (worker/client runtimes keep no table —
    callers fall back to stats-reported bytes)."""
    descrs: List[Any] = [None] * len(refs)
    try:
        with rt.lock:
            for i, ref in enumerate(refs):
                st = rt.objects.get(ref.id())
                descrs[i] = st.descr if st is not None else None
    except Exception:
        return [0] * len(refs)
    sizes = []
    for d in descrs:
        if d is not None and d[0] in ("shm", "spilled"):
            sizes.append(int(d[2]))
        elif d is not None and d[0] == "inline":
            sizes.append(len(d[1]))
        else:
            sizes.append(0)
    return sizes


# -------------------------------------------------------------- executor --
def execute(segments, rt, cfg, dstats, window=None):
    """Yield executed block refs of ``segments`` in order — the streaming
    replacement for the windowed chain submission.  ``dstats`` is the
    Dataset's ``DatasetStats``; per-op rows accumulate there and the
    engine snapshot attaches as ``dstats.streaming``.  ``window`` is the
    caller's legacy-shaped concurrency hint (``materialize`` opens it to
    the block count, ``iter_batches`` to ``prefetch_blocks``): it can
    RAISE the in-flight task cap above the auto default, while the byte
    budget still bounds memory."""
    budget = resolve_budget(rt, cfg)
    cap = resolve_inflight_cap(rt, cfg)
    if not cfg.data_max_inflight_tasks:
        # The window hint only widens the AUTO cap; an explicitly
        # configured task cap is a hard bound, like an explicit budget.
        cap = max(cap, int(window or 0))
    # An explicitly configured budget is a HARD bound: operators whose
    # output size is still unknown run one task at a time (an output-size
    # probe) so a first wave of admissions cannot collectively overshoot.
    # The auto budget (a store-capacity fraction) stays optimistic —
    # input-size estimates, full first-wave fan-out.
    strict = bool(cfg.data_memory_budget)
    stats = StreamingStats(budget, cap)
    dstats.streaming = stats

    # ---- compile ----
    pools: Dict[int, _ActorOperator] = {}
    states: List[_OpState] = []
    final_buf: Dict[int, tuple] = {}   # seq -> (ref, nbytes, producer)
    chain_heads: List[Optional[_OpState]] = []
    seen_names: Dict[str, int] = {}
    for blocks, ops in segments:
        operators = compile_chain(ops, pools)
        chain: List[_OpState] = []
        for depth, op in enumerate(operators):
            n = seen_names.get(op.name, 0)
            seen_names[op.name] = n + 1
            row_name = op.name if n == 0 else f"{op.name}#{n}"
            st = _OpState(op, stats.op_row(row_name), depth)
            if chain:
                chain[-1].next = st
                st.prev = chain[-1]
            chain.append(st)
        states.extend(chain)
        chain_heads.append(chain[0] if chain else None)

    source = [(head, b)
              for (blocks, _ops), head in zip(segments, chain_heads)
              for b in blocks]
    sizes = _descr_nbytes_many(rt, [b for _, b in source])
    for seq, ((head, b), nb) in enumerate(zip(source, sizes)):
        if head is None:
            final_buf[seq] = (b, 0, None)
        else:
            head.inq.append((seq, b, nb, False))
            head.inq_bytes += nb
    total_blocks = len(source)

    live = {"bytes": 0, "inflight": 0}
    # Largest single completed output so far: ordinary admissions keep
    # this much headroom under the budget, so the forced-progress
    # admission (which may not respect the budget) still lands within
    # it — the engine's bound is then `peak <= budget` whenever the
    # budget covers one downstream working set (in + out + one queued
    # block); blocks that keep GROWING along the pipeline can still
    # overshoot by at most one block.
    headroom = {"v": 0}
    owner: Dict[Any, _OpState] = {}   # in-flight head ref -> opstate
    next_yield = 0

    def _admit():
        """Admit tasks until budget/cap/backpressure stops them.
        Operator choice is backpressure by construction: the runnable
        operator with the SMALLEST queued output bytes goes first (ties
        to the deeper one), so producers whose consumers lag wait.  When
        nothing at all is in flight the first admission ignores the
        budget — a single block larger than the budget must still make
        progress."""
        while True:
            if live["inflight"] >= cap:
                return
            cands = [s for s in states if s.inq]
            if not cands:
                return
            if live["inflight"] == 0:
                # Forced progress: nothing runs, so the budget cannot be
                # respected without deadlock.  Admit the operator whose
                # queue holds the OLDEST block — the one blocking the
                # next ordered yield — so the overshoot is the minimum
                # that restores progress (at most one task's footprint).
                s = min(cands, key=lambda s: s.inq[0][0])
                est = s.est_out_bytes()
            else:
                s = None
                for cand in sorted(cands, key=lambda s:
                                   (s.queued_out_bytes, -s.depth)):
                    if strict and cand.out_n == 0 and cand.inflight:
                        continue  # output-size probe still outstanding
                    s = cand
                    break
                if s is None:
                    return
                est = s.est_out_bytes()
                if live["bytes"] + est > budget - headroom["v"]:
                    with stats._lock:
                        stats.backpressure_stalls += 1
                    return
            sq, in_ref, in_bytes, counted = s.inq.popleft()
            s.inq_bytes -= in_bytes
            if s.prev is not None:
                s.prev.queued_out_bytes -= in_bytes
            bref, sref, note = s.op.submit(in_ref)
            s.inflight[bref] = (sq, sref, in_ref, in_bytes, counted,
                                est, note)
            owner[bref] = s
            live["bytes"] += est
            live["inflight"] += 1
            with stats._lock:
                stats.admitted_tasks += 1
                s.row["inflight"] += 1
                s.row["peak_inflight"] = max(s.row["peak_inflight"],
                                             s.row["inflight"])
                s.row["queued_blocks"] = len(s.inq)
                s.row["queued_bytes"] = s.inq_bytes
            stats.note_live_bytes(live["bytes"])

    def _complete_batch(brefs):
        """Settle one wait round's completions: ONE object-table pass
        for exact sizes and ONE ``ray.get`` over the stats refs (which
        raises the first task error — the engine then cancels), instead
        of a driver-lock acquisition + blocking get per task."""
        recs = []
        for bref in brefs:
            s = owner.pop(bref)
            rec = s.inflight.pop(bref)
            s.op.on_done(rec[-1])
            recs.append((bref, s, rec))
        sizes = _descr_nbytes_many(rt, brefs)
        all_stats = ray.get([rec[1] for _, _, rec in recs])
        for (bref, s, rec), nbytes, block_stats in zip(recs, sizes,
                                                       all_stats):
            _settle(bref, s, rec, nbytes, block_stats)

    def _settle(bref, s, rec, nbytes, block_stats):
        sq, sref, in_ref, in_bytes, counted, est, note = rec
        # Exact store-descriptor size first — the UDF-side stats bytes
        # are a heuristic (rows-of-dicts estimate at 64 B/row) and an
        # explicit budget must not be enforced against a number that can
        # undercount by orders of magnitude.  The stats figure covers
        # inlined blocks and worker/client runtimes (no object table).
        if not nbytes:
            nbytes = int(block_stats[-1].get("bytes_out", 0)) \
                if block_stats else 0
        dstats.add_stats(block_stats)
        s.out_sum += nbytes
        s.out_n += 1
        headroom["v"] = max(headroom["v"], nbytes)
        # The consumed input ref is dropped here (the last executor
        # reference): the intermediate block's store bytes free now.
        del in_ref
        live["bytes"] += nbytes - est - (in_bytes if counted else 0)
        live["inflight"] -= 1
        s.queued_out_bytes += nbytes
        if s.next is not None:
            s.next.inq.append((sq, bref, nbytes, True))
            s.next.inq_bytes += nbytes
            with stats._lock:
                s.next.row["queued_blocks"] = len(s.next.inq)
                s.next.row["queued_bytes"] = s.next.inq_bytes
                s.next.row["peak_queued_bytes"] = max(
                    s.next.row["peak_queued_bytes"], s.next.inq_bytes)
        else:
            final_buf[sq] = (bref, nbytes, s)
        with stats._lock:
            stats.completed_tasks += 1
            s.row["inflight"] -= 1
            s.row["out_blocks"] += 1
            s.row["out_bytes"] += nbytes
            s.row["peak_queued_bytes"] = max(s.row["peak_queued_bytes"],
                                             s.row["queued_bytes"])
        stats.note_live_bytes(live["bytes"])

    def _cancel_outstanding():
        for s in states:
            for bref in list(s.inflight):
                note = s.inflight.pop(bref)[-1]
                s.op.on_done(note)
                owner.pop(bref, None)
                try:
                    done, _ = ray.wait([bref], num_returns=1, timeout=0)
                    finished = bool(done)
                except Exception:
                    finished = False
                try:
                    ray.cancel(bref)
                except Exception:
                    pass  # worker/client mode or already finished
                if not finished:
                    # Count only tasks that were genuinely cut short;
                    # a task that completed while we were tearing down
                    # was not cancelled, its result is just unread.
                    with stats._lock:
                        stats.cancelled_tasks += 1
            s.inq.clear()
        for pool in pools.values():
            pool.shutdown()

    try:
        while next_yield < total_blocks:
            while next_yield in final_buf:
                ref, nbytes, producer = final_buf.pop(next_yield)
                live["bytes"] -= nbytes
                if producer is not None:
                    producer.queued_out_bytes -= nbytes
                next_yield += 1
                yield ref
            if next_yield >= total_blocks:
                break
            _admit()
            heads = list(owner)
            if not heads:
                # The drain loop above already emptied every consecutive
                # final_buf entry and _admit() found nothing runnable:
                # this is a genuine stall, never a recoverable state.
                raise RuntimeError(
                    "streaming executor stalled: no runnable operator "
                    f"and no in-flight work at block {next_yield}/"
                    f"{total_blocks}")
            done, rest = ray.wait(heads, num_returns=1, timeout=None)
            if rest:
                more, _ = ray.wait(rest, num_returns=len(rest), timeout=0)
                done.extend(more)
            _complete_batch(done)
    finally:
        _cancel_outstanding()


class ShuffleOperator:
    """Driver-side coordinator for the push-based all-to-all shuffle.

    The operator owns the *plan* — how many reducers, where they live,
    which map produces which partition — while all data movement happens
    worker-to-worker through the striped put verbs (``data/shuffle.py``).
    Only descriptors (a few dozen bytes per partition) ever transit the
    head.  ``run`` returns ``(out_refs, summary)`` on success or ``None``
    when no plan could be formed (no alive nodes, or — for sort — no
    sample keys); the caller then falls back to the legacy pull path.

    Fault story:

    * a dead **map** task is re-run by the ordinary task-retry machinery
      (``max_retries``), with its input block rebuilt through lineage;
    * a partition whose *home* store died is re-materialised from the
      map hedge copy or, failing that, triggers the same lineage path;
    * a dead/stuck **reducer** is rebuilt on a different node from
      per-partition re-maps (``only_parts``) — bounded rounds, counted
      in ``shuffle_hedges``.
    """

    MAX_REBUILD_ROUNDS = 2
    SAMPLES_PER_BLOCK = 16

    def __init__(self, spec, rt, cfg):
        self.spec = spec
        self.rt = rt
        self.cfg = cfg

    # -- planning -----------------------------------------------------

    def _sort_bounds(self, blocks, num_reducers):
        """Sample keys and compute the R-1 decorated range boundaries.

        Identical sampling (``_sample_block``, 16 evenly spaced rows per
        block) and identical boundary *positions* to the legacy sort, so
        push on/off produce byte-identical output.  Returns False when
        no keys were sampled (all blocks empty) — caller falls back.
        """
        from ray_tpu.data import dataset as _ds
        from ray_tpu.data import shuffle as _sh
        from ray_tpu.remote_function import _bulk_submit

        refs = _bulk_submit([
            (_ds._sample_block, (b, self.SAMPLES_PER_BLOCK, self.spec.key),
             None)
            for b in blocks])
        samples = ray.get(refs)
        flat = sorted((s for part in samples for s in part),
                      key=_sh._none_key)
        if not flat:
            return False
        self.spec.bounds = [
            _sh._none_key(flat[len(flat) * (i + 1) // num_reducers])
            for i in range(num_reducers - 1)]
        return True

    # -- reducer lifecycle --------------------------------------------

    def _spawn_reducer(self, idx, node_hex):
        from ray_tpu.data import shuffle as _sh
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy as NA)

        return _sh._ShuffleReducer.options(
            scheduling_strategy=NA(node_id=node_hex, soft=True),
        ).remote(self.spec, idx)

    def _rebuild_reducer(self, j, blocks, targets):
        """Stand up a replacement for reducer ``j`` and re-feed it.

        Re-runs every map for partition ``j`` only (``only_parts``) so
        the re-map wave moves 1/R of the shuffle, not all of it, and
        points the fresh partitions at the replacement's store.
        """
        from ray_tpu.data import shuffle as _sh
        from ray_tpu.remote_function import _bulk_submit

        alive = _sh.reduce_targets(self.rt, len(targets))
        if not alive:
            raise RuntimeError("push shuffle: no alive node to rebuild "
                               f"reducer {j} on")
        bad = targets[j]
        pick = next((t for t in alive if t != bad), alive[j % len(alive)])
        targets[j] = pick
        _sh.note("shuffle_hedges")
        actor = self._spawn_reducer(j, pick[0])
        stores = [s for _nid, s in targets]
        refs = _bulk_submit([
            (_sh._shuffle_map_push, (b, self.spec, i, stores, (j,)), None)
            for i, b in enumerate(blocks)])
        accepts = []
        for i, descrs in enumerate(ray.get(refs)):
            accepts.append(actor.accept.remote(i, descrs[j]))
        return actor, accepts

    # -- the shuffle itself -------------------------------------------

    def run(self, blocks):
        from ray_tpu._private import recovery
        from ray_tpu.data import shuffle as _sh
        from ray_tpu.remote_function import _bulk_submit

        blocks = list(blocks)
        n = len(blocks)
        sizes = _descr_nbytes_many(self.rt, blocks)
        num_r = _sh.pick_reducer_count(
            self.cfg, n, sum(sizes), self.spec.mode)
        self.spec.merge_fanin = max(
            2, int(getattr(self.cfg, "shuffle_merge_fanin", 8)))
        targets = _sh.reduce_targets(self.rt, num_r)
        if not targets:
            return None
        if self.spec.mode == "sort" and not self._sort_bounds(blocks, num_r):
            return None

        stores = [s for _nid, s in targets]
        reducers = [self._spawn_reducer(j, nid)
                    for j, (nid, _s) in enumerate(targets)]
        map_refs = _bulk_submit([
            (_sh._shuffle_map_push, (b, self.spec, i, stores), None)
            for i, b in enumerate(blocks)])
        recovery.syncpoint("shuffle:maps_submitted", maps=n, reducers=num_r)

        # Merge-on-arrival: forward each map's descriptors the moment the
        # map lands; reducers fold/merge concurrently with later maps.
        accept_refs = [[] for _ in range(num_r)]
        pushed_bytes = spills = hedges = 0
        pending = {ref: i for i, ref in enumerate(map_refs)}
        while pending:
            done, rest = ray.wait(list(pending), num_returns=1, timeout=None)
            if rest:
                more, _ = ray.wait(rest, num_returns=len(rest), timeout=0)
                done.extend(more)
            for ref in done:
                i = pending.pop(ref)
                descrs = ray.get(ref)  # raises after retries exhausted
                for j, d in enumerate(descrs):
                    if d is None:
                        continue
                    pushed_bytes += d[2]
                    spills += 1 if d[0] == "spilled" else 0
                    hedges += 1 if d[5] else 0
                    accept_refs[j].append(reducers[j].accept.remote(i, d))

        # Actor calls from one submitter run in order, so a finalize
        # queued now executes only after every accept above — dispatch
        # all finalizes up front and let the R merges finish in parallel.
        final_refs = [r.finalize.remote() for r in reducers]

        merges = 0
        outs: List[Any] = [None] * num_r
        for j in range(num_r):
            err = None
            for _round in range(self.MAX_REBUILD_ROUNDS + 1):
                try:
                    # A failed accept leaves finalize's output silently
                    # partial — verify the accepts *before* trusting it.
                    ray.get(accept_refs[j])
                    ray.wait([final_refs[j]], num_returns=1, timeout=None)
                    # Tiny liveness probe: surfaces a reducer that died
                    # mid-finalize without pulling the output block here.
                    rstats = ray.get(reducers[j].stats.remote())
                    merges += rstats.get("merges", 0)
                    outs[j] = final_refs[j]
                    err = None
                    break
                except Exception as e:  # noqa: BLE001 - rebuild on any loss
                    err = e
                    reducers[j], accept_refs[j] = self._rebuild_reducer(
                        j, blocks, targets)
                    final_refs[j] = reducers[j].finalize.remote()
                    hedges += 1
            if err is not None:
                raise err

        for r in reducers:
            # Drop zero-copy segment pins; outputs are materialised.
            r.release.remote()

        summary = {
            "maps": n,
            "reducers": num_r,
            "shuffle_pushed_bytes": pushed_bytes,
            "shuffle_merges": merges,
            "shuffle_spills": spills,
            "shuffle_hedges": hedges,
        }
        return outs, summary
