"""Windowed/repeated dataset pipelines.

Reference: ``python/ray/data/dataset_pipeline.py`` — a DatasetPipeline is
a sequence of Datasets (windows) executed one window at a time, so a
training loop streams through data larger than the object store instead
of materializing it all.  Transforms apply lazily per window; iteration
drives exactly one window's tasks at a time, and within a window the
operator-graph streaming executor bounds in-flight BYTES (legacy path:
in-flight block count) — see streaming_executor.py.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List

from ray_tpu.data.dataset import Dataset


class DatasetPipeline:
    def __init__(self, windows: List[Dataset]):
        self._windows = list(windows)

    # ------------------------------------------------------- transforms --
    def _map_windows(self, f: Callable[[Dataset], Dataset]
                     ) -> "DatasetPipeline":
        return DatasetPipeline([f(w) for w in self._windows])

    def map(self, fn, *, num_cpus=None) -> "DatasetPipeline":
        return self._map_windows(lambda w: w.map(fn, num_cpus=num_cpus))

    def filter(self, fn, *, num_cpus=None) -> "DatasetPipeline":
        return self._map_windows(lambda w: w.filter(fn, num_cpus=num_cpus))

    def flat_map(self, fn, *, num_cpus=None) -> "DatasetPipeline":
        return self._map_windows(
            lambda w: w.flat_map(fn, num_cpus=num_cpus))

    def map_batches(self, fn, *, batch_format: str = "numpy",
                    compute=None, concurrency: int = 2,
                    num_cpus=None) -> "DatasetPipeline":
        return self._map_windows(
            lambda w: w.map_batches(fn, batch_format=batch_format,
                                    compute=compute,
                                    concurrency=concurrency,
                                    num_cpus=num_cpus))

    def stats(self) -> str:
        """Concatenated per-window execution stats (reference:
        DatasetPipeline.stats)."""
        return "\n".join(
            f"== window {i} ==\n{w.stats()}"
            for i, w in enumerate(self._windows))

    def random_shuffle_each_window(self, *, seed=None) -> "DatasetPipeline":
        return self._map_windows(lambda w: w.random_shuffle(seed=seed))

    def repeat(self, times: int = 1) -> "DatasetPipeline":
        return DatasetPipeline(self._windows * times)

    # -------------------------------------------------------- consumers --
    def iter_datasets(self) -> Iterator[Dataset]:
        yield from self._windows

    def iter_rows(self) -> Iterator[Any]:
        for w in self._windows:
            yield from w.iter_rows()

    def iter_batches(self, *, batch_size: "int | None" = 256,
                     batch_format: str = "numpy",
                     prefetch_blocks: int = 2) -> Iterator[Any]:
        for w in self._windows:
            yield from w.iter_batches(batch_size=batch_size,
                                      batch_format=batch_format,
                                      prefetch_blocks=prefetch_blocks)

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Shard each window for n consumers (reference:
        dataset_pipeline.py split)."""
        per_window = [w.split(n) for w in self._windows]
        return [DatasetPipeline([pw[i] for pw in per_window])
                for i in range(n)]

    def count(self) -> int:
        return sum(w.count() for w in self._windows)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for w in self._windows:
            out.extend(w.take(n - len(out)))
            if len(out) >= n:
                break
        return out[:n]

    def __repr__(self):
        return f"DatasetPipeline(num_windows={len(self._windows)})"
