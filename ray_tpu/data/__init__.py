"""ray_tpu.data — distributed data loading & transform (Ray Data equivalent).

Reference: ``python/ray/data/`` (SURVEY.md §2.3, 35k LoC) — Dataset over
Arrow blocks living in the object store, lazy ExecutionPlan, bulk + streaming
executors, datasource plugins, split() feeding Train shards.

Condensation here: blocks are object-store refs holding lists-of-rows or
dict-of-numpy "tensor blocks"; the plan is a lazy op chain executed by a
bulk executor (one task per block per op — streaming executor is a later
round); IO goes through pyarrow (parquet/csv/json).  The Train integration
contract is the same: ``ds.split(k)`` -> per-worker shards,
``shard.iter_batches()`` inside the train loop.
"""

from ray_tpu.data.dataset import (
    Dataset,
    from_items,
    from_numpy,
    from_pandas,
    range as range_,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)

# `range` shadows the builtin inside this namespace on purpose — the
# reference exposes ray.data.range the same way.
range = range_

__all__ = [
    "Dataset", "from_items", "from_numpy", "from_pandas", "range",
    "read_csv", "read_json", "read_parquet", "read_text",
]
