"""ray_tpu.data — distributed data loading & transform (Ray Data equivalent).

Reference: ``python/ray/data/`` (SURVEY.md §2.3, 35k LoC) — Dataset over
Arrow blocks living in the object store, lazy ExecutionPlan, bulk + streaming
executors, datasource plugins, split() feeding Train shards.

Condensation here: blocks are object-store refs holding lists-of-rows,
dict-of-numpy "tensor blocks", or pyarrow Tables; transforms build a lazy
plan compiled into a DAG of fused physical operators and executed by the
backpressured streaming engine (``data/streaming_executor.py`` —
per-operator queues, global in-flight byte budget; the
``streaming_executor.py:35`` analog, legacy windowed path behind
``config.streaming_executor=off``); split/repartition plan row ranges
and cut blocks with tasks (no driver materialization); IO goes through
pyarrow (parquet/csv/json).  The Train integration contract is the same:
``ds.split(k)`` -> per-worker shards, ``shard.iter_batches()`` inside the
train loop.
"""

from ray_tpu.data.dataset import (
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range as range_,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data.grouped_dataset import (
    AggregateFn, Count, GroupedDataset, Max, Mean, Min, Std, Sum,
)

# `range` shadows the builtin inside this namespace on purpose — the
# reference exposes ray.data.range the same way.
range = range_

__all__ = [
    "Dataset", "DatasetPipeline", "GroupedDataset", "AggregateFn",
    "Count", "Sum", "Min", "Max", "Mean", "Std",
    "from_arrow", "from_items", "from_numpy", "from_pandas",
    "range", "read_csv", "read_json", "read_parquet", "read_text",
]
