"""Dataset: lazy fused-op plan over object-store blocks, executed by a
backpressured streaming operator-graph engine.

Reference: ``python/ray/data/dataset.py:166`` (4.5k LoC Dataset),
``_internal/plan.py`` (ExecutionPlan), and the streaming executor
(``_internal/execution/streaming_executor.py:35``).  Three properties kept
from the reference's model, re-designed small:

- **Lazy plan + operator fusion**: transforms append ops to a plan; at
  execution the plan compiles to physical operators, consecutive
  compatible map-like ops fusing into one task per block (the reference
  fuses the same way; per-op ``num_cpus`` is a fusion boundary).
- **Streaming with backpressure**: consumers pull block refs through the
  operator-graph executor (``streaming_executor.py``): per-operator
  input/output queues, admission under a global in-flight BYTE budget
  (``config.data_memory_budget``), and slowest-consumer-first dispatch,
  so a dataset larger than driver RAM streams through with peak store
  bytes bounded.  ``config.streaming_executor=off`` falls back to the
  legacy windowed chain-submission path (at most ``max_in_flight``
  whole-chain block tasks; memory bounded in block count only).
- **No driver materialization for layout ops**: ``split``/``repartition``
  plan row ranges from per-block counts and cut blocks with tasks —
  rows move store-to-store, never through the driver (the round-2
  ``take_all`` versions bounded pipelines by driver RAM).

Blocks are lists of rows, dict-of-numpy "tensor blocks", or
``pyarrow.Table`` (tabular zero-copy path, ``_internal/arrow_block.py``
analog).
"""

from __future__ import annotations

import builtins
import itertools
from collections import deque
from typing import Any, Callable, Iterator, List, Optional, Union

import numpy as np

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit


# --------------------------------------------------------------- block ops
# A block is a list of rows (dicts or scalars), a dict-of-numpy arrays
# ("tensor block"), or a pyarrow.Table.  Ops below run inside tasks.

def _is_arrow(block) -> bool:
    try:
        import pyarrow as pa

        return isinstance(block, pa.Table)
    except ImportError:
        return False


def _block_len(block) -> int:
    if _is_arrow(block):
        return block.num_rows
    if isinstance(block, dict):
        for v in block.values():
            return len(v)
        return 0
    return len(block)


def _block_rows(block) -> Iterator[Any]:
    if _is_arrow(block):
        yield from block.to_pylist()
        return
    if isinstance(block, dict):
        keys = list(block)
        for i in builtins.range(_block_len(block)):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block


def _slice_rows(block, start: int, stop: int):
    """Row-range cut of any block kind, zero-copy where the format allows
    (arrow slice / numpy views)."""
    if _is_arrow(block):
        return block.slice(start, stop - start)
    if isinstance(block, dict):
        return {k: v[start:stop] for k, v in block.items()}
    return block[start:stop]


def _format_batch(rows: List[Any], batch_format: str):
    if batch_format == "numpy":
        if rows and isinstance(rows[0], dict):
            return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        return np.asarray(rows)
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame(rows)
    if batch_format == "pyarrow":
        import pyarrow as pa

        return pa.Table.from_pylist(rows)
    return rows


def _native_batch(block, batch_format: str):
    """The block itself when it already IS a valid batch of
    ``batch_format`` (the zero-copy pass-through both map_batches and
    iter_batches use), else None.  Numpy tensor batches are marked
    read-only before crossing to the consumer: they may be views over
    the shared store (or the driver's value cache), so an in-place
    mutation would silently corrupt every later read — the reference
    marks plasma-backed arrays the same way."""
    if batch_format == "pyarrow" and _is_arrow(block):
        return block
    if batch_format == "numpy" and isinstance(block, dict) and all(
            isinstance(v, np.ndarray) for v in block.values()):
        for v in block.values():
            v.setflags(write=False)
        return block
    return None


def _apply_op(op, block):
    """One fused-plan step applied to a whole block (runs inside a task)."""
    kind, arg = op[0], op[1]
    if kind == "map":
        return [arg(r) for r in _block_rows(block)]
    if kind == "filter":
        return [r for r in _block_rows(block) if arg(r)]
    if kind == "flat_map":
        out = []
        for r in _block_rows(block):
            out.extend(arg(r))
        return out
    if kind == "map_batches":
        batch_format = op[2]
        # Fast paths keep the native block kind (no row materialization).
        # Deliberately NOT _native_batch: UDF inputs stay writable —
        # in-task mutation of an inline batch is harmless (the task owns
        # it), while consumer-facing iter_batches marks them read-only.
        if batch_format == "pyarrow" and _is_arrow(block):
            batch = block
        elif batch_format == "numpy" and isinstance(block, dict):
            batch = block
        else:
            batch = _format_batch(list(_block_rows(block)), batch_format)
        out = arg(batch)
        # Any block kind may come back: arrow Table, dict-of-numpy, list,
        # ndarray, or DataFrame.
        if _is_arrow(out) or isinstance(out, dict):
            return out
        try:
            import pandas as pd

            if isinstance(out, pd.DataFrame):
                return out.to_dict("records")
        except ImportError:
            pass
        if isinstance(out, np.ndarray):
            return list(out)
        return list(out)
    raise ValueError(f"unknown op {kind!r}")


@ray.remote
def _count_block(block):
    return _block_len(block)


@ray.remote
def _slice_block(block, start, stop):
    return _slice_rows(block, start, stop)


@ray.remote
def _sort_block(block, key, descending):
    rows = list(_block_rows(block))
    keyfn = _keyfn_of(key)
    return sorted(rows, key=lambda r: _none_key(keyfn(r)),
                  reverse=descending)


@ray.remote
def _merge_sorted(key, descending, *blocks):
    import heapq

    keyfn = _keyfn_of(key)
    return list(heapq.merge(*blocks,
                            key=lambda r: _none_key(keyfn(r)),
                            reverse=descending))


def _keyfn_of(key):
    if isinstance(key, str):
        return lambda r: r[key]
    return key or (lambda r: r)


def _none_key(v):
    """None-safe sort decoration — the ``(x is None, x)`` convention
    grouped_dataset already uses for group keys, applied uniformly to
    every sort/range-partition comparison so None keys order after all
    real keys instead of raising TypeError."""
    return (v is None, v)


@ray.remote
def _sample_block(block, k, key):
    """Evenly-spaced key samples for range partitioning (reference:
    sort sampling in _internal/sort.py — sample, pick boundaries,
    partition)."""
    rows = list(_block_rows(block))
    if not rows:
        return []
    keyfn = _keyfn_of(key)
    idx = np.linspace(0, len(rows) - 1,
                      min(k, len(rows))).astype(int)
    return [keyfn(rows[int(i)]) for i in idx]


@ray.remote
def _range_partition(block, key, descending, bounds):
    """Bucket rows by the sampled boundaries: bucket i holds keys in
    (bounds[i-1], bounds[i]].  ``bounds`` are DECORATED (``_none_key``)
    so None keys bisect instead of raising.  num_returns =
    len(bounds) + 1."""
    import bisect

    keyfn = _keyfn_of(key)
    n_out = len(bounds) + 1
    buckets = [[] for _ in builtins.range(n_out)]
    for r in _block_rows(block):
        i = bisect.bisect_left(bounds, _none_key(keyfn(r)))
        if descending:
            i = n_out - 1 - i
        buckets[i].append(r)
    return buckets if n_out > 1 else buckets[0]


@ray.remote
def _sort_range(key, descending, *parts):
    rows = list(itertools.chain(*parts))
    keyfn = _keyfn_of(key)
    rows.sort(key=lambda r: _none_key(keyfn(r)), reverse=descending)
    return rows


@ray.remote
def _hash_partition(block, key, num_reducers):
    """Hash rows to reducers by group key (push-based shuffle map side)."""
    keyfn = _keyfn_of(key)
    buckets = [[] for _ in builtins.range(num_reducers)]
    for r in _block_rows(block):
        buckets[hash(keyfn(r)) % num_reducers].append(r)
    return buckets if num_reducers > 1 else buckets[0]


@ray.remote
def _zip_blocks(a, b):
    ra, rb = list(_block_rows(a)), list(_block_rows(b))
    out = []
    for x, y in zip(ra, rb):
        if isinstance(x, dict) and isinstance(y, dict):
            merged = dict(x)
            for k2, v2 in y.items():
                merged[k2 if k2 not in merged else f"{k2}_1"] = v2
            out.append(merged)
        else:
            out.append((x, y))
    return out


@ray.remote
def _shuffle_map(block, num_reducers, seed):
    rng = np.random.default_rng(seed)
    rows = list(_block_rows(block))
    assignment = rng.integers(0, num_reducers, size=len(rows))
    return [[r for r, a in zip(rows, assignment) if a == i]
            for i in builtins.range(num_reducers)]


@ray.remote
def _shuffle_reduce(seed, *parts):
    rows = list(itertools.chain(*parts))
    rng = np.random.default_rng(seed)
    rng.shuffle(rows)
    return rows


# Concurrent block tasks per consuming iterator — the streaming window
# (reference: resource-budgeted admission in streaming_executor.py:35).
DEFAULT_STREAMING_WINDOW = 8


class Dataset:
    """Immutable, lazily-transformed distributed collection.

    Internally a list of *segments* — (block_refs, fused op chain) pairs —
    so ``union`` of differently-transformed datasets stays lazy: nothing
    submits until a consumer pulls through the streaming window."""

    def __init__(self, block_refs: List[Any], ops: tuple = ()):
        self._segments: List[tuple] = [(list(block_refs), tuple(ops))]
        # Executed-block memo: consuming the same Dataset twice must not
        # re-run its UDF tasks (filled only when a consumer drains the
        # whole stream; partial reads like take/limit leave it unset).
        self._cached_refs: Optional[List[Any]] = None
        # Per-operator accounting from the last execution (ds.stats()).
        self._stats = None

    @classmethod
    def _from_segments(cls, segments: List[tuple]) -> "Dataset":
        ds = cls([])
        ds._segments = [(list(b), tuple(o)) for b, o in segments]
        return ds

    @property
    def _blocks(self) -> List[Any]:
        return [b for blocks, _ in self._segments for b in blocks]

    @property
    def _ops(self) -> tuple:
        # Uniform-plan view (tests / introspection); multi-segment datasets
        # report the first segment's ops.
        return self._segments[0][1] if self._segments else ()

    # ------------------------------------------------------------ transforms
    def _with_op(self, op) -> "Dataset":
        return Dataset._from_segments(
            [(blocks, ops + (op,)) for blocks, ops in self._segments])

    @staticmethod
    def _task_op(base: tuple, num_cpus) -> tuple:
        """Append the per-op resource opts only when requested: plan
        tuples from pre-existing call sites stay byte-identical, and the
        opts dict is both the streaming engine's fusion boundary and its
        task resource request.  The legacy windowed path fuses the whole
        chain regardless and runs it at the default 1 CPU."""
        if num_cpus is None:
            return base
        return base + ({"num_cpus": num_cpus},)

    def map(self, fn: Callable[[Any], Any], *,
            num_cpus: Optional[float] = None) -> "Dataset":
        return self._with_op(self._task_op(("map", fn), num_cpus))

    def filter(self, fn: Callable[[Any], bool], *,
               num_cpus: Optional[float] = None) -> "Dataset":
        return self._with_op(self._task_op(("filter", fn), num_cpus))

    def flat_map(self, fn: Callable[[Any], List[Any]], *,
                 num_cpus: Optional[float] = None) -> "Dataset":
        return self._with_op(self._task_op(("flat_map", fn), num_cpus))

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute: Optional[str] = None,
                    concurrency: int = 2,
                    num_cpus: Optional[float] = None) -> "Dataset":
        """``compute="actors"`` runs ``fn`` on a pool of long-lived
        actors — a CLASS fn is instantiated once per actor, carrying
        state (model weights etc.) across blocks (reference:
        execution/operators/actor_pool_map_operator.py +
        ActorPoolStrategy).  ``num_cpus`` sets the per-task CPU request
        of task-compute ops (heterogeneous per-operator resources; a
        differing request is a fusion boundary in the streaming
        engine)."""
        if compute == "actors":
            from ray_tpu.data.execution import ACTOR_OP

            if num_cpus is not None:
                raise ValueError(
                    "num_cpus applies to task compute; actor pools "
                    "reserve 1 CPU per actor")
            return self._with_op((ACTOR_OP, fn, batch_format,
                                  max(1, int(concurrency))))
        if compute not in (None, "tasks"):
            raise ValueError(f"compute must be 'tasks' or 'actors', "
                             f"got {compute!r}")
        return self._with_op(self._task_op(
            ("map_batches", fn, batch_format), num_cpus))

    # ------------------------------------------------------------- execution
    def _stream_refs(self, window: Optional[int] = None) -> Iterator[Any]:
        """Yield executed block refs in order.  Default engine: the
        backpressured operator-graph executor (streaming_executor.py) —
        fused physical operators, per-operator queues, admission under
        the ``data_memory_budget`` byte budget.  ``window`` is the
        caller's concurrency hint (``materialize`` opens it to the
        block count, ``iter_batches`` to ``prefetch_blocks``): the
        streaming engine lets an explicit window RAISE its in-flight
        task cap above the auto default (the byte budget still bounds
        memory); the legacy path (config.streaming_executor=off) keeps
        it as its chain window.  Per-op stats accumulate on ``_stats``.
        A fully-drained stream memoizes its refs."""
        if self._cached_refs is not None:
            yield from self._cached_refs
            return
        from ray_tpu._private import api_internal
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu.data import execution as _ex

        rt = api_internal.require_runtime()
        cfg = getattr(rt, "config", None) or GLOBAL_CONFIG
        if getattr(cfg, "streaming_executor", True):
            from ray_tpu.data import streaming_executor as _se

            prev = self._stats
            stats = self._stats = _ex.DatasetStats()
            if prev is not None:
                # The push-shuffle summary describes how THESE blocks
                # were produced — keep it visible across consumption.
                stats.shuffle = prev.shuffle
            stats.note_start()
            produced: List[Any] = []
            for ref in _se.execute(self._segments, rt, cfg, stats,
                                   window=window):
                produced.append(ref)
                yield ref
            self._cached_refs = produced
            stats.note_end()
            return
        yield from self._stream_refs_windowed(window)

    def _stream_refs_windowed(self,
                              window: Optional[int] = None) -> Iterator[Any]:
        """The pre-streaming-engine path, kept for A/B: at most
        ``window`` whole block CHAINS in flight (count-bounded, not
        byte-bounded); a block's full stage chain is submitted at once
        and pipelines on dependency resolution (stages split at
        actor-compute ops, execution.py)."""
        from ray_tpu.data import execution as _ex

        window = window or DEFAULT_STREAMING_WINDOW
        prev = self._stats
        stats = self._stats = _ex.DatasetStats()
        if prev is not None:
            stats.shuffle = prev.shuffle
        stats.note_start()
        pairs = ((b, ops) for blocks, ops in self._segments
                 for b in blocks)
        pools: dict = {}  # id(actor op) -> ActorPoolMapOperator

        def pool_for(op):
            p = pools.get(id(op))
            if p is None:
                p = pools[id(op)] = _ex.ActorPoolMapOperator(
                    op[1], op[2], op[3])
            return p

        def submit(pair):
            """Submit one block's full stage chain; returns
            (final_ref, [(pool, actor_idx)...]) for inflight release."""
            b, ops = pair
            if not ops:
                return b, ()
            ref = b
            done_notes = []
            for kind, payload in _ex.split_stages(ops):
                if kind == "actors":
                    pool = pool_for(payload)
                    ref, sref, ai = pool.submit((), ref)
                    done_notes.append((pool, ai))
                else:
                    ref, sref = _ex.apply_stage_with_stats.remote(
                        payload, ref)
                stats.add_ref(sref)
            return ref, tuple(done_notes)

        dq: deque = deque()
        it = iter(pairs)
        for pair in itertools.islice(it, window):
            dq.append(submit(pair))
        produced: List[Any] = []
        try:
            while dq:
                head, notes = dq.popleft()
                ray.wait([head], num_returns=1, timeout=None)
                for pool, ai in notes:
                    pool.done(ai)
                nxt = next(it, None)
                if nxt is not None:
                    dq.append(submit(nxt))
                produced.append(head)
                yield head
            self._cached_refs = produced
            stats.note_end()
        finally:
            for pool in pools.values():
                pool.shutdown()

    def stats(self) -> str:
        """Per-operator execution summary of the last run (reference:
        Dataset.stats() / _internal/stats.py)."""
        from ray_tpu.data.execution import DatasetStats

        return str(self._stats or DatasetStats())

    def materialize(self) -> "Dataset":
        """Execute the plan fully; the result holds plain block refs
        (reference: Dataset.materialize).  Eager execution wants
        THROUGHPUT: the window argument opens to the full block count so
        the task-count cap never binds (every execution slot is usable).
        Under the streaming engine the byte budget
        (``config.data_memory_budget``) still gates admission — the
        result set is retained anyway, but intermediates stay bounded;
        the legacy windowed path (``streaming_executor=off``) runs
        unbounded as before."""
        if self._cached_refs is not None:
            return Dataset(self._cached_refs)
        if all(not ops for _, ops in self._segments):
            return self
        for _ in self._stream_refs(window=max(DEFAULT_STREAMING_WINDOW,
                                              len(self._blocks))):
            pass
        out = Dataset(self._cached_refs)
        out._stats = self._stats
        return out

    def _executed_refs(self) -> List[Any]:
        return self.materialize()._blocks

    # -------------------------------------------------------- layout ops
    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into ``num_blocks`` row-equal blocks with slice tasks —
        rows never pass through the driver (reference: repartition via
        shuffle/split_at_indices, not driver collect)."""
        blocks = self._executed_refs()
        counts = ray.get(_bulk_submit([(_count_block, (b,), None)
                                       for b in blocks]))
        total = sum(counts)
        num_blocks = max(1, num_blocks)
        bounds = [total * (i + 1) // num_blocks
                  for i in builtins.range(num_blocks)]
        plans = _plan_row_ranges(counts, bounds)
        out = []
        for plan in plans:
            if len(plan) == 1:
                bi, s, e = plan[0]
                out.append(_slice_block.remote(blocks[bi], s, e)
                           if (s, e) != (0, counts[bi])
                           else blocks[bi])
            else:
                out.append(_concat_slices.remote(
                    [(i, s, e) for i, s, e in plan],
                    *[blocks[bi] for bi, _, _ in plan]))
        return Dataset(out)

    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """Shard for Train workers without driver materialization
        (reference: dataset.py split + Train dataset_spec.py).  Each shard
        is a lazy Dataset over sliced block refs; Train workers consume
        them via iter_batches inside their own processes."""
        blocks = self._executed_refs()
        if not equal:
            return [Dataset(blocks[i::n]) for i in builtins.range(n)]
        counts = ray.get(_bulk_submit([(_count_block, (b,), None)
                                       for b in blocks]))
        total = sum(counts)
        per = total // n
        bounds = [per * (i + 1) for i in builtins.range(n)]
        plans = _plan_row_ranges(counts, bounds)
        out = []
        for plan in plans:
            refs = []
            for bi, s, e in plan:
                if e > s:
                    refs.append(blocks[bi] if (s, e) == (0, counts[bi])
                                else _slice_block.remote(blocks[bi], s, e))
            out.append(Dataset(refs))
        return out

    def _try_push_shuffle(self, mode: str, *, key=None,
                          descending: bool = False, seed: int = 0,
                          aggs=None, fn=None) -> Optional["Dataset"]:
        """Route an all-to-all through the push-based shuffle engine
        (``data/shuffle.py`` + ``streaming_executor.ShuffleOperator``).

        Returns the result Dataset, or None when the push path does not
        apply and the caller should run the legacy pull shuffle:
        ``config.push_shuffle`` is off (the module is then never even
        imported — every shuffle counter stays zero), the driving
        process is not the head (no node table), fewer than 2 blocks,
        or no plan could be formed (no alive nodes / no sort samples)."""
        from ray_tpu._private import api_internal
        from ray_tpu._private.config import GLOBAL_CONFIG

        rt = api_internal.get_runtime()
        if rt is None:
            return None
        cfg = getattr(rt, "config", None) or GLOBAL_CONFIG
        if not getattr(cfg, "push_shuffle", False):
            return None
        if not hasattr(rt, "nodes") or not hasattr(rt, "node_order"):
            return None  # worker- or client-driven dataset
        blocks = self._executed_refs()
        if len(blocks) < 2:
            return None
        from ray_tpu.data import execution as _ex
        from ray_tpu.data import shuffle as _sh
        from ray_tpu.data import streaming_executor as _se

        spec = _sh.ShuffleSpec(mode, key=key, descending=descending,
                               seed=seed, aggs=aggs, fn=fn)
        res = _se.ShuffleOperator(spec, rt, cfg).run(blocks)
        if res is None:
            return None
        refs, summary = res
        out = Dataset(refs)
        st = self._stats or _ex.DatasetStats()
        st.shuffle = summary
        out._stats = st
        return out

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Push-based two-stage shuffle (reference:
        _internal/push_based_shuffle.py): map tasks partition rows to
        reducers; reduce tasks concat + locally shuffle.  With
        ``config.push_shuffle`` on, partitions move worker-to-worker
        over the striped put verbs and reducers shuffle on arrival."""
        seed = 0 if seed is None else seed
        pushed = self._try_push_shuffle("random", seed=seed)
        if pushed is not None:
            return pushed
        blocks = self._executed_refs()
        n = len(blocks)
        if n == 0:
            return Dataset([])
        mapper = _shuffle_map.options(num_returns=n)
        parts = _bulk_submit([(mapper, (b, n, seed + i), None)
                              for i, b in enumerate(blocks)])
        if n == 1:
            parts = [[p] for p in parts]
        reducers = _bulk_submit([
            (_shuffle_reduce,
             (seed + 1000 + j, *[parts[i][j] for i in builtins.range(n)]),
             None)
            for j in builtins.range(n)])
        return Dataset(reducers)

    def sort(self, key: Union[str, Callable, None] = None,
             descending: bool = False) -> "Dataset":
        """Distributed sample-partition-sort (reference:
        _internal/push_based_shuffle.py + sort.py): sample each block for
        range boundaries, partition rows to P reducers, sort per range.
        Output is P globally-ordered blocks — no single-task merge, no
        O(dataset) memory on one worker (the v1 design concatenated
        every block in ONE reducer).  With ``config.push_shuffle`` on,
        range partitions push straight to their reducer's node store and
        reducers k-way-merge pre-sorted runs on arrival."""
        pushed = self._try_push_shuffle("sort", key=key,
                                        descending=descending)
        if pushed is not None:
            return pushed
        blocks = self._executed_refs()
        n = len(blocks)
        if n == 0:
            return Dataset([])
        if n == 1:
            return Dataset([_sort_block.remote(blocks[0], key, descending)])
        samples = ray.get(_bulk_submit([
            (_sample_block, (b, 16, key), None) for b in blocks]))
        flat = sorted((s for part in samples for s in part), key=_none_key)
        if not flat:
            return Dataset(blocks)
        # P-1 boundaries at even sample quantiles (decorated, so the
        # partition bisect never compares None against a real key).
        bounds = [_none_key(flat[len(flat) * (i + 1) // n])
                  for i in builtins.range(n - 1)]
        mapper = _range_partition.options(num_returns=n)
        parts = _bulk_submit([(mapper, (b, key, descending, bounds), None)
                              for b in blocks])
        out = _bulk_submit([
            (_sort_range,
             (key, descending,
              *[parts[i][j] for i in builtins.range(n)]),
             None)
            for j in builtins.range(n)])
        return Dataset(out)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise zip (reference: dataset.py Dataset.zip): the other
        dataset is re-sliced to this one's block row boundaries, then
        blocks pair off in per-block tasks."""
        blocks = self._executed_refs()
        counts = ray.get(_bulk_submit([(_count_block, (b,), None)
                                       for b in blocks]))
        bounds = list(itertools.accumulate(counts))
        other_blocks = other._executed_refs()
        other_counts = ray.get(_bulk_submit([(_count_block, (b,), None)
                                             for b in other_blocks]))
        if sum(counts) != sum(other_counts):
            raise ValueError(
                f"zip requires equal row counts: {sum(counts)} vs "
                f"{sum(other_counts)}")
        plans = _plan_row_ranges(other_counts, bounds)
        out = []
        for mine, plan in zip(blocks, plans):
            if len(plan) == 1:
                bi, s, e = plan[0]
                theirs = (other_blocks[bi]
                          if (s, e) == (0, other_counts[bi])
                          else _slice_block.remote(other_blocks[bi], s, e))
            else:
                theirs = _concat_slices.remote(
                    [(i, s, e) for i, s, e in plan],
                    *[other_blocks[bi] for bi, _, _ in plan])
            out.append(_zip_blocks.remote(mine, theirs))
        return Dataset(out)

    def groupby(self, key: Union[str, Callable]) -> "GroupedDataset":
        """reference: grouped_dataset.py Dataset.groupby."""
        from ray_tpu.data.grouped_dataset import GroupedDataset

        return GroupedDataset(self, key)

    def window(self, *, blocks_per_window: int = 2) -> "DatasetPipeline":
        """Split into a pipeline of windows executed one at a time
        (reference: dataset_pipeline.py Dataset.window)."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        pairs = [(b, ops) for blocks, ops in self._segments
                 for b in blocks]
        windows = []
        for i in builtins.range(0, len(pairs), blocks_per_window):
            chunk = pairs[i:i + blocks_per_window]
            windows.append(Dataset._from_segments(
                [([b], ops) for b, ops in chunk]))
        return DatasetPipeline(windows)

    def repeat(self, times: int = 1) -> "DatasetPipeline":
        """reference: dataset_pipeline.py Dataset.repeat."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        return DatasetPipeline([self] * times)

    def union(self, *others: "Dataset") -> "Dataset":
        """Lazy concatenation: segments are appended, not executed — the
        streaming window still governs when block tasks run."""
        segments = list(self._segments)
        for o in others:
            segments.extend(o._segments)
        return Dataset._from_segments(segments)

    def limit(self, n: int) -> "Dataset":
        """First n rows; executes only as many blocks as needed (streaming
        early-exit)."""
        taken, refs = 0, []
        for ref in self._stream_refs():
            cnt = ray.get(_count_block.remote(ref))
            if taken + cnt <= n:
                refs.append(ref)
                taken += cnt
            else:
                refs.append(_slice_block.remote(ref, 0, n - taken))
                taken = n
            if taken >= n:
                break
        return Dataset(refs)

    # ------------------------------------------------------------ consumers
    def count(self) -> int:
        return sum(ray.get([_count_block.remote(r)
                            for r in self._stream_refs()]))

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for ref in self._stream_refs():
            out.extend(_block_rows(ray.get(ref)))
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> List[Any]:
        out = []
        for ref in self._stream_refs():
            out.extend(_block_rows(ray.get(ref)))
        return out

    def iter_rows(self) -> Iterator[Any]:
        for ref in self._stream_refs():
            yield from _block_rows(ray.get(ref))

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_blocks: int = 2) -> Iterator[Any]:
        """Batch iterator (reference: dataset.py iter_batches).

        ``prefetch_blocks`` widens the streaming window so upcoming
        blocks execute while the consumer works (the reference's
        prefetch_batches).  ``batch_size=None`` yields each BLOCK as one
        native batch — dict-of-numpy and arrow blocks pass through
        zero-copy (views over the store mapping, never row-materialized),
        which is the train-ingest fast path."""
        window = max(DEFAULT_STREAMING_WINDOW, prefetch_blocks)
        if batch_size is None:
            for ref in self._stream_refs(window=window):
                block = ray.get(ref)
                native = _native_batch(block, batch_format)
                yield (native if native is not None
                       else _format_batch(list(_block_rows(block)),
                                          batch_format))
            return
        buf: List[Any] = []
        for ref in self._stream_refs(window=window):
            for row in _block_rows(ray.get(ref)):
                buf.append(row)
                if len(buf) == batch_size:
                    yield _format_batch(buf, batch_format)
                    buf = []
        if buf and not drop_last:
            yield _format_batch(buf, batch_format)

    def num_blocks(self) -> int:
        return len(self._blocks)

    def schema(self):
        rows = self.take(1)
        if not rows:
            return None
        r = rows[0]
        if isinstance(r, dict):
            return {k: type(v).__name__ for k, v in r.items()}
        return type(r).__name__

    def sum(self, key: Optional[str] = None):
        vals = (r[key] if key else r for r in self.iter_rows())
        return builtins.sum(vals)

    def mean(self, key: Optional[str] = None):
        total, n = 0.0, 0
        for r in self.iter_rows():
            total += (r[key] if key else r)
            n += 1
        return total / max(n, 1)

    # ------------------------------------------------------------------- IO
    def write_parquet(self, path: str):
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._stream_refs()):
            block = ray.get(ref)
            if _is_arrow(block):
                table = block
            else:
                rows = list(_block_rows(block))
                if not rows:
                    continue
                table = pa.Table.from_pylist(rows)
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str):
        import os

        import pandas as pd

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._stream_refs()):
            rows = list(_block_rows(ray.get(ref)))
            if rows:
                pd.DataFrame(rows).to_csv(
                    os.path.join(path, f"part-{i:05d}.csv"), index=False)

    def write_json(self, path: str):
        import json
        import os

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._stream_refs()):
            rows = list(_block_rows(ray.get(ref)))
            with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")

    def __repr__(self):
        ops = "->".join(op[0] for op in self._ops)
        extra = (f", segments={len(self._segments)}"
                 if len(self._segments) > 1 else "")
        return (f"Dataset(num_blocks={len(self._blocks)}"
                + (f", plan={ops}" if ops else "") + extra + ")")


@ray.remote
def _concat_slices(ranges, *blocks):
    rows = []
    for (bi, s, e), block in zip(ranges, blocks):
        rows.extend(_block_rows(_slice_rows(block, s, e)))
    return rows


def _plan_row_ranges(counts: List[int], bounds: List[int]):
    """Cut blocks with ``counts`` rows at global row ``bounds`` →
    per-output-partition lists of (block_idx, start, stop)."""
    plans: List[List[tuple]] = []
    bi, offset = 0, 0  # position in input blocks
    prev = 0
    for bound in bounds:
        want = bound - prev
        plan: List[tuple] = []
        while want > 0 and bi < len(counts):
            avail = counts[bi] - offset
            take = min(want, avail)
            if take > 0:
                plan.append((bi, offset, offset + take))
            offset += take
            want -= take
            if offset >= counts[bi]:
                bi += 1
                offset = 0
        plans.append(plan)
        prev = bound
    return plans


# ------------------------------------------------------------ constructors

def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(items)) if items else 1)
    per = (len(items) + n - 1) // n
    blocks = [ray.put(items[i * per:(i + 1) * per])
              for i in builtins.range(n)]
    return Dataset(blocks)


def range(n: int, *, parallelism: int = 8) -> Dataset:
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def from_numpy(arr: np.ndarray, *, parallelism: int = 8) -> Dataset:
    return from_items(list(arr), parallelism=parallelism)


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    return from_items(df.to_dict("records"), parallelism=parallelism)


def from_arrow(table, *, parallelism: int = 8) -> Dataset:
    """Blocks are pyarrow.Table slices — the tabular zero-copy path
    (reference: _internal/arrow_block.py)."""
    n = max(1, min(parallelism, table.num_rows) or 1)
    per = (table.num_rows + n - 1) // n
    blocks = [ray.put(table.slice(i * per, per)) for i in builtins.range(n)]
    return Dataset(blocks)


def read_parquet(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os

    files = sorted(glob.glob(os.path.join(path, "*.parquet"))) \
        if os.path.isdir(path) else [path]

    @ray.remote
    def _load(f):
        import pyarrow.parquet as pq

        return pq.read_table(f)  # arrow Table block, zero-copy downstream

    return Dataset(_bulk_submit([(_load, (f,), None) for f in files]))


def read_csv(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os

    files = sorted(glob.glob(os.path.join(path, "*.csv"))) \
        if os.path.isdir(path) else [path]

    @ray.remote
    def _load(f):
        import pandas as pd

        return pd.read_csv(f).to_dict("records")

    return Dataset(_bulk_submit([(_load, (f,), None) for f in files]))


def read_json(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os

    files = sorted(glob.glob(os.path.join(path, "*.json"))) \
        if os.path.isdir(path) else [path]

    @ray.remote
    def _load(f):
        import json

        with open(f) as fh:
            return [json.loads(line) for line in fh if line.strip()]

    return Dataset(_bulk_submit([(_load, (f,), None) for f in files]))


def read_text(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os

    files = sorted(glob.glob(path)) if any(c in path for c in "*?") \
        else ([os.path.join(path, f) for f in sorted(os.listdir(path))]
              if os.path.isdir(path) else [path])

    @ray.remote
    def _load(f):
        with open(f) as fh:
            return [line.rstrip("\n") for line in fh]

    return Dataset(_bulk_submit([(_load, (f,), None) for f in files]))
