"""Dataset: lazy op-chain over object-store blocks.

Reference: ``python/ray/data/dataset.py:166`` (4.5k LoC Dataset),
``_internal/plan.py`` (ExecutionPlan), ``_internal/execution/bulk_executor
.py:20``.  Execution model kept: a Dataset is (block refs, lazy ops); ops
are applied block-parallel as tasks at materialization; consumed via
iter_rows/iter_batches/take/write_* or split() into Train shards.
"""

from __future__ import annotations

import builtins
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu as ray


# --------------------------------------------------------------- block ops
# A block is a list of rows (dicts or scalars) or a dict-of-numpy arrays
# ("tensor block").  Ops below run inside tasks (block-parallel).

def _block_len(block) -> int:
    if isinstance(block, dict):
        for v in block.values():
            return len(v)
        return 0
    return len(block)


def _block_rows(block) -> Iterator[Any]:
    if isinstance(block, dict):
        keys = list(block)
        for i in builtins.range(_block_len(block)):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block


def _rows_to_block(rows: List[Any]):
    return rows


@ray.remote
def _map_block(fn, block):
    return _rows_to_block([fn(r) for r in _block_rows(block)])


@ray.remote
def _filter_block(fn, block):
    return _rows_to_block([r for r in _block_rows(block) if fn(r)])


@ray.remote
def _flat_map_block(fn, block):
    out = []
    for r in _block_rows(block):
        out.extend(fn(r))
    return _rows_to_block(out)


@ray.remote
def _map_batches_block(fn, block, batch_format):
    rows = list(_block_rows(block))
    if batch_format == "numpy":
        if rows and isinstance(rows[0], dict):
            batch = {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        else:
            batch = np.asarray(rows)
    elif batch_format == "pandas":
        import pandas as pd
        batch = pd.DataFrame(rows)
    else:
        batch = rows
    out = fn(batch)
    if isinstance(out, dict):
        return out
    try:
        import pandas as pd
        if isinstance(out, pd.DataFrame):
            return out.to_dict("records")
    except ImportError:
        pass
    if isinstance(out, np.ndarray):
        return list(out)
    return list(out)


@ray.remote
def _sort_block(block, key, descending):
    rows = list(_block_rows(block))
    keyfn = (lambda r: r[key]) if isinstance(key, str) else key
    return sorted(rows, key=keyfn, reverse=descending)


@ray.remote
def _merge_sorted(key, descending, *blocks):
    import heapq
    keyfn = (lambda r: r[key]) if isinstance(key, str) else (key or (lambda r: r))
    rows = list(heapq.merge(*blocks, key=keyfn, reverse=descending))
    return rows


@ray.remote
def _shuffle_map(block, num_reducers, seed):
    rng = np.random.default_rng(seed)
    rows = list(_block_rows(block))
    assignment = rng.integers(0, num_reducers, size=len(rows))
    return [[r for r, a in zip(rows, assignment) if a == i]
            for i in builtins.range(num_reducers)]


@ray.remote
def _shuffle_reduce(seed, *parts):
    rows = list(itertools.chain(*parts))
    rng = np.random.default_rng(seed)
    rng.shuffle(rows)
    return rows


class Dataset:
    """Immutable, lazily-transformed distributed collection."""

    def __init__(self, block_refs: List[Any]):
        self._blocks = list(block_refs)

    # ------------------------------------------------------------ transforms
    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return Dataset([_map_block.remote(fn, b) for b in self._blocks])

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return Dataset([_filter_block.remote(fn, b) for b in self._blocks])

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return Dataset([_flat_map_block.remote(fn, b) for b in self._blocks])

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy"
                    ) -> "Dataset":
        return Dataset([_map_batches_block.remote(fn, b, batch_format)
                        for b in self._blocks])

    def repartition(self, num_blocks: int) -> "Dataset":
        rows = self.take_all()
        return from_items(rows, parallelism=num_blocks)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Push-based two-stage shuffle (reference:
        _internal/push_based_shuffle.py): map tasks partition rows to
        reducers; reduce tasks concat + locally shuffle."""
        n = len(self._blocks)
        if n == 0:
            return self
        seed = 0 if seed is None else seed
        parts = [_shuffle_map.options(num_returns=n).remote(b, n, seed + i)
                 for i, b in enumerate(self._blocks)]
        if n == 1:
            parts = [[p] for p in parts]
        reducers = []
        for j in builtins.range(n):
            reducers.append(_shuffle_reduce.remote(
                seed + 1000 + j, *[parts[i][j] for i in builtins.range(n)]))
        return Dataset(reducers)

    def sort(self, key: Union[str, Callable, None] = None,
             descending: bool = False) -> "Dataset":
        sorted_blocks = [_sort_block.remote(b, key, descending)
                         for b in self._blocks]
        merged = _merge_sorted.remote(key, descending, *sorted_blocks)
        return Dataset([merged])

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self._blocks)
        for o in others:
            blocks.extend(o._blocks)
        return Dataset(blocks)

    def limit(self, n: int) -> "Dataset":
        rows = []
        for b in self._blocks:
            rows.extend(_block_rows(ray.get(b)))
            if len(rows) >= n:
                break
        return from_items(rows[:n], parallelism=1)

    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """Shard for Train workers (reference: dataset.py split + Train
        dataset_spec.py)."""
        rows = self.take_all()
        if equal:
            per = len(rows) // n
            return [from_items(rows[i * per:(i + 1) * per], parallelism=1)
                    for i in builtins.range(n)]
        sizes = [len(rows) // n + (1 if i < len(rows) % n else 0)
                 for i in builtins.range(n)]
        out, cur = [], 0
        for s in sizes:
            out.append(from_items(rows[cur:cur + s], parallelism=1))
            cur += s
        return out

    # ------------------------------------------------------------ consumers
    def count(self) -> int:
        @ray.remote
        def _len(b):
            return _block_len(b)
        return sum(ray.get([_len.remote(b) for b in self._blocks]))

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for b in self._blocks:
            out.extend(_block_rows(ray.get(b)))
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> List[Any]:
        out = []
        for b in ray.get(list(self._blocks)):
            out.extend(_block_rows(b))
        return out

    def iter_rows(self) -> Iterator[Any]:
        for b in self._blocks:
            yield from _block_rows(ray.get(b))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        buf: List[Any] = []
        for row in self.iter_rows():
            buf.append(row)
            if len(buf) == batch_size:
                yield _format_batch(buf, batch_format)
                buf = []
        if buf and not drop_last:
            yield _format_batch(buf, batch_format)

    def num_blocks(self) -> int:
        return len(self._blocks)

    def schema(self):
        rows = self.take(1)
        if not rows:
            return None
        r = rows[0]
        if isinstance(r, dict):
            return {k: type(v).__name__ for k, v in r.items()}
        return type(r).__name__

    def sum(self, key: Optional[str] = None):
        vals = (r[key] if key else r for r in self.iter_rows())
        return sum(vals)

    def mean(self, key: Optional[str] = None):
        total, n = 0.0, 0
        for r in self.iter_rows():
            total += (r[key] if key else r)
            n += 1
        return total / max(n, 1)

    # ------------------------------------------------------------------- IO
    def write_parquet(self, path: str):
        import pyarrow as pa
        import pyarrow.parquet as pq
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks):
            rows = list(_block_rows(ray.get(b)))
            if not rows:
                continue
            table = pa.Table.from_pylist(rows)
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str):
        import pandas as pd
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks):
            rows = list(_block_rows(ray.get(b)))
            if rows:
                pd.DataFrame(rows).to_csv(
                    os.path.join(path, f"part-{i:05d}.csv"), index=False)

    def write_json(self, path: str):
        import json
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks):
            rows = list(_block_rows(ray.get(b)))
            with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")

    def __repr__(self):
        return f"Dataset(num_blocks={len(self._blocks)})"


def _format_batch(rows: List[Any], batch_format: str):
    if batch_format == "numpy":
        if rows and isinstance(rows[0], dict):
            return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        return np.asarray(rows)
    if batch_format == "pandas":
        import pandas as pd
        return pd.DataFrame(rows)
    return rows


# ------------------------------------------------------------ constructors

def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(items)) if items else 1)
    per = (len(items) + n - 1) // n
    blocks = [ray.put(items[i * per:(i + 1) * per])
              for i in builtins.range(n)]
    return Dataset(blocks)


def range(n: int, *, parallelism: int = 8) -> Dataset:
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def from_numpy(arr: np.ndarray, *, parallelism: int = 8) -> Dataset:
    return from_items(list(arr), parallelism=parallelism)


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    return from_items(df.to_dict("records"), parallelism=parallelism)


def read_parquet(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os
    files = sorted(glob.glob(os.path.join(path, "*.parquet"))) \
        if os.path.isdir(path) else [path]

    @ray.remote
    def _load(f):
        import pyarrow.parquet as pq
        return pq.read_table(f).to_pylist()

    return Dataset([_load.remote(f) for f in files])


def read_csv(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os
    files = sorted(glob.glob(os.path.join(path, "*.csv"))) \
        if os.path.isdir(path) else [path]

    @ray.remote
    def _load(f):
        import pandas as pd
        return pd.read_csv(f).to_dict("records")

    return Dataset([_load.remote(f) for f in files])


def read_json(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os
    files = sorted(glob.glob(os.path.join(path, "*.json"))) \
        if os.path.isdir(path) else [path]

    @ray.remote
    def _load(f):
        import json
        with open(f) as fh:
            return [json.loads(line) for line in fh if line.strip()]

    return Dataset([_load.remote(f) for f in files])


def read_text(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os
    files = sorted(glob.glob(path)) if any(c in path for c in "*?") \
        else ([os.path.join(path, f) for f in sorted(os.listdir(path))]
              if os.path.isdir(path) else [path])

    @ray.remote
    def _load(f):
        with open(f) as fh:
            return [line.rstrip("\n") for line in fh]

    return Dataset([_load.remote(f) for f in files])
