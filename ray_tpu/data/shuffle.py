"""Push-based all-to-all shuffle: map-side partition push over the
striped data plane, merge-on-arrival reduce.

Reference: Exoshuffle (SIGCOMM'23) — shuffle as an application-level
library over a shared-memory object store with push-based map output —
and the pipelined-operator argument of Ownership (NSDI'21).  The legacy
shuffles behind ``Dataset.random_shuffle``/``sort`` and
``GroupedDataset`` are pull-based: each map task returns N partition
objects and each reduce task takes N of them as *arguments*, so N
blocks x N reducers puts O(N^2) objects in the head's table and every
partition byte rides the arg-fetch path at reduce start, serializing
transfer behind compute.  This engine inverts the flow:

- **Map side** (``_shuffle_map_push``): partition one block's rows
  (range partition for sort, key hash for groupby, seeded RNG for
  random_shuffle), serialize each partition, and push its segment image
  straight into the *reducer's* node store over the direct-put verbs
  (``reserve_put``/``put_range``/``commit_put`` — a partition is just a
  segment image, and ``ObjectPusher`` already knows how to stripe one).
  Only tiny descriptors ``(kind, ident, total, store, nrows, hedged)``
  ride the task result; no partition payload ever crosses a head
  message.  A push to one's OWN store short-circuits through
  ``shm_store.put_local`` (same admission, no wire).
- **Reduce side** (``_ShuffleReducer`` actor): partitions are consumed
  as they arrive — a streaming k-way merge of sorted runs for sort
  (``shuffle_merge_fanin`` bounds held runs), contiguous-range
  accumulator merging for groupby/aggregate, concat+seeded-shuffle for
  random_shuffle — instead of waiting for all N inputs.  Admission is
  spill-aware by construction: ``reserve_put`` degrades over-capacity
  partitions to spill files, and the reducer attaches those by path.
- **Fault story** composes from existing planes: a lost partition means
  re-running ONE map task (its input block rebuilt by PR 9 lineage if
  needed), never restarting the shuffle; a *stalled* reducer link trips
  the PR 14 deadline core inside ``ObjectPusher.push`` and the map task
  hedges the partition into its own healthy store (the reducer then
  pulls it over the data plane).  The driver-side coordinator
  (``streaming_executor.ShuffleOperator``) rebuilds a dead reducer on a
  healthy node from per-partition re-maps.

Exact-equality contract: with distinct (or integer-exact) data the push
path reproduces the legacy output bit-for-bit — sort merges on the
strict key ``(key, map_idx, pos)`` (the tie order a stable sort of the
map-order concatenation produces), groupby merges partial accumulators
in map order, random_shuffle re-applies the legacy per-reducer seeds.
``config.push_shuffle=off`` never imports this module from workers and
runs the pre-PR path byte-identical with every counter zero.

LOCK ORDER: ``_STATS_LOCK`` is an independent LEAF — it guards only the
process-local counter dict read by ``shuffle_stats()`` (the xfer_stats
flusher / ``transfer_stats()`` merge); no other lock is ever acquired
while holding it and it is never held across serialization, a push, or
any wire call.  Pinned in tests/test_lockcheck.py next to the
StreamingStats leaf.
"""

from __future__ import annotations

import builtins
import heapq
import itertools
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu as ray


# ------------------------------------------------------------- counters --
# Process-local cumulative counters.  In workers they ride the periodic
# ("xfer_stats", delta) flush (worker_main.flush_xfer_stats looks this
# module up lazily); in the driver/head process transfer_stats() merges
# them directly.  All zero while push_shuffle is off — pinned by tests.
_STATS_LOCK = threading.Lock()  # lock-order: leaf (see module docstring)
_STATS = {
    "shuffle_pushed_bytes": 0,
    "shuffle_merges": 0,
    "shuffle_spills": 0,
    "shuffle_hedges": 0,
}


def note(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def shuffle_stats() -> Dict[str, int]:
    """Cumulative snapshot (monotonic — the flusher ships deltas).
    Deliberately NOT named ``stats()``: protocheck's counter-survival
    rule scans worker modules' ``stats()`` providers, and this module's
    keys are aggregated through the lazy flush hook instead."""
    with _STATS_LOCK:
        return dict(_STATS)


# ------------------------------------------------------------------ spec --
class ShuffleSpec:
    """Everything a map task / reducer needs to know about one shuffle.

    ``mode`` is ``"sort"`` / ``"groupby"`` / ``"map_groups"`` /
    ``"random"``.  ``bounds`` (sort only) holds the None-safe DECORATED
    range boundaries the coordinator sampled.  Plain attributes so
    cloudpickle ships the key/agg/fn callables like any task arg."""

    def __init__(self, mode: str, key=None, descending: bool = False,
                 seed: int = 0, aggs: Optional[list] = None, fn=None,
                 bounds: Optional[list] = None, merge_fanin: int = 8):
        self.mode = mode
        self.key = key
        self.descending = descending
        self.seed = seed
        self.aggs = aggs
        self.fn = fn
        self.bounds = bounds
        self.merge_fanin = max(2, int(merge_fanin))


class _Rev:
    """Order-inverting key wrapper: descending sort merges still need
    ASCENDING (map_idx, pos) tie order — the order a stable
    ``reverse=True`` sort of the map-order concatenation yields — so the
    primary key alone inverts inside the strict merge tuple."""

    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return other.k < self.k

    def __eq__(self, other):
        return other.k == self.k


def _strict_key(spec: "ShuffleSpec", keyfn, map_idx: int):
    """row, pos -> the total-order merge key ``(key, map_idx, pos)``.
    Strictness (no ties anywhere) is what makes merge-on-arrival safe:
    intermediate merges of out-of-order run subsets cannot perturb the
    final order."""
    if spec.descending:
        return lambda r, pos: ((_Rev(_none_key(keyfn(r))), map_idx, pos))
    return lambda r, pos: ((_none_key(keyfn(r)), map_idx, pos))


def _none_key(v):
    """The repo-wide None-safe sort decoration (grouped_dataset's
    ``(x is None, x)`` convention): None keys order after every real
    key instead of raising TypeError."""
    return (v is None, v)


def _keyfn_of(key):
    from ray_tpu.data.dataset import _keyfn_of as _k

    return _k(key)


# ------------------------------------------------------------- map side --
def _partition_rows(rows: List[Any], spec: ShuffleSpec,
                    num_reducers: int, map_idx: int) -> List[List[Any]]:
    """One block's rows -> per-reducer row lists, exactly mirroring the
    legacy partitioners (same RNG streams, same bisection) so the two
    engines bucket identically."""
    import bisect

    n = num_reducers
    buckets: List[List[Any]] = [[] for _ in builtins.range(n)]
    if spec.mode == "random":
        rng = np.random.default_rng(spec.seed + map_idx)
        assignment = rng.integers(0, n, size=len(rows))
        for r, a in zip(rows, assignment):
            buckets[a].append(r)
        return buckets
    keyfn = _keyfn_of(spec.key)
    if spec.mode == "sort":
        bounds = spec.bounds or []
        n_out = len(bounds) + 1
        for r in rows:
            i = bisect.bisect_left(bounds, _none_key(keyfn(r)))
            if spec.descending:
                i = n_out - 1 - i
            buckets[i].append(r)
        # Pre-sort each partition into a run (stable, so equal keys keep
        # block-row order = the tie order the legacy concat-then-stable-
        # sort reducer produces); the reducer then only merges.
        for b in buckets:
            b.sort(key=lambda r: _none_key(keyfn(r)),
                   reverse=spec.descending)
        return buckets
    # groupby / map_groups: the legacy _hash_partition bucketing.
    for r in rows:
        buckets[hash(keyfn(r)) % n].append(r)
    return buckets


def _push_partition(rows: List[Any], store: str) -> tuple:
    """Serialize one partition and land its segment image in ``store``:
    local short-circuit through ``put_local``, else a striped
    ``ObjectPusher.push``.  A failed/stalled/unsupported remote push
    HEDGES into the map worker's own store (the reducer pulls it over
    the data plane) — the shuffle never dies on one gray link.  Returns
    the descriptor ``(kind, ident, total, home_store, nrows, hedged)``."""
    from ray_tpu._private import api_internal, object_transfer, serialization
    from ray_tpu._private import shm_store as shm_mod
    from ray_tpu._private.ids import ObjectID

    if not rows:
        # Nothing to ship: a zero-byte sentinel descriptor (the reducer
        # still sees the accept, so groupby's map-range coalescing and
        # random_shuffle's concat order stay complete).
        return ("empty", "", 0, "", 0, False)
    rt = api_internal.require_runtime()
    res = serialization.dumps_adaptive(rows, 0)  # max_inline=0: parts form
    meta, bufs = res[1], res[2]
    oid_bin = ObjectID.for_put().binary()
    hedged = False
    if store != rt.store_id:
        ent = rt.resolve_store_addr(store)
        if ent is not None and object_transfer.peer_accepts_puts(ent[1]):
            try:
                kind, ident, total = rt._pusher.push(
                    store, ent[0], oid_bin, meta, bufs, caps=ent[1])
                note("shuffle_pushed_bytes", total)
                if kind == "spilled":
                    note("shuffle_spills")
                return (kind, ident, total, store, len(rows), False)
            except Exception:
                # Dead or stalled-past-deadline link (the pusher already
                # retried with backoff under the PR 14 deadline core):
                # fall through to the local hedge.
                rt.forget_store_addr(store)
        hedged = True
        note("shuffle_hedges")
    kind, ident, total = shm_mod.put_local(rt.shm, oid_bin, meta, bufs)
    note("shuffle_pushed_bytes", total)
    if kind == "spilled":
        note("shuffle_spills")
    return (kind, ident, total, rt.store_id, len(rows), hedged)


@ray.remote
def _shuffle_map_push(block, spec: ShuffleSpec, map_idx: int,
                      target_stores: List[str],
                      only_parts: Optional[tuple] = None):
    """Partition one block and push every partition to its reducer's
    store.  ``only_parts`` restricts the pushes (per-partition re-maps
    after a reducer loss — the partitioning pass still runs in full so
    bucketing stays identical).  Returns one descriptor per reducer
    (None for skipped partitions)."""
    from ray_tpu.data.dataset import _block_rows

    rows = list(_block_rows(block))
    parts = _partition_rows(rows, spec, len(target_stores), map_idx)
    out: List[Optional[tuple]] = []
    for j, prows in enumerate(parts):
        if only_parts is not None and j not in only_parts:
            out.append(None)
            continue
        out.append(_push_partition(prows, target_stores[j]))
    return out


# ---------------------------------------------------------- reduce side --
@ray.remote(num_cpus=0)
class _ShuffleReducer:
    """One reducer: merges partitions ON ARRIVAL instead of waiting for
    all N map inputs.  ``num_cpus=0`` so R reducers never starve the map
    wave of execution slots on a small cluster (they are merge/IO-bound
    and spend their life blocked in ``accept``).

    Single-threaded by the actor model — no locks; the strict merge key
    makes arrival order irrelevant to the final output (see module
    docstring)."""

    def __init__(self, spec: ShuffleSpec, reducer_idx: int):
        self._spec = spec
        self._idx = reducer_idx
        self._segs: List[Any] = []   # attached partition segments, kept
        #                              alive until release() — loaded
        #                              rows may be zero-copy views
        self._runs: List[List[tuple]] = []      # sort: strict-key runs
        self._partials: List[list] = []  # groupby: [start, end, accs]
        self._rows: List[tuple] = []     # map_groups: (map_idx, pos, row)
        self._parts: Dict[int, List[Any]] = {}  # random: map_idx -> rows
        self._merges = 0
        self._accepted = 0

    # -- partition intake -------------------------------------------------
    def _load(self, descr: tuple) -> List[Any]:
        """Descriptor -> row list.  Locally-homed partitions attach by
        name/path and unlink immediately (the mapping stays readable
        until release()); hedged remote-homed ones pull over the data
        plane through the runtime's materialize path."""
        from ray_tpu._private import api_internal, protocol

        kind, ident, total, store, _nrows, _hedged = descr
        rt = api_internal.require_runtime()
        if store == rt.store_id:
            if kind == "spilled":
                seg = rt.shm.attach_path(ident)
                self._segs.append(seg)
                rows = seg.deserialize()
                try:
                    os.unlink(ident)
                except OSError:
                    pass
            else:
                seg = rt.shm.attach(ident)
                self._segs.append(seg)
                rows = seg.deserialize()
                # Owner-routed free: releases the node byte accounting
                # the pusher's reserve_put charged.
                rt.shm.unlink(ident, total)
            return rows
        pkind = protocol.SHM if kind == "shm" else protocol.SPILLED
        return rt.materialize((pkind, ident, total, store))

    def accept(self, map_idx: int, descr: tuple) -> int:
        spec = self._spec
        rows = [] if descr[0] == "empty" else self._load(descr)
        self._accepted += 1
        if spec.mode == "sort":
            if not rows:
                return 0
            keyfn = _keyfn_of(spec.key)
            sk = _strict_key(spec, keyfn, map_idx)
            run = [(*sk(r, pos), r) for pos, r in enumerate(rows)]
            self._runs.append(run)
            if len(self._runs) >= spec.merge_fanin:
                # Streaming k-way merge: held runs collapse into one, so
                # memory tracks the fan-in knob, not the map count.
                merged = list(heapq.merge(*self._runs))
                self._runs = [merged]
                self._merges += 1
                note("shuffle_merges")
        elif spec.mode == "groupby":
            self._fold_groupby(map_idx, rows)
        elif spec.mode == "map_groups":
            for pos, r in enumerate(rows):
                self._rows.append((map_idx, pos, r))
        else:  # random
            self._parts[map_idx] = rows
        return len(rows)

    def _fold_groupby(self, map_idx: int, rows: List[Any]) -> None:
        """Fold one arriving partition into a per-map-range partial
        accumulator set, then merge CONTIGUOUS ranges on arrival — the
        merge order is then always map order, the order the legacy
        single-pass fold consumes rows in."""
        spec = self._spec
        keyfn = _keyfn_of(spec.key)
        aggs = spec.aggs
        accs: Dict[Any, list] = {}
        for r in rows:
            k = keyfn(r)
            acc = accs.get(k)
            if acc is None:
                acc = accs[k] = [a.init() for a in aggs]
            for i, a in enumerate(aggs):
                acc[i] = a.accumulate(acc[i], r)
        self._partials.append([map_idx, map_idx, accs])
        self._partials.sort(key=lambda p: p[0])
        # Coalesce neighbors while any adjacent map ranges touch.
        merged_any = True
        while merged_any:
            merged_any = False
            for i in builtins.range(len(self._partials) - 1):
                lo, hi = self._partials[i], self._partials[i + 1]
                if lo[1] + 1 == hi[0]:
                    self._merge_partials(lo, hi)
                    del self._partials[i + 1]
                    merged_any = True
                    self._merges += 1
                    note("shuffle_merges")
                    break

    def _merge_partials(self, lo: list, hi: list) -> None:
        aggs = self._spec.aggs
        for k, hacc in hi[2].items():
            lacc = lo[2].get(k)
            if lacc is None:
                lo[2][k] = hacc
            else:
                for i, a in enumerate(aggs):
                    lacc[i] = a.merge(lacc[i], hacc[i])
        lo[1] = hi[1]

    # -- output -----------------------------------------------------------
    def finalize(self):
        spec = self._spec
        if spec.mode == "sort":
            if len(self._runs) > 1:
                self._merges += 1
                note("shuffle_merges")
            out = [t[-1] for t in heapq.merge(*self._runs)]
            self._runs = []
            return out
        if spec.mode == "groupby":
            # Stragglers (non-contiguous ranges) merge here, still in
            # map order; then emit exactly like the legacy _agg_reduce.
            while len(self._partials) > 1:
                self._merge_partials(self._partials[0], self._partials[1])
                del self._partials[1]
                self._merges += 1
                note("shuffle_merges")
            accs = self._partials[0][2] if self._partials else {}
            key_col = spec.key if isinstance(spec.key, str) else "key"
            out = []
            for k in sorted(accs, key=_none_key):
                row = {key_col: k}
                for a, acc in zip(spec.aggs, accs[k]):
                    row[a.name] = a.finalize(acc)
                out.append(row)
            self._partials = []
            return out
        if spec.mode == "map_groups":
            keyfn = _keyfn_of(spec.key)
            groups: Dict[Any, list] = {}
            # (map_idx, pos) order inside each group = the legacy
            # concat-in-map-order row order fn() observes.
            for map_idx, pos, r in sorted(
                    self._rows, key=lambda t: (t[0], t[1])):
                groups.setdefault(keyfn(r), []).append(r)
            self._merges += 1
            note("shuffle_merges")
            out = []
            for k in sorted(groups, key=_none_key):
                res = spec.fn(groups[k])
                out.extend(res if isinstance(res, list) else [res])
            self._rows = []
            return out
        # random: legacy _shuffle_reduce with the same per-reducer seed.
        rows = list(itertools.chain(
            *(self._parts[i] for i in sorted(self._parts))))
        rng = np.random.default_rng(spec.seed + 1000 + self._idx)
        rng.shuffle(rows)
        self._merges += 1
        note("shuffle_merges")
        self._parts = {}
        return rows

    def stats(self) -> Dict[str, int]:
        return {"merges": self._merges, "accepted": self._accepted}

    def release(self) -> None:
        """Close the partition mappings once the coordinator has seen
        the finalize result land in the store (rows loaded from them may
        be zero-copy views, so this must not run earlier)."""
        segs, self._segs = self._segs, []
        for seg in segs:
            try:
                seg.close()
            except Exception:
                pass


# ------------------------------------------------------------- planning --
def reduce_targets(rt, num_reducers: int) -> List[Tuple[str, str]]:
    """Round-robin reducer placement over alive, non-draining nodes:
    ``[(node_id_hex, store_id), ...]`` of length ``num_reducers``.
    Returns [] when the runtime has no node table (worker/client-driven
    datasets fall back to the legacy path)."""
    try:
        with rt.lock:
            nodes = [(n.node_id.hex(), n.store_id or rt.store_id)
                     for n in (rt.nodes[nid] for nid in rt.node_order)
                     if n.alive and not n.draining]
    except AttributeError:
        return []
    if not nodes:
        return []
    return [nodes[j % len(nodes)] for j in builtins.range(num_reducers)]


def pick_reducer_count(cfg, n_blocks: int, total_bytes: int,
                       mode: str) -> int:
    """R for one shuffle: one reducer per input block unless a bytes
    target is set (sort/groupby only — random_shuffle keeps R=n so its
    seeded permutation is reproducible across the switch)."""
    target = int(getattr(cfg, "shuffle_partition_bytes_target", 0) or 0)
    if mode == "random" or target <= 0 or total_bytes <= 0:
        return max(1, n_blocks)
    want = (total_bytes + target - 1) // target
    return max(1, min(int(want), 4 * n_blocks))
