"""ray_tpu.autoscaler — demand-driven cluster scaling (autoscaler v1
equivalent).

Reference: ``python/ray/autoscaler/_private/autoscaler.py:168``
(StandardAutoscaler), ``resource_demand_scheduler.py`` (bin-packing), and
the fake in-process provider the reference tests against
(``_private/fake_multi_node/node_provider.py:237``).  TPU-native stance:
nodes are slice-atomic — a TPU slice scales in and out as one unit.
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import FakeSliceProvider, NodeProvider

__all__ = ["StandardAutoscaler", "NodeProvider", "FakeSliceProvider"]
