"""Node providers: the pluggable "how do I get a node" seam.

Reference: ``python/ray/autoscaler/node_provider.py`` (the interface every
cloud implements) and ``_private/fake_multi_node/node_provider.py:237``
(the in-process fake the reference uses to test scale-up/down logic with
no cloud).  TPU twist: nodes come in *slice-atomic* units — a TPU slice
(e.g. v5e-4) joins or leaves as one node with all its chips; the provider
never splits a slice.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Provider surface the autoscaler programs against: node-type
    catalog + create/terminate/list + per-node type lookup."""

    # name -> {"resources": {...}, "max_workers": int, "spot": bool}.
    # ``"spot": True`` marks a preemptible slice pool (GCE preemptible /
    # spot TPU slices): the autoscaler PREFERS spot types while their
    # observed preemption rate is tolerable and falls back to on-demand
    # peers past ``spot_fallback_threshold`` preemptions of the type.
    node_types: Dict[str, Dict[str, Any]] = {}

    def create_node(self, node_type: str) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, node_id: str) -> Optional[str]:
        raise NotImplementedError

    def node_resources(self, node_type: str) -> Dict[str, float]:
        return dict(self.node_types[node_type]["resources"])

    def max_workers(self, node_type: str) -> int:
        return int(self.node_types[node_type].get("max_workers", 10))

    def is_spot(self, node_type: str) -> bool:
        spec = self.node_types.get(node_type) or {}
        return bool(spec.get("spot", False))


class FakeSliceProvider(NodeProvider):
    """In-process provider over ``cluster_utils.Cluster``: each created
    node is a REAL node_agent subprocess whose resources are one whole TPU
    slice (or a CPU shape).  The autoscaler's decisions run end-to-end —
    agents register, workers spawn there, objects move between stores —
    with no cloud (reference: FakeMultiNodeProvider, node_provider.py:237).
    """

    def __init__(self, cluster, node_types: Dict[str, Dict[str, Any]]):
        """node_types: name -> {"resources": {...}, "max_workers": int}.
        A TPU slice type carries its whole chip count, e.g.
        {"v5e-4": {"resources": {"CPU": 4, "TPU": 4}, "max_workers": 2}}.
        """
        self._cluster = cluster
        self.node_types = node_types
        self._nodes: Dict[str, str] = {}  # node_id_hex -> node_type

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        r = dict(spec["resources"])
        num_cpus = r.pop("CPU", 1.0)
        num_tpus = r.pop("TPU", 0.0)
        node_id = self._cluster.add_node(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=r or None,
            labels={"autoscaler_node_type": node_type}, external=True)
        self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        # Pop the record only AFTER the removal succeeds: popping first
        # stranded a live agent the provider no longer tracked whenever
        # remove_node raised — invisible to non_terminated_nodes, never
        # terminated again, still burning a slice.
        self._cluster.remove_node(node_id)
        self._nodes.pop(node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        alive = {n["node_id"] for n in self._cluster.rt.list_nodes()
                 if n["alive"]}
        return [nid for nid in self._nodes if nid in alive]

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._nodes.get(node_id)
