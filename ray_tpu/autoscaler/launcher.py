"""Cluster launcher: ``ray_tpu up / down / exec / attach`` over a YAML
config, with pluggable command runners and cloud node providers.

Reference: ``python/ray/autoscaler/_private/commands.py`` (create_or_update
_cluster, teardown_cluster, exec_cluster), ``command_runner.py`` (SSH
command runner), the provider zoo under ``python/ray/autoscaler/_private/``
and the ``ray up/down/attach/exec`` CLI (``scripts.py:1247``).

TPU-native shape: worker nodes are SLICE-ATOMIC (a TPU slice joins as one
node with all chips); the cloud provider is GCP TPU-VM — optionally via
queued resources, the way TPU capacity is actually obtained — driven
through ``gcloud`` subprocesses.  A ``subprocess`` provider launches real
node agents locally so the whole up/exec/down path is testable with no
cloud.

Config (YAML):

    cluster_name: demo
    provider:
      type: subprocess            # or: gcp_tpu
      # gcp_tpu only:
      # project: my-proj
      # zone: us-central2-b
      # accelerator_type: v5litepod-4
      # runtime_version: tpu-ubuntu2204-base
      # queued_resources: true
    head:
      num_cpus: 4
      port: 46001                 # fixed so agents/clients can re-dial
    worker_types:
      v5e-4:
        resources: {CPU: 4, TPU: 4}
        min_workers: 1
        max_workers: 2
    setup_commands: []            # run on each cloud node before the agent
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


# ---------------------------------------------------------------- runners --
class LocalCommandRunner:
    """Run commands on this machine (subprocess provider / head host)."""

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            timeout: float = 600.0) -> str:
        out = subprocess.run(cmd, shell=True, capture_output=True,
                             text=True, timeout=timeout,
                             env={**os.environ, **(env or {})})
        if out.returncode != 0:
            raise RuntimeError(f"command failed ({cmd!r}): "
                               f"{out.stderr[-1000:]}")
        return out.stdout


class SSHCommandRunner:
    """Run commands on a remote host over ssh (reference:
    command_runner.py SSHCommandRunner — BatchMode so a missing key fails
    fast instead of prompting)."""

    def __init__(self, host: str, user: Optional[str] = None,
                 key_path: Optional[str] = None):
        self._target = f"{user}@{host}" if user else host
        self._opts = ["-o", "StrictHostKeyChecking=no",
                      "-o", "BatchMode=yes",
                      "-o", "ConnectTimeout=15"]
        if key_path:
            self._opts += ["-i", key_path]

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            timeout: float = 600.0) -> str:
        envs = " ".join(f"{k}={v}" for k, v in (env or {}).items())
        full = ["ssh", *self._opts, self._target,
                f"{envs} {cmd}".strip()]
        out = subprocess.run(full, capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode != 0:
            raise RuntimeError(f"ssh {self._target} failed ({cmd!r}): "
                               f"{out.stderr[-1000:]}")
        return out.stdout


# -------------------------------------------------------------- providers --
class SubprocessAgentProvider(NodeProvider):
    """Worker 'nodes' are local ``node_agent`` subprocesses dialing the
    head over TCP — the full multi-node path (registration, remote
    stores, chunked transfer) with no cloud."""

    def __init__(self, node_types: Dict[str, Any], head_address: str,
                 authkey_hex: str):
        self.node_types = node_types
        self._head_address = head_address
        self._authkey_hex = authkey_hex
        self._procs: Dict[str, subprocess.Popen] = {}
        self._types: Dict[str, str] = {}
        self._n = 0

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        r = dict(spec["resources"])
        self._n += 1
        node_id = f"{node_type}-{self._n}-{os.getpid()}"
        env = dict(os.environ,
                   RAY_TPU_HEAD_ADDRESS=self._head_address,
                   RAY_TPU_AUTHKEY=self._authkey_hex,
                   RAY_TPU_AGENT_RESOURCES=json.dumps(r),
                   RAY_TPU_AGENT_LABELS=json.dumps(
                       {"autoscaler_node_type": node_type,
                        "launcher_node_id": node_id}),
                   JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_agent"],
            env=env)
        self._procs[node_id] = proc
        self._types[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        self._types.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
            except Exception:
                pass

    def non_terminated_nodes(self) -> List[str]:
        return [nid for nid, p in self._procs.items()
                if p.poll() is None]

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._types.get(node_id)

    def pids(self) -> Dict[str, int]:
        return {nid: p.pid for nid, p in self._procs.items()}


class GCPTpuProvider(NodeProvider):
    """GCP TPU-VM provider driven through ``gcloud`` (reference: the
    _private/gcp provider; TPU-native twist: nodes are whole slices,
    optionally obtained via QUEUED RESOURCES — the production way to get
    TPU capacity — instead of direct create).

    Each created node runs ``setup_commands`` then joins the cluster as
    a node agent (``python -m ray_tpu.scripts agent``)."""

    def __init__(self, node_types: Dict[str, Any], conf: Dict[str, Any],
                 head_address: str, authkey_hex: str,
                 setup_commands: Optional[List[str]] = None):
        import shutil

        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "GCPTpuProvider needs the gcloud CLI on PATH")
        self.node_types = node_types
        self._conf = conf
        self._head_address = head_address
        self._authkey_hex = authkey_hex
        self._setup = list(setup_commands or [])
        self._types: Dict[str, str] = {}
        self._n = 0

    def _gcloud(self, *args: str, timeout: float = 900.0) -> str:
        cmd = ["gcloud", "compute", "tpus", *args,
               f"--project={self._conf['project']}",
               f"--zone={self._conf['zone']}", "--format=json"]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode != 0:
            raise RuntimeError(
                f"gcloud {' '.join(args)} failed: {out.stderr[-1500:]}")
        return out.stdout

    def create_node(self, node_type: str) -> str:
        self._n += 1
        name = f"raytpu-{self._conf.get('cluster_name', 'c')}-" \
               f"{node_type}-{self._n}"
        acc = self.node_types[node_type].get(
            "accelerator_type", self._conf.get("accelerator_type"))
        rv = self._conf.get("runtime_version", "tpu-ubuntu2204-base")
        if self._conf.get("queued_resources"):
            # Queued resources: capacity arrives asynchronously — the
            # node exists only once the queue grants it, so bootstrap
            # must wait for READY (bounded; a still-queued node is left
            # tracked so `down` releases the queued resource).
            self._gcloud(
                "queued-resources", "create", name,
                f"--node-id={name}", f"--accelerator-type={acc}",
                f"--runtime-version={rv}")
            self._types[name] = node_type  # track BEFORE the wait
            self._wait_ready(name, float(self._conf.get(
                "queued_resources_timeout_s", 1800)))
        else:
            self._gcloud("tpu-vm", "create", name,
                         f"--accelerator-type={acc}",
                         f"--runtime-version={rv}")
            self._types[name] = node_type
        self._bootstrap(name, node_type)
        return name

    def _wait_ready(self, name: str, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                node = json.loads(self._gcloud("tpu-vm", "describe",
                                               name))
                if node.get("state") == "READY":
                    return
            except RuntimeError:
                pass  # not materialized yet
            time.sleep(15.0)
        raise RuntimeError(
            f"queued resource {name} not READY after {timeout_s:.0f}s "
            f"(still tracked; `ray_tpu down` releases it)")

    AUTHKEY_REMOTE_PATH = "~/.ray_tpu_authkey"

    def _push_authkey(self, name: str):
        """Deliver the cluster authkey as a 0600 file over scp.  It must
        NEVER ride the remote command line: ``--command="RAY_TPU_CLIENT_
        AUTHKEY=<hex> ..."`` lands the key in the remote shell's argv —
        visible to every local user via ``ps`` and in shell/audit logs
        on the TPU VM."""
        import tempfile

        fd, tmp = tempfile.mkstemp(prefix="rtpu-authkey-")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(self._authkey_hex + "\n")
            subprocess.run(
                ["gcloud", "compute", "tpus", "tpu-vm", "scp", tmp,
                 f"{name}:{self.AUTHKEY_REMOTE_PATH}",
                 f"--project={self._conf['project']}",
                 f"--zone={self._conf['zone']}", "--worker=all"],
                capture_output=True, text=True, timeout=900.0, check=True)
        finally:
            os.unlink(tmp)

    def _bootstrap(self, name: str, node_type: str):
        """Run setup commands + start the node agent on every slice host
        (``--worker=all`` — a multi-host slice joins with one agent per
        host, each owning its local chips).  The authkey arrives as a
        0600 file (scp, above); the agent command only references the
        file, so the literal ``$(cat ...)`` — not the key — is what
        appears in process listings."""
        self._push_authkey(name)
        r = self.node_types[node_type]["resources"]
        key_file = self.AUTHKEY_REMOTE_PATH
        agent_cmd = (
            f"chmod 600 {key_file} && "
            f"RAY_TPU_CLIENT_AUTHKEY=$(cat {key_file}) "
            f"python3 -m ray_tpu.scripts agent "
            f"--address {self._head_address} "
            f"--num-cpus {r.get('CPU', 1)} "
            f"--num-tpus {r.get('TPU', 0)} "
            f"</dev/null >/tmp/ray_tpu_agent.log 2>&1 &")
        script = " && ".join(self._setup + [agent_cmd]) \
            if self._setup else agent_cmd
        if self._authkey_hex in script:  # belt + suspenders: the guard
            # must survive `python -O` (assert would be compiled out)
            raise RuntimeError(
                "cluster authkey leaked into the remote command line")
        subprocess.run(
            ["gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
             f"--project={self._conf['project']}",
             f"--zone={self._conf['zone']}", "--worker=all",
             f"--command={script}"],
            capture_output=True, text=True, timeout=900.0, check=True)

    def terminate_node(self, node_id: str) -> None:
        self._types.pop(node_id, None)
        if self._conf.get("queued_resources"):
            self._gcloud("queued-resources", "delete", node_id,
                         "--force")
        else:
            self._gcloud("tpu-vm", "delete", node_id, "--quiet")

    def non_terminated_nodes(self) -> List[str]:
        nodes = json.loads(self._gcloud("tpu-vm", "list"))
        live = {n["name"].rsplit("/", 1)[-1] for n in nodes
                if n.get("state") in ("READY", "CREATING")}
        return [nid for nid in self._types if nid in live]

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._types.get(node_id)


# --------------------------------------------------------------- commands --
def _state_path(name: str) -> str:
    # The state dir holds cluster authkeys: owner-only, like ~/.ssh.
    os.makedirs(STATE_DIR, mode=0o700, exist_ok=True)
    try:
        os.chmod(STATE_DIR, 0o700)  # pre-existing dir from an older run
    except OSError:
        pass
    return os.path.join(STATE_DIR, f"{name}.json")


def _write_state(state_file: str, state: Dict[str, Any]) -> None:
    """Write the cluster state file with mode 0600: it carries the
    cluster authkey, which a world-readable file would hand to every
    local user (the cluster trusts any dialer holding it)."""
    fd = os.open(state_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        json.dump(state, f)
    try:
        os.chmod(state_file, 0o600)  # file may predate this hardening
    except OSError:
        pass


def _load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path, encoding="utf-8") as f:
        cfg = yaml.safe_load(f)
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("provider", {"type": "subprocess"})
    cfg.setdefault("head", {})
    cfg.setdefault("worker_types", {})
    return cfg


def _make_provider(cfg: Dict[str, Any], head_address: str,
                   authkey_hex: str) -> NodeProvider:
    ptype = cfg["provider"].get("type", "subprocess")
    if ptype == "subprocess":
        return SubprocessAgentProvider(cfg["worker_types"], head_address,
                                       authkey_hex)
    if ptype == "gcp_tpu":
        conf = dict(cfg["provider"],
                    cluster_name=cfg["cluster_name"])
        return GCPTpuProvider(cfg["worker_types"], conf, head_address,
                              authkey_hex,
                              cfg.get("setup_commands"))
    raise ValueError(f"unknown provider type {ptype!r}")


def up(config_path: str) -> Dict[str, Any]:
    """create_or_update_cluster: start the head process, then launch
    every worker type's min_workers (reference: commands.py:
    create_or_update_cluster -> get_or_create_head_node + updaters)."""
    cfg = _load_config(config_path)
    name = cfg["cluster_name"]
    state_file = _state_path(name)
    if os.path.exists(state_file):
        state = json.load(open(state_file, encoding="utf-8"))
        if _head_alive(state):
            print(f"cluster {name!r} already up at {state['address']}")
            return state
    ptype = cfg["provider"].get("type", "subprocess")
    bind_host = cfg["head"].get("host", "127.0.0.1")
    # The address worker nodes DIAL.  Cloud nodes cannot reach loopback:
    # require a routable advertise host rather than billing TPU VMs that
    # can never join.
    adv_host = cfg["head"].get("advertise_host", bind_host)
    if ptype == "gcp_tpu" and adv_host.startswith("127."):
        raise ValueError(
            "gcp_tpu clusters need head.host/head.advertise_host set to "
            "an address the TPU VMs can reach (and head.host should "
            "usually be 0.0.0.0)")
    authkey_hex = os.urandom(16).hex()
    port = int(cfg["head"].get("port", 0)) or _free_port()
    head_env = dict(os.environ, JAX_PLATFORMS="cpu")
    head_env.pop("PALLAS_AXON_POOL_IPS", None)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    head_env["PYTHONPATH"] = pkg_root + os.pathsep + head_env.get(
        "PYTHONPATH", "")
    head_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts", "head",
         "--num-cpus", str(cfg["head"].get("num_cpus", 4)),
         "--port", str(port), "--authkey", authkey_hex,
         "--host", bind_host],
        env=head_env)
    address = f"tcp://{adv_host}:{port}"
    local_address = f"tcp://127.0.0.1:{port}"
    _wait_head(local_address, authkey_hex, head_proc)
    # State lands BEFORE worker launches: a failed create_node must
    # leave a state file so `down` can clean up the head and any nodes
    # already created.
    state = {
        "cluster_name": name, "address": address,
        "local_address": local_address,
        "authkey": authkey_hex, "head_pid": head_proc.pid,
        "nodes": [], "config_path": os.path.abspath(config_path),
        "provider_type": ptype, "agent_pids": {},
    }
    _write_state(state_file, state)
    provider = _make_provider(cfg, address, authkey_hex)
    try:
        for node_type, spec in cfg["worker_types"].items():
            for _ in range(int(spec.get("min_workers", 0))):
                state["nodes"].append(
                    {"id": provider.create_node(node_type),
                     "type": node_type})
    finally:
        state["agent_pids"] = (
            provider.pids() if isinstance(provider,
                                          SubprocessAgentProvider)
            else {})
        _write_state(state_file, state)
    print(f"cluster {name!r} up: {address} "
          f"(head pid {head_proc.pid}, "
          f"{len(state['nodes'])} worker node(s))")
    return state


def down(config_path: str) -> None:
    """teardown_cluster (reference: commands.py teardown_cluster)."""
    cfg = _load_config(config_path)
    state_file = _state_path(cfg["cluster_name"])
    if not os.path.exists(state_file):
        print(f"cluster {cfg['cluster_name']!r} is not up")
        return
    state = json.load(open(state_file, encoding="utf-8"))
    if state.get("provider_type") == "gcp_tpu":
        provider = _make_provider(cfg, state["address"], state["authkey"])
        for n in state.get("nodes", []):
            provider._types[n["id"]] = n["type"]  # rebuild tracking
            try:
                provider.terminate_node(n["id"])
            except Exception as e:  # noqa: BLE001
                print(f"  terminate {n['id']}: {e}")
    for pid in state.get("agent_pids", {}).values():
        _kill_pid(pid)
    _kill_pid(state.get("head_pid"))
    os.unlink(state_file)
    print(f"cluster {cfg['cluster_name']!r} down")


def _cluster_env(state: Dict[str, Any]) -> Dict[str, str]:
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ,
               RAY_TPU_ADDRESS=state["address"],
               RAY_TPU_CLIENT_AUTHKEY=state["authkey"])
    env["PYTHONPATH"] = pkg_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def exec_cmd(config_path: str, command: str) -> int:
    """exec_cluster: run a shell command wired to the cluster
    (RAY_TPU_ADDRESS / RAY_TPU_CLIENT_AUTHKEY set, as the reference sets
    RAY_ADDRESS)."""
    return subprocess.call(command, shell=True,
                           env=_cluster_env(_require_state(config_path)))


def attach(config_path: str) -> int:
    """attach_cluster: an interactive shell wired to the cluster."""
    state = _require_state(config_path)
    env = _cluster_env(state)
    shell = os.environ.get("SHELL", "/bin/sh")
    print(f"attached to {state['cluster_name']!r} at {state['address']} "
          f"(exit the shell to detach)")
    return subprocess.call([shell], env=env)


def _require_state(config_path: str) -> Dict[str, Any]:
    cfg = _load_config(config_path)
    state_file = _state_path(cfg["cluster_name"])
    if not os.path.exists(state_file):
        raise SystemExit(f"cluster {cfg['cluster_name']!r} is not up "
                         f"(run: ray_tpu up {config_path})")
    return json.load(open(state_file, encoding="utf-8"))


def _head_alive(state: Dict[str, Any]) -> bool:
    try:
        os.kill(state["head_pid"], 0)
        return True
    except (OSError, KeyError):
        return False


def _kill_pid(pid):
    if not pid:
        return
    try:
        os.kill(pid, signal.SIGTERM)
    except OSError:
        pass


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_head(address: str, authkey_hex: str, proc,
               timeout: float = 60.0):
    from ray_tpu._private.client import client_connect

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"head process exited with {proc.returncode}")
        try:
            rt = client_connect(address, bytes.fromhex(authkey_hex))
            rt.disconnect()
            return
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.5)
    raise RuntimeError(f"head never came up at {address}: {last!r}")
