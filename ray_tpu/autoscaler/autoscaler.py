"""StandardAutoscaler: load signals -> node-count decisions.

Reference: ``python/ray/autoscaler/_private/autoscaler.py:168``
(StandardAutoscaler.update: read load, bin-pack demand onto node types,
launch/terminate) + ``resource_demand_scheduler.py`` (first-fit packing).
Condensed: demand comes from the runtime's queued-but-unplaced shapes
(`pending_resource_demand` — which since the elastic-pods PR also
carries parked client-lease requests, the lease-starvation signal the
task queues never show), utilization from `node_activity`, and the loop
either runs on a timer, is stepped manually (`update()`), or is woken
early by a serve-controller scale event (the head's "serve_scale"
pubsub topic).

Slice-atomicity is inherited from the provider: one launch == one whole
TPU slice; scale-down terminates whole idle slices only — and routes
through the head's drain protocol (``Runtime.drain_node``: leases
revoked, restartable actors checkpointed to a surviving store, small
sole-copy objects migrated) before ``terminate_node``, so a planned
departure is never a surprise death.  Spot/preemptible node types
(``"spot": True`` in the type spec) are preferred when they fit; after
``spot_fallback_threshold`` observed preemptions of a type the planner
falls back to its on-demand peers.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(avail: Dict[str, float], shape: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in shape.items())


def _take(avail: Dict[str, float], shape: Dict[str, float]):
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, runtime, provider: NodeProvider,
                 idle_timeout_s: float = 10.0,
                 update_interval_s: float = 2.0,
                 spot_fallback_threshold: Optional[int] = None,
                 drain_deadline_s: Optional[float] = None):
        self._rt = runtime
        self.provider = provider
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self._idle_since: Dict[str, float] = {}
        # Launches issued but whose node has not registered alive yet:
        # counted against caps and capacity so an async provider cannot
        # be asked twice for the same demand (reference: the pending-
        # launch accounting in StandardAutoscaler).
        self._pending_launches: Dict[str, tuple] = {}  # id -> (type, ts)
        self._launch_timeout_s = 120.0
        # Every node this scaler launched that is still provider-alive:
        # id -> type.  A tracked node that turns up dead WITHOUT us
        # terminating it was preempted — the per-type spot accounting.
        self._tracked: Dict[str, str] = {}
        cfg = getattr(runtime, "config", None)
        if cfg is None:
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        self._elastic_drain = bool(getattr(cfg, "elastic_drain", False))
        self._drain_deadline_s = (
            float(drain_deadline_s) if drain_deadline_s is not None
            else float(getattr(cfg, "drain_deadline_s", 10.0)))
        self.spot_fallback_threshold = (
            int(spot_fallback_threshold)
            if spot_fallback_threshold is not None
            else int(getattr(cfg, "spot_fallback_threshold", 2)))
        # Observability (satellite: the silent monitor loop): errors are
        # counted + rate-limit-logged, never swallowed; surfaced next to
        # the elastic counters via stats().
        self._errors = 0
        self._last_err_log = 0.0
        self._err_log_interval_s = 5.0
        self._preemptions: Dict[str, int] = {}   # node_type -> count
        self._drains_requested = 0
        self._drains_completed = 0
        self._serve_scale_events = 0
        # Nodes whose scale-down drain is running off-thread: skipped by
        # the idle loop until the drain concludes and terminates them.
        self._draining_down: set = set()
        # One reconcile at a time (satellite): the background loop, a
        # manual update(), and the serve-event trigger must not
        # interleave — two concurrent ticks each see the same
        # unfulfilled demand and both launch for it.
        self._update_lock = threading.Lock()
        self._wake = threading.Event()
        self._stopped = False
        self._gen = 0
        self._thread: Optional[threading.Thread] = None
        self._listener_on = False

    # ------------------------------------------------------------- policy
    def _unfulfilled_demand(self) -> List[Dict[str, float]]:
        """Queued shapes that the current cluster cannot place even when
        fully free — first-fit over every alive node's TOTAL resources
        (reference: infeasible + backlog demand fed to the bin-packer).
        Draining nodes take no new placements, so they contribute no
        capacity here."""
        demand = self._rt.pending_resource_demand()
        if not demand:
            return []
        free = [dict(n["resources"]) for n in self._rt.node_activity()
                if n["alive"] and not n.get("draining")]
        # Nodes still booting count as capacity-to-be.
        for _nid, (ntype, _ts) in self._pending_launches.items():
            free.append(dict(self.provider.node_resources(ntype)))
        unfulfilled = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            for avail in free:
                if _fits(avail, shape):
                    _take(avail, shape)
                    break
            else:
                unfulfilled.append(shape)
        return unfulfilled

    def _type_order(self) -> List[str]:
        """Launch-preference order over the provider's catalog: healthy
        SPOT types first (cheap capacity while the preemption rate is
        tolerable), then on-demand, then spot types past the fallback
        threshold — still eligible when nothing else fits, but no
        longer preferred (reference: the spot-fallback behavior of
        cloud autoscaler node-type selection)."""
        def rank(t: str) -> int:
            if not self.provider.is_spot(t):
                return 1
            if self._preemptions.get(t, 0) >= self.spot_fallback_threshold:
                return 2
            return 0

        return sorted(self.provider.node_types, key=rank)

    def _plan_launches(self, unfulfilled) -> Dict[str, int]:
        """First-fit-decreasing the unfulfilled shapes onto fresh nodes of
        each type (reference: resource_demand_scheduler.get_nodes_for)."""
        launches: Dict[str, int] = {}
        pools: List[Dict[str, float]] = []
        counts = {t: len([n for n in self.provider.non_terminated_nodes()
                          if self.provider.node_type_of(n) == t])
                  + len([1 for _ntype, _ in self._pending_launches.values()
                         if _ntype == t])
                  for t in self.provider.node_types}
        for shape in unfulfilled:
            placed = False
            for avail in pools:
                if _fits(avail, shape):
                    _take(avail, shape)
                    placed = True
                    break
            if placed:
                continue
            # pick the first (spot-preferred) type that can hold the shape
            for t in self._type_order():
                res = self.provider.node_resources(t)
                if _fits(res, shape) and \
                        counts[t] + launches.get(t, 0) \
                        < self.provider.max_workers(t):
                    avail = dict(res)
                    _take(avail, shape)
                    pools.append(avail)
                    launches[t] = launches.get(t, 0) + 1
                    break
            # shapes no type can hold stay infeasible (reference: warn)
        return launches

    def _note_preemptions(self, alive_ids):
        """Per-type preemption accounting: a tracked node that died
        without us terminating it was taken away (agent SIGKILL, spot
        reclaim).  Counted against its type for the fallback policy,
        then cleaned out of the provider's books (terminate_node on a
        dead node is idempotent bookkeeping, as on a real cloud)."""
        for nid, ntype in list(self._tracked.items()):
            if nid in alive_ids or nid in self._pending_launches \
                    or nid in self._draining_down:
                continue
            self._tracked.pop(nid, None)
            self._preemptions[ntype] = self._preemptions.get(ntype, 0) + 1
            self._idle_since.pop(nid, None)
            try:
                self.provider.terminate_node(nid)
            except Exception:
                pass

    def _scale_down(self, nid: str):
        """Idle scale-down — through the drain protocol when it is on
        (leases revoked, actors checkpointed, small sole-copy objects
        migrated, agent released cleanly), with ``terminate_node`` as
        both the completion and the hard fallback.  The drain runs
        OFF-THREAD: a reconcile tick must stay reactive (a serve
        scale-up event cannot wait out a drain deadline), so update()
        reports the node terminated now and the terminate itself
        follows the drain's conclusion.  Off-switch
        (``elastic_drain=False``) is the legacy inline bare terminate."""
        # Planned departure: never let _note_preemptions count it.
        self._tracked.pop(nid, None)
        drain = getattr(self._rt, "drain_node", None)
        if not (self._elastic_drain and drain is not None):
            self.provider.terminate_node(nid)
            return
        self._drains_requested += 1
        self._draining_down.add(nid)

        def run():
            try:
                try:
                    drained = bool(drain(nid, self._drain_deadline_s,
                                         "scale_down"))
                except Exception:
                    drained = False
                if drained:
                    # Off-thread += races a concurrent drain's (and the
                    # GIL does not make LOAD/ADD/STORE atomic): count
                    # under the same lock stats() readers already see
                    # consistent state through.
                    with self._update_lock:
                        self._drains_completed += 1
                try:
                    self.provider.terminate_node(nid)
                except Exception:
                    pass
            finally:
                self._draining_down.discard(nid)

        threading.Thread(target=run, daemon=True,
                         name="ray_tpu-scale-down").start()

    def update(self) -> Dict[str, Any]:
        """One reconcile tick: launch for unfulfilled demand, terminate
        slices idle past the timeout.  Returns what it did.  Serialized
        by ``_update_lock`` — the loop, manual callers, and the serve
        trigger can never double-launch against one demand snapshot."""
        with self._update_lock:
            return self._update_locked()

    def _update_locked(self) -> Dict[str, Any]:
        # Drain the serve-event topic (the wake already happened; the
        # events themselves are the observability trail).
        poll = getattr(self._rt, "poll_events", None)
        if poll is not None:
            try:
                self._serve_scale_events += len(poll("serve_scale"))
            except Exception:
                pass
        # Reconcile pending launches first: registered or timed out.
        now0 = time.monotonic()
        alive_ids = {a["node_id"] for a in self._rt.node_activity()
                     if a["alive"]}
        for nid in list(self._pending_launches):
            ntype, ts = self._pending_launches[nid]
            if nid in alive_ids:
                self._pending_launches.pop(nid, None)
            elif now0 - ts > self._launch_timeout_s:
                # Never came up: cancel it at the provider (a stuck
                # instance left behind both leaks money and keeps
                # counting against max_workers) and stop counting it
                # against caps/capacity, so the demand it was meant to
                # cover is re-planned — the re-issue happens in the
                # launch pass below.
                self._pending_launches.pop(nid, None)
                self._tracked.pop(nid, None)
                try:
                    self.provider.terminate_node(nid)
                except Exception:
                    pass
        self._note_preemptions(alive_ids)
        launched: List[str] = []
        for node_type, n in self._plan_launches(
                self._unfulfilled_demand()).items():
            for _ in range(n):
                nid = self.provider.create_node(node_type)
                launched.append(nid)
                self._pending_launches[nid] = (node_type, now0)
                self._tracked[nid] = node_type
        # scale-down: whole idle provider nodes only (never the head)
        now = time.monotonic()
        terminated: List[str] = []
        activity = {a["node_id"]: a for a in self._rt.node_activity()}
        # Only SATISFIABLE demand vetoes scale-down: a shape no alive node
        # and no node type could ever hold must not pin idle slices.
        demand_left = [
            shape for shape in self._rt.pending_resource_demand()
            if any(_fits(a["resources"], shape)
                   for a in activity.values()
                   if a["alive"] and not a.get("draining"))
            or any(_fits(self.provider.node_resources(t), shape)
                   for t in self.provider.node_types)]
        for nid in list(self.provider.non_terminated_nodes()):
            a = activity.get(nid)
            if a is None or a["is_head"]:
                continue
            if a.get("draining") or nid in self._draining_down:
                # Already on its way out (our own off-thread scale-down,
                # or a preemption drain the head is running): a second
                # pick here would hard-terminate it mid-migration.
                self._idle_since.pop(nid, None)
                continue
            if a["busy"] or demand_left:
                self._idle_since.pop(nid, None)
                continue
            first_idle = self._idle_since.setdefault(nid, now)
            if now - first_idle >= self.idle_timeout_s:
                self._scale_down(nid)
                self._idle_since.pop(nid, None)
                terminated.append(nid)
        return {"launched": launched, "terminated": terminated}

    def stats(self) -> Dict[str, Any]:
        """Elastic observability: loop errors (satellite: the monitor
        loop no longer swallows them silently), per-type preemption
        counts feeding the spot fallback, drain outcomes, and the
        serve-event trigger count — read next to the head's
        transfer_stats() elastic counters."""
        return {
            "autoscaler_errors": self._errors,
            "preemptions_by_type": dict(self._preemptions),
            "drains_requested": self._drains_requested,
            "drains_completed": self._drains_completed,
            "serve_scale_events": self._serve_scale_events,
            "pending_launches": len(self._pending_launches),
        }

    # -------------------------------------------------------------- loop
    def request_update(self):
        """Wake the background loop for an immediate reconcile (the
        serve-controller scale-event trigger).  No-op without start()."""
        self._wake.set()

    def start(self):
        """Background monitor loop (reference: monitor.py's driver)."""
        if self._thread is not None:
            return
        self._stopped = False
        self._wake.clear()  # a stale stop()-wake must not fire an early tick
        # Serve-event trigger: a controller scale event wakes the loop
        # immediately (the listener only nudges; the tick itself drains
        # the topic and reconciles).  Registered for the loop's
        # lifetime only — stop() unhooks it, so a stopped scaler is not
        # referenced (and woken) by the runtime forever.
        if not self._listener_on:
            add_listener = getattr(self._rt, "add_event_listener", None)
            if add_listener is not None:
                try:
                    add_listener("serve_scale", self.request_update)
                    self._listener_on = True
                except Exception:
                    pass
        self._gen += 1
        gen = self._gen

        def loop():
            # Generation check: a stop()+start() inside one sleep interval
            # must not leave the superseded loop running alongside.
            while not self._stopped and self._gen == gen:
                self._wake.wait(self.update_interval_s)
                self._wake.clear()
                if self._stopped or self._gen != gen:
                    return
                try:
                    self.update()
                except Exception:
                    # Monitor loops must survive anything — but silence
                    # turned real launch failures into "the cluster just
                    # never scales": count every error and log at most
                    # one traceback per interval.
                    self._errors += 1
                    now = time.monotonic()
                    if now - self._last_err_log \
                            >= self._err_log_interval_s:
                        self._last_err_log = now
                        import sys
                        import traceback

                        print("[ray_tpu autoscaler] update failed "
                              f"({self._errors} total):",
                              file=sys.stderr)
                        traceback.print_exc()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ray_tpu-autoscaler")
        self._thread.start()

    def stop(self):
        self._stopped = True
        self._gen += 1
        self._wake.set()
        self._thread = None
        if self._listener_on:
            remove = getattr(self._rt, "remove_event_listener", None)
            if remove is not None:
                try:
                    remove("serve_scale", self.request_update)
                except Exception:
                    pass
            self._listener_on = False
