"""StandardAutoscaler: pending demand -> node-count decisions.

Reference: ``python/ray/autoscaler/_private/autoscaler.py:168``
(StandardAutoscaler.update: read load, bin-pack demand onto node types,
launch/terminate) + ``resource_demand_scheduler.py`` (first-fit packing).
Condensed: demand comes straight from the runtime's queued-but-unplaced
shapes (`pending_resource_demand`), utilization from `node_activity`, and
the loop either runs on a timer or is stepped manually (`update()`), which
is how the reference tests it against the fake provider.

Slice-atomicity is inherited from the provider: one launch == one whole
TPU slice; scale-down terminates whole idle slices only.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(avail: Dict[str, float], shape: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in shape.items())


def _take(avail: Dict[str, float], shape: Dict[str, float]):
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, runtime, provider: NodeProvider,
                 idle_timeout_s: float = 10.0,
                 update_interval_s: float = 2.0):
        self._rt = runtime
        self.provider = provider
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self._idle_since: Dict[str, float] = {}
        # Launches issued but whose node has not registered alive yet:
        # counted against caps and capacity so an async provider cannot
        # be asked twice for the same demand (reference: the pending-
        # launch accounting in StandardAutoscaler).
        self._pending_launches: Dict[str, tuple] = {}  # id -> (type, ts)
        self._launch_timeout_s = 120.0
        self._stopped = False
        self._gen = 0
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- policy
    def _unfulfilled_demand(self) -> List[Dict[str, float]]:
        """Queued shapes that the current cluster cannot place even when
        fully free — first-fit over every alive node's TOTAL resources
        (reference: infeasible + backlog demand fed to the bin-packer)."""
        demand = self._rt.pending_resource_demand()
        if not demand:
            return []
        free = [dict(n["resources"]) for n in self._rt.node_activity()
                if n["alive"]]
        # Nodes still booting count as capacity-to-be.
        for _nid, (ntype, _ts) in self._pending_launches.items():
            free.append(dict(self.provider.node_resources(ntype)))
        unfulfilled = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            for avail in free:
                if _fits(avail, shape):
                    _take(avail, shape)
                    break
            else:
                unfulfilled.append(shape)
        return unfulfilled

    def _plan_launches(self, unfulfilled) -> Dict[str, int]:
        """First-fit-decreasing the unfulfilled shapes onto fresh nodes of
        each type (reference: resource_demand_scheduler.get_nodes_for)."""
        launches: Dict[str, int] = {}
        pools: List[Dict[str, float]] = []
        counts = {t: len([n for n in self.provider.non_terminated_nodes()
                          if self.provider.node_type_of(n) == t])
                  + len([1 for _ntype, _ in self._pending_launches.values()
                         if _ntype == t])
                  for t in self.provider.node_types}
        for shape in unfulfilled:
            placed = False
            for avail in pools:
                if _fits(avail, shape):
                    _take(avail, shape)
                    placed = True
                    break
            if placed:
                continue
            # pick the first node type that can hold the shape at all
            for t in self.provider.node_types:
                res = self.provider.node_resources(t)
                if _fits(res, shape) and \
                        counts[t] + launches.get(t, 0) \
                        < self.provider.max_workers(t):
                    avail = dict(res)
                    _take(avail, shape)
                    pools.append(avail)
                    launches[t] = launches.get(t, 0) + 1
                    break
            # shapes no type can hold stay infeasible (reference: warn)
        return launches

    def update(self) -> Dict[str, Any]:
        """One reconcile tick: launch for unfulfilled demand, terminate
        slices idle past the timeout.  Returns what it did."""
        # Reconcile pending launches first: registered or timed out.
        now0 = time.monotonic()
        alive_ids = {a["node_id"] for a in self._rt.node_activity()
                     if a["alive"]}
        for nid in list(self._pending_launches):
            ntype, ts = self._pending_launches[nid]
            if nid in alive_ids or now0 - ts > self._launch_timeout_s:
                self._pending_launches.pop(nid, None)
        launched: List[str] = []
        for node_type, n in self._plan_launches(
                self._unfulfilled_demand()).items():
            for _ in range(n):
                nid = self.provider.create_node(node_type)
                launched.append(nid)
                self._pending_launches[nid] = (node_type, now0)
        # scale-down: whole idle provider nodes only (never the head)
        now = time.monotonic()
        terminated: List[str] = []
        activity = {a["node_id"]: a for a in self._rt.node_activity()}
        # Only SATISFIABLE demand vetoes scale-down: a shape no alive node
        # and no node type could ever hold must not pin idle slices.
        demand_left = [
            shape for shape in self._rt.pending_resource_demand()
            if any(_fits(a["resources"], shape)
                   for a in activity.values() if a["alive"])
            or any(_fits(self.provider.node_resources(t), shape)
                   for t in self.provider.node_types)]
        for nid in list(self.provider.non_terminated_nodes()):
            a = activity.get(nid)
            if a is None or a["is_head"]:
                continue
            if a["busy"] or demand_left:
                self._idle_since.pop(nid, None)
                continue
            first_idle = self._idle_since.setdefault(nid, now)
            if now - first_idle >= self.idle_timeout_s:
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
                terminated.append(nid)
        return {"launched": launched, "terminated": terminated}

    # -------------------------------------------------------------- loop
    def start(self):
        """Background monitor loop (reference: monitor.py's driver)."""
        if self._thread is not None:
            return
        self._stopped = False
        self._gen += 1
        gen = self._gen

        def loop():
            # Generation check: a stop()+start() inside one sleep interval
            # must not leave the superseded loop running alongside.
            while not self._stopped and self._gen == gen:
                time.sleep(self.update_interval_s)
                if self._stopped or self._gen != gen:
                    return
                try:
                    self.update()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ray_tpu-autoscaler")
        self._thread.start()

    def stop(self):
        self._stopped = True
        self._gen += 1
        self._thread = None
