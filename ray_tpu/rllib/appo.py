"""APPO: asynchronous PPO on the IMPALA actor-learner skeleton.

Reference: ``rllib/algorithms/appo/appo.py`` — IMPALA's asynchronous
sampling pipeline, but the learner optimizes the PPO clipped surrogate
on V-trace-corrected advantages against a periodically-synced TARGET
policy (the reference updates it every ``target_update_frequency``
learner steps).  Staleness robustness comes from both mechanisms:
V-trace reweights old trajectories; the clip bounds the update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as ray
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, OBS, REWARDS,
)
from ray_tpu.rllib.vtrace import vtrace


def appo_loss(params, module, batch, *, gamma: float, clip_param: float,
              vf_coef: float, ent_coef: float, clip_rho: float,
              clip_c: float):
    """PPO clipped surrogate on V-trace advantages computed from the
    TARGET policy's values (rider in batch as 'target_logp'/'target_vs'
    precomputation happens learner-side for one jitted program)."""
    t, b = batch[ACTIONS].shape
    obs = batch[OBS].reshape(t * b, -1)
    logits, values = module.apply(params, obs)
    logits = logits.reshape(t, b, -1)
    values = values.reshape(t, b)
    logp_all = jax.nn.log_softmax(logits)
    cur_logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][..., None].astype(jnp.int32), -1)[..., 0]
    _, bootstrap = module.apply(params, batch["bootstrap_obs"])
    discounts = gamma * (1.0 - batch[DONES].astype(jnp.float32))
    # V-trace targets/advantages from the TARGET policy's logp (stop-
    # gradient semantics: target params produced these outside the jit).
    vt = vtrace(batch[LOGP], batch["target_logp"], batch[REWARDS],
                batch["target_values"], batch["target_bootstrap"],
                discounts, clip_rho, clip_c)
    ratio = jnp.exp(cur_logp - batch[LOGP])
    adv = vt.pg_advantages
    surrogate = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
    pi_loss = -jnp.mean(surrogate)
    vf_loss = jnp.mean((values - vt.vs) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                  "entropy": entropy,
                  "mean_ratio": jnp.mean(ratio)}


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.3
        self.target_update_frequency = 4  # learner updates per sync

    @property
    def algo_class(self):
        return APPO


class APPO(Impala):
    """reference: appo.py:51 APPO(Impala)."""

    config_class = APPOConfig

    def _setup(self, cfg: APPOConfig):
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        if hasattr(env, "close"):
            env.close()
        model_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        self._obs_dim = obs_dim
        self.workers = WorkerSet(
            cfg.env_maker, model_config, cfg.num_rollout_workers,
            cfg.num_envs_per_worker, gamma=cfg.gamma)
        module = ActorCriticMLP(**model_config)
        self._module = module

        def loss(params, mod, batch):
            return appo_loss(params, mod, batch, gamma=cfg.gamma,
                             clip_param=cfg.clip_param,
                             vf_coef=cfg.vf_loss_coeff,
                             ent_coef=cfg.entropy_coeff,
                             clip_rho=cfg.clip_rho_threshold,
                             clip_c=cfg.clip_c_threshold)

        self.learner_group = LearnerGroup(lambda: Learner(
            module, loss, optimizer=optax.chain(
                optax.clip_by_global_norm(cfg.grad_clip),
                optax.adam(cfg.lr)), seed=cfg.seed))
        self._target_params = jax.tree.map(
            jnp.copy, self.learner_group.get_weights())
        self._updates_since_target_sync = 0

        def target_fwd(params, obs_flat, actions, bootstrap_obs):
            logits, values = module.apply(params, obs_flat)
            logp_all = jax.nn.log_softmax(logits)
            tl = jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
            _, bs = module.apply(params, bootstrap_obs)
            return tl, values, bs

        self._target_fwd = jax.jit(target_fwd)
        w = self.learner_group.get_weights()
        self.workers.sync_weights(w)
        from ray_tpu.remote_function import _bulk_submit
        sample_futs = _bulk_submit([
            (worker.sample, (cfg.rollout_fragment_length,), None)
            for worker in self.workers.workers])
        self._inflight = {fut: i for i, fut in enumerate(sample_futs)}

    def _augment_with_target(self, tm: Dict[str, Any]) -> Dict[str, Any]:
        t, b = tm[ACTIONS].shape
        obs = jnp.asarray(tm[OBS].reshape(t * b, -1))
        tl, tv, bs = self._target_fwd(
            self._target_params, obs,
            jnp.asarray(tm[ACTIONS].reshape(t * b)),
            jnp.asarray(tm["bootstrap_obs"]))
        tm["target_logp"] = np.asarray(tl).reshape(t, b)
        tm["target_values"] = np.asarray(tv).reshape(t, b)
        tm["target_bootstrap"] = np.asarray(bs)
        return tm

    def training_step(self) -> Dict[str, Any]:
        cfg: APPOConfig = self.algo_config
        from ray_tpu.rllib.sample_batch import SampleBatch

        metrics: Dict[str, Any] = {}
        steps = 0
        processed = 0
        while processed < cfg.max_batches_per_step and self._inflight:
            done, _ = ray.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
            if not done:
                break
            fut = done[0]
            idx = self._inflight.pop(fut)
            worker = self.workers.workers[idx]
            try:
                flat = ray.get(fut)
            except Exception:
                worker = self.workers.recreate(idx)
                self._resubmit(worker, idx)
                continue
            tm = self._to_time_major(flat, cfg.rollout_fragment_length)
            tm = self._augment_with_target(tm)
            metrics = self.learner_group.update(SampleBatch(tm))
            steps += len(flat)
            processed += 1
            self._updates_since_target_sync += 1
            if self._updates_since_target_sync >= \
                    cfg.target_update_frequency:
                self._target_params = jax.tree.map(
                    jnp.copy, self.learner_group.get_weights())
                self._updates_since_target_sync = 0
            self._resubmit(worker, idx)
        returns = self.workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
        metrics["num_env_steps_sampled"] = steps
        return metrics
