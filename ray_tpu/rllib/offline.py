"""Offline RL: logged-experience IO, behavior cloning, MARWIL, discrete
CQL, and off-policy estimators.

Reference surface: ``rllib/offline/json_reader.py`` / ``json_writer.py``
(JSON-lines SampleBatch IO), ``rllib/offline/estimators/
importance_sampling.py`` + ``weighted_importance_sampling.py`` (per-episode
IS/WIS value estimates from behavior-logged action probs), and the
algorithms ``rllib/algorithms/bc/``, ``rllib/algorithms/marwil/``,
``rllib/algorithms/cql/``.

TPU shape: readers yield numpy SampleBatches; every algorithm's update is
the same single jitted Learner program as the online stack — offline just
swaps the rollout fleet for a file/dataset reader (the reference does the
same through its ``input_`` config).
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS, SampleBatch,
    concat_batches,
)

_ARRAY_DTYPES = {OBS: np.float32, NEXT_OBS: np.float32,
                 ACTIONS: np.int32, REWARDS: np.float32,
                 LOGP: np.float32, DONES: bool}


class JsonWriter:
    """Append SampleBatches as JSON lines (reference: json_writer.py —
    one serialized batch per line, files rolled by size; we roll only on
    explicit ``new_file``)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def write(self, batch: SampleBatch):
        rec = {k: np.asarray(v).tolist() for k, v in batch.items()}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()


class JsonReader:
    """Cycle through logged batches (reference: json_reader.py:30 —
    ``next()`` returns one SampleBatch, looping over the input files
    forever; globs and directories accepted)."""

    def __init__(self, inputs: str, shuffle: bool = True, seed: int = 0):
        if os.path.isdir(inputs):
            paths = sorted(_glob.glob(os.path.join(inputs, "*.json")))
        else:
            paths = sorted(_glob.glob(inputs)) or [inputs]
        self._lines: List[str] = []
        for p in paths:
            with open(p, encoding="utf-8") as f:
                self._lines.extend(
                    ln for ln in f.read().splitlines() if ln.strip())
        if not self._lines:
            raise ValueError(f"No batches found in {inputs!r}")
        self._rng = np.random.default_rng(seed)
        self._shuffle = shuffle
        self._order: List[int] = []

    @staticmethod
    def _decode(line: str) -> SampleBatch:
        rec = json.loads(line)
        return SampleBatch({
            k: np.asarray(v, dtype=_ARRAY_DTYPES.get(k))
            for k, v in rec.items()})

    def next(self) -> SampleBatch:
        if not self._order:
            self._order = list(range(len(self._lines)))
            if self._shuffle:
                self._rng.shuffle(self._order)
            else:
                self._order.reverse()  # tail pops -> chronological order
        return self._decode(self._lines[self._order.pop()])

    def read_all(self) -> SampleBatch:
        return concat_batches([self._decode(ln) for ln in self._lines])

    def __iter__(self) -> Iterator[SampleBatch]:
        while True:
            yield self.next()


# --------------------------------------------------------------------------
# Off-policy estimators (reference: rllib/offline/estimators/*.py).
# --------------------------------------------------------------------------

def _episodes(batch: SampleBatch) -> List[SampleBatch]:
    """Split on done flags (reference: estimators operate per episode)."""
    dones = np.asarray(batch[DONES])
    ends = np.nonzero(dones)[0]
    out, start = [], 0
    for e in ends:
        out.append(batch.slice(start, int(e) + 1))
        start = int(e) + 1
    if start < len(dones):
        out.append(batch.slice(start, len(dones)))
    return out


class ImportanceSampling:
    """Ordinary importance sampling: V^pi ≈ mean_ep sum_t gamma^t
    (prod_{t'<=t} pi/mu) r_t (reference: importance_sampling.py)."""

    weighted = False

    def __init__(self, policy_logp_fn, gamma: float = 0.99):
        """``policy_logp_fn(obs, actions) -> logp`` under the TARGET
        policy; the batch's ``action_logp`` column is the behavior
        policy's logged prob."""
        self._logp = policy_logp_fn
        self._gamma = gamma

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        eps = _episodes(batch)
        # Per-episode cumulative ratios, padded to the longest horizon so
        # WIS can normalize across episodes at each t.
        horizon = max(len(e) for e in eps)
        cumr = np.zeros((len(eps), horizon), np.float64)
        rews = np.zeros((len(eps), horizon), np.float64)
        for i, ep in enumerate(eps):
            target_logp = np.asarray(
                self._logp(ep[OBS], ep[ACTIONS]), np.float64)
            ratio = np.exp(target_logp - np.asarray(ep[LOGP], np.float64))
            cumr[i, :len(ep)] = np.cumprod(ratio)
            rews[i, :len(ep)] = ep[REWARDS]
        disc = self._gamma ** np.arange(horizon)
        if self.weighted:
            norm = cumr.mean(axis=0)
            norm = np.where(norm > 0, norm, 1.0)
            v = (disc * cumr / norm * rews).sum(axis=1)
        else:
            v = (disc * cumr * rews).sum(axis=1)
        behavior = (disc * rews).sum(axis=1)
        return {
            "v_behavior": float(behavior.mean()),
            "v_target": float(v.mean()),
            "v_gain": float(v.mean() / (abs(behavior.mean()) + 1e-8)),
            "episodes": len(eps),
        }


class WeightedImportanceSampling(ImportanceSampling):
    """WIS: cumulative ratios normalized by their cross-episode mean at
    each step — biased but far lower variance (reference:
    weighted_importance_sampling.py)."""

    weighted = True


# --------------------------------------------------------------------------
# BC — behavior cloning (reference: rllib/algorithms/bc/bc.py: MARWIL
# with beta=0, pure -logp supervised loss).
# --------------------------------------------------------------------------

class OfflineAlgorithmConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.input_path: Optional[str] = None
        self.num_batches_per_step = 8

    def offline_data(self, *, input_path: str,
                     num_batches_per_step: Optional[int] = None
                     ) -> "OfflineAlgorithmConfig":
        self.input_path = input_path
        if num_batches_per_step is not None:
            self.num_batches_per_step = num_batches_per_step
        return self


def _infer_spaces_from_batch(batch: SampleBatch):
    obs_dim = int(np.asarray(batch[OBS]).shape[-1])
    num_actions = int(np.asarray(batch[ACTIONS]).max()) + 1
    return obs_dim, num_actions


def _probe_spaces(reader: JsonReader, scans: int = 5):
    """(obs_dim, num_actions) inferred from logged batches; several
    batches scanned so rare actions are not missed."""
    obs_dim, num_actions = _infer_spaces_from_batch(reader.next())
    for _ in range(scans - 1):
        _, n2 = _infer_spaces_from_batch(reader.next())
        num_actions = max(num_actions, n2)
    return obs_dim, num_actions


class _LearnerCheckpointMixin:
    def save_checkpoint(self):
        return self.learner.state()

    def load_checkpoint(self, state):
        self.learner.load_state(state)


class BCConfig(OfflineAlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.bc_logstd_coeff = 0.0

    @property
    def algo_class(self):
        return BC


class BC(_LearnerCheckpointMixin, Algorithm):
    config_class = BCConfig

    def _setup(self, cfg: BCConfig):
        self.reader = JsonReader(cfg.input_path, seed=cfg.seed)
        obs_dim, num_actions = _probe_spaces(self.reader)
        self.module = ActorCriticMLP(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))))

        def loss(params, module, batch):
            logits, _ = module.apply(params, batch[OBS])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch[ACTIONS][:, None].astype(jnp.int32),
                axis=-1)[:, 0]
            bc_loss = -jnp.mean(logp)
            return bc_loss, {"bc_loss": bc_loss}

        self.learner = Learner(self.module, loss,
                               optimizer=optax.adam(cfg.lr), seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        metrics: Dict[str, Any] = {}
        n = 0
        for _ in range(cfg.num_batches_per_step):
            batch = self.reader.next()
            metrics = self.learner.update(batch)
            n += len(batch)
        metrics["num_env_steps_trained"] = n
        return metrics

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        logits, _ = self.module.apply(self.learner.params,
                                      jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(logits, axis=-1))



# --------------------------------------------------------------------------
# MARWIL — monotonic advantage re-weighted imitation learning
# (reference: rllib/algorithms/marwil/marwil.py — exp(beta*A) weighted BC
# + value regression; BC is the beta=0 special case).
# --------------------------------------------------------------------------

class MARWILConfig(OfflineAlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.vf_coeff = 1.0

    @property
    def algo_class(self):
        return MARWIL


class MARWIL(_LearnerCheckpointMixin, Algorithm):
    config_class = MARWILConfig

    def _setup(self, cfg: MARWILConfig):
        self.reader = JsonReader(cfg.input_path, seed=cfg.seed)
        obs_dim, num_actions = _probe_spaces(self.reader)
        self.module = ActorCriticMLP(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))))
        gamma, beta, vf_coeff = cfg.gamma, cfg.beta, cfg.vf_coeff

        def loss(params, module, batch):
            logits, values = module.apply(params, batch[OBS])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch[ACTIONS][:, None].astype(jnp.int32),
                axis=-1)[:, 0]
            returns = batch["returns"]
            adv = returns - values
            # Advantage re-weighting with a stop-gradient through the
            # weights (marwil_torch_policy.py does the same detach).
            w = jnp.exp(jnp.clip(beta * jax.lax.stop_gradient(adv),
                                 -10.0, 10.0))
            pi_loss = -jnp.mean(w * logp)
            vf_loss = jnp.mean(adv ** 2)
            total = pi_loss + vf_coeff * vf_loss
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss}

        self.learner = Learner(self.module, loss,
                               optimizer=optax.adam(cfg.lr), seed=cfg.seed)
        self._gamma = gamma

    def _with_returns(self, batch: SampleBatch) -> SampleBatch:
        """Discounted returns-to-go per episode (the advantage target)."""
        rews = np.asarray(batch[REWARDS], np.float32)
        dones = np.asarray(batch[DONES])
        ret = np.zeros_like(rews)
        acc = 0.0
        for t in reversed(range(len(rews))):
            acc = rews[t] + self._gamma * acc * (1.0 - float(dones[t]))
            ret[t] = acc
        out = SampleBatch(batch)
        out["returns"] = ret
        return out

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        metrics: Dict[str, Any] = {}
        n = 0
        for _ in range(cfg.num_batches_per_step):
            batch = self._with_returns(self.reader.next())
            metrics = self.learner.update(batch)
            n += len(batch)
        metrics["num_env_steps_trained"] = n
        return metrics

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        logits, _ = self.module.apply(self.learner.params,
                                      jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(logits, axis=-1))



# --------------------------------------------------------------------------
# CQL — conservative Q-learning, discrete variant (reference:
# rllib/algorithms/cql/cql.py; the conservative regularizer
# logsumexp(Q) - Q(a_logged) keeps unseen actions' Q-values down so the
# greedy policy stays inside the dataset's support).
# --------------------------------------------------------------------------

class CQLConfig(OfflineAlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.min_q_weight = 1.0
        self.target_update_freq = 8
        self.tau = 1.0

    @property
    def algo_class(self):
        return CQL


class CQL(Algorithm):
    config_class = CQLConfig

    def _setup(self, cfg: CQLConfig):
        from ray_tpu.rllib.dqn import QNetworkMLP

        self.reader = JsonReader(cfg.input_path, seed=cfg.seed)
        obs_dim, num_actions = _probe_spaces(self.reader)
        self.module = QNetworkMLP(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))))
        self.params = self.module.init(jax.random.PRNGKey(cfg.seed))
        # jnp.copy, not identity: params are donated by the jitted update,
        # so an aliasing target would reference donated (stale) buffers.
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self.params)
        gamma, w_cons = cfg.gamma, cfg.min_q_weight
        module = self.module

        def update(params, target_params, opt_state, batch):
            def loss_fn(p):
                q = module.apply(p, batch[OBS])
                q_a = jnp.take_along_axis(
                    q, batch[ACTIONS][:, None].astype(jnp.int32),
                    axis=-1)[:, 0]
                # Double-Q target through the online argmax.
                next_q_online = module.apply(p, batch[NEXT_OBS])
                next_q_target = module.apply(target_params,
                                             batch[NEXT_OBS])
                next_a = jnp.argmax(next_q_online, axis=-1)
                next_q = jnp.take_along_axis(
                    next_q_target, next_a[:, None], axis=-1)[:, 0]
                not_done = 1.0 - batch[DONES].astype(jnp.float32)
                target = batch[REWARDS] + gamma * not_done * \
                    jax.lax.stop_gradient(next_q)
                td = jnp.mean((q_a - target) ** 2)
                conservative = jnp.mean(
                    jax.scipy.special.logsumexp(q, axis=-1) - q_a)
                total = td + w_cons * conservative
                return total, {"td_loss": td, "cql_loss": conservative}

            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, dict(metrics, total_loss=loss)

        self._update = jax.jit(update, donate_argnums=(0, 2))
        self._steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        metrics: Dict[str, Any] = {}
        n = 0
        for _ in range(cfg.num_batches_per_step):
            batch = self.reader.next()
            dev = {k: jnp.asarray(v) for k, v in batch.items()}
            self.params, self._opt_state, metrics = self._update(
                self.params, self.target_params, self._opt_state, dev)
            self._steps += 1
            if self._steps % cfg.target_update_freq == 0:
                self.target_params = jax.tree.map(jnp.copy, self.params)
            n += len(batch)
        out = {k: float(v) for k, v in metrics.items()}
        out["num_env_steps_trained"] = n
        return out

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        q = self.module.apply(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(q, axis=-1))

    def save_checkpoint(self):
        return {"params": jax.device_get(self.params),
                "target": jax.device_get(self.target_params)}

    def load_checkpoint(self, state):
        self.params = jax.device_put(state["params"])
        self.target_params = jax.device_put(state["target"])
