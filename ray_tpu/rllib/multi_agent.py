"""Multi-agent RL: env API, per-policy batches, policy-mapped rollouts.

Reference surface: ``rllib/env/multi_agent_env.py`` (MultiAgentEnv — dict
obs/action/reward keyed by agent id, ``__all__`` termination),
``rllib/policy/sample_batch.py`` (MultiAgentBatch — {policy_id:
SampleBatch} + env-step accounting), and the policy-mapping rollout in
``rllib/evaluation/rollout_worker.py:166`` (policy_mapping_fn routes each
agent's transition into its policy's batch).

TPU division of labor is unchanged from the single-agent stack: rollout
workers are CPU actors; each POLICY gets its own JAX Learner whose update
is one jitted program.  Agents sharing a policy share parameters — their
transitions concatenate into one batch, which is what makes parameter
sharing the cheap default on a TPU (one big minibatch instead of N tiny
per-agent updates).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.rllib.models import ActorCriticMLP, sample_action
from ray_tpu.rllib.rollout_worker import WorkerSet, compute_gae
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, OBS, REWARDS, SampleBatch, VF_PREDS,
    concat_batches,
)

ALL_DONE = "__all__"


class MultiAgentEnv:
    """Dict-keyed multi-agent environment (reference:
    rllib/env/multi_agent_env.py).

    ``reset() -> (obs_dict, info_dict)``;
    ``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``
    — all dicts keyed by agent id.  ``terminateds[ALL_DONE]`` ends the
    episode.  Only agents present in the obs dict act next step (supports
    turn-based and agents joining/leaving mid-episode)."""

    agent_ids: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


class MultiAgentBatch:
    """{policy_id: SampleBatch} + env-step count (reference:
    rllib/policy/sample_batch.py MultiAgentBatch — agent steps accumulate
    per policy; env_steps counts environment transitions once)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = env_steps

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    def __len__(self) -> int:
        return self._env_steps

    def __getitem__(self, policy_id: str) -> SampleBatch:
        return self.policy_batches[policy_id]

    def items(self):
        return self.policy_batches.items()


def concat_ma_batches(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
    pids = {p for b in batches for p in b.policy_batches}
    merged = {}
    for pid in pids:
        parts = [b.policy_batches[pid] for b in batches
                 if pid in b.policy_batches and len(b.policy_batches[pid])]
        if parts:
            merged[pid] = concat_batches(parts)
    return MultiAgentBatch(merged, sum(b.env_steps() for b in batches))


class _AgentBuffer:
    """One agent's in-flight trajectory, flushed (GAE'd) on episode end."""

    __slots__ = ("cols",)

    def __init__(self):
        self.cols = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGP,
                                     VF_PREDS)}

    def add(self, obs, act, rew, done, logp, vf):
        c = self.cols
        c[OBS].append(obs)
        c[ACTIONS].append(act)
        c[REWARDS].append(rew)
        c[DONES].append(done)
        c[LOGP].append(logp)
        c[VF_PREDS].append(vf)

    def __len__(self):
        return len(self.cols[OBS])

    def to_batch(self) -> SampleBatch:
        c = self.cols
        return SampleBatch({
            OBS: np.asarray(c[OBS], np.float32),
            ACTIONS: np.asarray(c[ACTIONS], np.int32),
            REWARDS: np.asarray(c[REWARDS], np.float32),
            DONES: np.asarray(c[DONES], bool),
            LOGP: np.asarray(c[LOGP], np.float32),
            VF_PREDS: np.asarray(c[VF_PREDS], np.float32),
        })


@ray.remote
class MultiAgentRolloutWorker:
    """CPU rollout actor with per-policy models and a policy-mapping fn
    (reference: rollout_worker.py:166 — the policy map + per-agent
    routing; sampler.py's _env_runner agent-to-policy bookkeeping)."""

    def __init__(self, env_maker: Callable[[], MultiAgentEnv],
                 policy_model_configs: Dict[str, Dict[str, Any]],
                 policy_mapping_fn: Callable[[str], str],
                 worker_index: int = 0, gamma: float = 0.99,
                 lam: float = 0.95, seed: Optional[int] = None):
        import jax

        self._env = env_maker()
        self._models = {pid: ActorCriticMLP(**mc)
                        for pid, mc in policy_model_configs.items()}
        self._apply = {pid: jax.jit(m.apply)
                       for pid, m in self._models.items()}
        self._params: Dict[str, Any] = {}
        self._map = policy_mapping_fn
        self._gamma, self._lam = gamma, lam
        self._rng = np.random.default_rng(
            seed if seed is not None else worker_index)
        self._obs, _ = self._env.reset(
            seed=int(self._rng.integers(2**31)))
        self._bufs: Dict[str, _AgentBuffer] = {}
        # Summed-over-agents return of the CURRENT episode; persists
        # across sample() horizons so only true episode ends record a
        # completed return (the single-agent worker's _ep_returns).
        self._ep_reward_sum = 0.0
        self._completed_returns: List[float] = []

    def set_weights(self, weights: Dict[str, Any]):
        self._params.update(weights)
        return True

    def _values_of(self, obs_dict) -> Dict[str, float]:
        """Each live agent's value of its current obs under its policy
        (truncation/horizon bootstrap)."""
        out: Dict[str, float] = {}
        for agent_id in self._bufs:
            if agent_id in obs_dict:
                pid = self._map(agent_id)
                _, v = self._apply[pid](
                    self._params[pid],
                    np.asarray(obs_dict[agent_id], np.float32)[None, :])
                out[agent_id] = float(np.asarray(v)[0])
        return out

    def _flush_trajectories(self,
                            done_batches: Dict[str, List[SampleBatch]],
                            last_values: Dict[str, float],
                            terminated: bool):
        """GAE each agent's trajectory into its policy's bucket.
        ``last_values`` bootstraps truncated/horizon-cut trajectories.
        Does NOT touch episode-return accounting — that belongs to true
        episode ends only."""
        for agent_id, buf in self._bufs.items():
            if not len(buf):
                continue
            b = buf.to_batch()
            last_v = 0.0 if terminated else last_values.get(agent_id, 0.0)
            b = compute_gae(b, last_v, self._gamma, self._lam)
            done_batches.setdefault(self._map(agent_id), []).append(b)
        self._bufs = {}

    def sample(self, num_env_steps: int) -> MultiAgentBatch:
        assert self._params, "set_weights first"
        done_batches: Dict[str, List[SampleBatch]] = {}
        env_steps = 0
        for _ in range(num_env_steps):
            # Group the agents awaiting actions by policy: ONE forward
            # pass per policy per step, not one per agent.
            by_policy: Dict[str, List[str]] = {}
            for agent_id in self._obs:
                by_policy.setdefault(self._map(agent_id), []).append(
                    agent_id)
            actions, logps, vfs = {}, {}, {}
            for pid, agent_ids in by_policy.items():
                obs_arr = np.stack([self._obs[a] for a in agent_ids]) \
                    .astype(np.float32)
                logits, values = self._apply[pid](self._params[pid],
                                                  obs_arr)
                acts, lp = sample_action(np.asarray(logits), self._rng)
                values = np.asarray(values)
                for i, a in enumerate(agent_ids):
                    actions[a] = int(acts[i])
                    logps[a] = float(lp[i])
                    vfs[a] = float(values[i])
            nobs, rews, terms, truncs, _ = self._env.step(actions)
            env_steps += 1
            all_term = terms.get(ALL_DONE, False)
            all_trunc = truncs.get(ALL_DONE, False)
            for a, act in actions.items():
                # GAE's done flag means TERMINATION (value of the next
                # state is zero); a truncated agent's trajectory instead
                # bootstraps from its final obs below.
                agent_term = terms.get(a, False) or all_term
                self._bufs.setdefault(a, _AgentBuffer()).add(
                    self._obs[a], act, float(rews.get(a, 0.0)),
                    bool(agent_term), logps[a], vfs[a])
                self._ep_reward_sum += float(rews.get(a, 0.0))
            if all_term or all_trunc:
                if all_trunc and not all_term:
                    # Time-limit truncation: bootstrap from the final
                    # obs the env just returned.
                    self._flush_trajectories(
                        done_batches, self._values_of(nobs),
                        terminated=False)
                else:
                    self._flush_trajectories(done_batches, {},
                                             terminated=True)
                self._completed_returns.append(self._ep_reward_sum)
                self._ep_reward_sum = 0.0
                nobs, _ = self._env.reset()
            self._obs = nobs
        # Sample horizon hit mid-episode: flush for training with a
        # current-obs bootstrap, WITHOUT recording an episode return
        # (the episode continues into the next sample() call).
        if self._bufs:
            self._flush_trajectories(done_batches,
                                     self._values_of(self._obs),
                                     terminated=False)
        merged = {pid: concat_batches(parts)
                  for pid, parts in done_batches.items() if parts}
        return MultiAgentBatch(merged, env_steps)

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._completed_returns)
        if clear:
            self._completed_returns.clear()
        return out


class MultiAgentWorkerSet(WorkerSet):
    """Fault-tolerant multi-agent rollout fleet: WorkerSet's recreate /
    sample_sync / episode_returns machinery with the multi-agent worker
    factory and batch merge swapped in."""

    def __init__(self, env_maker, policy_model_configs, policy_mapping_fn,
                 num_workers: int, gamma: float = 0.99, lam: float = 0.95,
                 recreate_failed: bool = True):
        self._make = lambda idx: MultiAgentRolloutWorker.options(
            num_cpus=1).remote(
                env_maker, policy_model_configs, policy_mapping_fn,
                worker_index=idx, gamma=gamma, lam=lam, seed=idx)
        self._workers = [self._make(i) for i in range(num_workers)]
        self._recreate = recreate_failed

    @staticmethod
    def _concat(batches):
        return concat_ma_batches(batches)

    @staticmethod
    def _empty():
        return MultiAgentBatch({}, 0)
