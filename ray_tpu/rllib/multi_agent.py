"""Multi-agent RL: env API, per-policy batches, policy-mapped rollouts.

Reference surface: ``rllib/env/multi_agent_env.py`` (MultiAgentEnv — dict
obs/action/reward keyed by agent id, ``__all__`` termination),
``rllib/policy/sample_batch.py`` (MultiAgentBatch — {policy_id:
SampleBatch} + env-step accounting), and the policy-mapping rollout in
``rllib/evaluation/rollout_worker.py:166`` (policy_mapping_fn routes each
agent's transition into its policy's batch).

TPU division of labor is unchanged from the single-agent stack: rollout
workers are CPU actors; each POLICY gets its own JAX Learner whose update
is one jitted program.  Agents sharing a policy share parameters — their
transitions concatenate into one batch, which is what makes parameter
sharing the cheap default on a TPU (one big minibatch instead of N tiny
per-agent updates).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.rllib.models import ActorCriticMLP, sample_action
from ray_tpu.rllib.rollout_worker import WorkerSet, compute_gae
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, OBS, REWARDS, SampleBatch, VF_PREDS,
    concat_batches,
)

ALL_DONE = "__all__"


class MultiAgentEnv:
    """Dict-keyed multi-agent environment (reference:
    rllib/env/multi_agent_env.py).

    ``reset() -> (obs_dict, info_dict)``;
    ``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``
    — all dicts keyed by agent id.  ``terminateds[ALL_DONE]`` ends the
    episode.  Only agents present in the obs dict act next step (supports
    turn-based and agents joining/leaving mid-episode)."""

    agent_ids: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


class MultiAgentBatch:
    """{policy_id: SampleBatch} + env-step count (reference:
    rllib/policy/sample_batch.py MultiAgentBatch — agent steps accumulate
    per policy; env_steps counts environment transitions once)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = env_steps

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    def __len__(self) -> int:
        return self._env_steps

    def __getitem__(self, policy_id: str) -> SampleBatch:
        return self.policy_batches[policy_id]

    def items(self):
        return self.policy_batches.items()


def concat_ma_batches(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
    pids = {p for b in batches for p in b.policy_batches}
    merged = {}
    for pid in pids:
        parts = [b.policy_batches[pid] for b in batches
                 if pid in b.policy_batches and len(b.policy_batches[pid])]
        if parts:
            merged[pid] = concat_batches(parts)
    return MultiAgentBatch(merged, sum(b.env_steps() for b in batches))


class _AgentBuffer:
    """One agent's in-flight trajectory, flushed (GAE'd) on episode end."""

    __slots__ = ("cols",)

    def __init__(self):
        self.cols = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGP,
                                     VF_PREDS)}

    def add(self, obs, act, rew, done, logp, vf):
        c = self.cols
        c[OBS].append(obs)
        c[ACTIONS].append(act)
        c[REWARDS].append(rew)
        c[DONES].append(done)
        c[LOGP].append(logp)
        c[VF_PREDS].append(vf)

    def __len__(self):
        return len(self.cols[OBS])

    def to_batch(self) -> SampleBatch:
        c = self.cols
        return SampleBatch({
            OBS: np.asarray(c[OBS], np.float32),
            ACTIONS: np.asarray(c[ACTIONS], np.int32),
            REWARDS: np.asarray(c[REWARDS], np.float32),
            DONES: np.asarray(c[DONES], bool),
            LOGP: np.asarray(c[LOGP], np.float32),
            VF_PREDS: np.asarray(c[VF_PREDS], np.float32),
        })


@ray.remote
class MultiAgentRolloutWorker:
    """CPU rollout actor with per-policy models and a policy-mapping fn
    (reference: rollout_worker.py:166 — the policy map + per-agent
    routing; sampler.py's _env_runner agent-to-policy bookkeeping)."""

    def __init__(self, env_maker: Callable[[], MultiAgentEnv],
                 policy_model_configs: Dict[str, Dict[str, Any]],
                 policy_mapping_fn: Callable[[str], str],
                 worker_index: int = 0, gamma: float = 0.99,
                 lam: float = 0.95, seed: Optional[int] = None):
        import jax

        self._env = env_maker()
        self._models = {pid: ActorCriticMLP(**mc)
                        for pid, mc in policy_model_configs.items()}
        self._apply = {pid: jax.jit(m.apply)
                       for pid, m in self._models.items()}
        self._params: Dict[str, Any] = {}
        self._map = policy_mapping_fn
        self._gamma, self._lam = gamma, lam
        self._rng = np.random.default_rng(
            seed if seed is not None else worker_index)
        self._obs, _ = self._env.reset(
            seed=int(self._rng.integers(2**31)))
        self._bufs: Dict[str, _AgentBuffer] = {}
        # Rewards received BEFORE an agent's first action of the episode
        # (turn-based envs): accrued here, folded into its next
        # transition (already counted in the episode return).
        self._pending_rew: Dict[str, float] = {}
        # Sticky: set the first time a live agent sits a step out — i.e.
        # the env has turn-based dynamics, so off-turn rewards are
        # possible and horizon flushes must hold each agent's newest
        # transition back.  Simultaneous-action envs never set it and
        # keep the flush-everything path (no one-transition training
        # lag, sample(1) is never empty).
        self._turn_based = False
        # Agents terminated THIS episode (cleared at reset): their
        # absence from the action dict is early termination, not
        # turn-taking, and must not flip the flag.
        self._done_agents: set = set()
        # Agents observed/rewarded THIS episode — the roster fallback
        # for envs that don't declare ``agent_ids``: once an agent has
        # appeared, it sitting a later step out is turn-taking evidence
        # that survives horizon flushes (buffers may be empty).
        # Per-episode (reset re-seeds it) so variable-roster
        # simultaneous envs don't trip over last episode's cast.
        self._seen_agents: set = set(self._obs)
        # Summed-over-agents return of the CURRENT episode; persists
        # across sample() horizons so only true episode ends record a
        # completed return (the single-agent worker's _ep_returns).
        self._ep_reward_sum = 0.0
        self._completed_returns: List[float] = []

    def set_weights(self, weights: Dict[str, Any]):
        self._params.update(weights)
        return True

    def _values_of(self, obs_dict) -> Dict[str, float]:
        """Each live agent's value of its current obs under its policy
        (truncation/horizon bootstrap)."""
        out: Dict[str, float] = {}
        for agent_id in self._bufs:
            if agent_id in obs_dict:
                pid = self._map(agent_id)
                _, v = self._apply[pid](
                    self._params[pid],
                    np.asarray(obs_dict[agent_id], np.float32)[None, :])
                out[agent_id] = float(np.asarray(v)[0])
        return out

    def _flush_trajectories(self,
                            done_batches: Dict[str, List[SampleBatch]],
                            last_values: Dict[str, float],
                            terminated: bool, hold_last: bool = False):
        """GAE each agent's trajectory into its policy's bucket.
        ``last_values`` bootstraps truncated/horizon-cut trajectories.
        ``hold_last`` (horizon cut mid-episode) keeps each agent's most
        recent transition buffered instead of shipping it: a turn-based
        env may pay that agent an off-turn reward on a LATER step (the
        opponent's move deciding the game), which must land on a real
        transition — the flushed prefix bootstraps from the held
        transition's own value prediction, and the held row rides out
        with the next flush.  Does NOT touch episode-return accounting —
        that belongs to true episode ends only."""
        kept: Dict[str, _AgentBuffer] = {}
        for agent_id, buf in self._bufs.items():
            if not len(buf):
                continue
            if hold_last:
                held = {k: v.pop() for k, v in buf.cols.items()}
                last_v = held[VF_PREDS]
                nb = _AgentBuffer()
                nb.add(held[OBS], held[ACTIONS], held[REWARDS],
                       held[DONES], held[LOGP], held[VF_PREDS])
                kept[agent_id] = nb
                if not len(buf):
                    continue
            else:
                last_v = 0.0 if terminated \
                    else last_values.get(agent_id, 0.0)
            b = buf.to_batch()
            b = compute_gae(b, last_v, self._gamma, self._lam)
            done_batches.setdefault(self._map(agent_id), []).append(b)
        self._bufs = kept

    def sample(self, num_env_steps: int) -> MultiAgentBatch:
        assert self._params, "set_weights first"
        done_batches: Dict[str, List[SampleBatch]] = {}
        env_steps = 0
        for _ in range(num_env_steps):
            # Group the agents awaiting actions by policy: ONE forward
            # pass per policy per step, not one per agent.
            by_policy: Dict[str, List[str]] = {}
            for agent_id in self._obs:
                by_policy.setdefault(self._map(agent_id), []).append(
                    agent_id)
            actions, logps, vfs = {}, {}, {}
            for pid, agent_ids in by_policy.items():
                obs_arr = np.stack([self._obs[a] for a in agent_ids]) \
                    .astype(np.float32)
                logits, values = self._apply[pid](self._params[pid],
                                                  obs_arr)
                acts, lp = sample_action(np.asarray(logits), self._rng)
                values = np.asarray(values)
                for i, a in enumerate(agent_ids):
                    actions[a] = int(acts[i])
                    logps[a] = float(lp[i])
                    vfs[a] = float(values[i])
            # A LIVE agent sitting a step out marks turn-based dynamics
            # — detected both from the env's declared roster (works from
            # step 1, before any buffer exists, so even sample(1)
            # horizons see it) and from buffered agents absent from the
            # action dict (envs without an ``agent_ids`` attribute).
            # An agent whose last transition is done (or in
            # _done_agents) merely terminated early (battle-royale style
            # simultaneous envs): it is finished, not waiting its turn,
            # and no further reward may arrive for it.  A live-but-idle
            # agent is deliberately NOT excluded — this worker has no
            # per-agent truncation, so an absent live agent may act (or
            # be paid off-turn) later and the hold-back lag is the price
            # of not dropping that reward.
            if not self._turn_based:
                roster = getattr(self._env, "agent_ids", None) \
                    or self._seen_agents
                if any(a not in actions and a not in self._done_agents
                       for a in roster) or \
                   any(a not in actions and a not in self._done_agents
                       and len(buf) and not buf.cols[DONES][-1]
                       for a, buf in self._bufs.items()):
                    self._turn_based = True
            nobs, rews, terms, truncs, _ = self._env.step(actions)
            env_steps += 1
            self._seen_agents.update(nobs)
            self._seen_agents.update(a for a in rews if a != ALL_DONE)
            all_term = terms.get(ALL_DONE, False)
            all_trunc = truncs.get(ALL_DONE, False)
            for a, act in actions.items():
                # GAE's done flag means TERMINATION (value of the next
                # state is zero); a truncated agent's trajectory instead
                # bootstraps from its final obs below.
                agent_term = terms.get(a, False) or all_term
                self._bufs.setdefault(a, _AgentBuffer()).add(
                    self._obs[a], act,
                    float(rews.get(a, 0.0)) + self._pending_rew.pop(a, 0.0),
                    bool(agent_term), logps[a], vfs[a])
                self._ep_reward_sum += float(rews.get(a, 0.0))
            # Turn-based envs reward agents on steps they did NOT act
            # (e.g. the opponent's move decides the game): credit those
            # rewards to the agent's buffered LAST transition — or accrue
            # them for its next one if it hasn't acted yet — so terminal
            # rewards reach both the trajectory (GAE sees them) and the
            # episode-return accounting, instead of being dropped with
            # the action dict.
            for a, r in rews.items():
                if a in actions or a == ALL_DONE or not r:
                    continue
                self._ep_reward_sum += float(r)
                if a not in self._done_agents:
                    # A reward paid to a live non-acting agent IS
                    # turn-based dynamics (the definitive signal for
                    # envs with no ``agent_ids`` roster); a posthumous
                    # reward to an early-terminated agent is not.
                    self._turn_based = True
                buf = self._bufs.get(a)
                if buf is not None and len(buf):
                    buf.cols[REWARDS][-1] += float(r)
                else:
                    self._pending_rew[a] = \
                        self._pending_rew.get(a, 0.0) + float(r)
            # Off-turn TERMINATION — with or without a reward riding the
            # same step — must mark the agent's buffered last transition
            # done (GAE must not bootstrap past the end of its
            # trajectory); a zero/absent reward skips the credit loop
            # above, so the done flag is handled here for all of them.
            for a, buf in self._bufs.items():
                if a in actions or not len(buf):
                    continue
                if terms.get(a, False) or all_term:
                    buf.cols[DONES][-1] = True
            for a, t in terms.items():
                if t and a != ALL_DONE:
                    self._done_agents.add(a)
            if all_term or all_trunc:
                if all_trunc and not all_term:
                    # Time-limit truncation: bootstrap from the final
                    # obs the env just returned.  A turn-based env only
                    # returns the next-turn agent's obs, so off-turn
                    # agents fall back to their last recorded value
                    # prediction (the same proxy hold_last uses) rather
                    # than a flat 0.0 that would bias their advantages.
                    vals = self._values_of(nobs)
                    for a, buf in self._bufs.items():
                        if a not in vals and len(buf):
                            vals[a] = float(buf.cols[VF_PREDS][-1])
                    self._flush_trajectories(done_batches, vals,
                                             terminated=False)
                else:
                    self._flush_trajectories(done_batches, {},
                                             terminated=True)
                self._completed_returns.append(self._ep_reward_sum)
                self._ep_reward_sum = 0.0
                # Accrued rewards of agents that never acted this episode
                # have no transition to land on; they were counted in the
                # return above and must not leak into the next episode.
                self._pending_rew.clear()
                self._done_agents.clear()
                nobs, _ = self._env.reset()
                self._seen_agents = set(nobs)
            self._obs = nobs
        # Sample horizon hit mid-episode: flush for training WITHOUT
        # recording an episode return (the episode continues into the
        # next sample() call).  Under turn-based dynamics each agent's
        # newest transition stays buffered (hold_last) so an off-turn
        # terminal reward arriving next sample() still reaches a
        # trajectory — the prefix bootstraps from that transition's
        # recorded value; simultaneous-action envs flush everything with
        # a current-obs bootstrap as before.
        if self._bufs:
            if self._turn_based:
                self._flush_trajectories(done_batches, {},
                                         terminated=False, hold_last=True)
            else:
                self._flush_trajectories(done_batches,
                                         self._values_of(self._obs),
                                         terminated=False)
        merged = {pid: concat_batches(parts)
                  for pid, parts in done_batches.items() if parts}
        return MultiAgentBatch(merged, env_steps)

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._completed_returns)
        if clear:
            self._completed_returns.clear()
        return out


class MultiAgentWorkerSet(WorkerSet):
    """Fault-tolerant multi-agent rollout fleet: WorkerSet's recreate /
    sample_sync / episode_returns machinery with the multi-agent worker
    factory and batch merge swapped in."""

    def __init__(self, env_maker, policy_model_configs, policy_mapping_fn,
                 num_workers: int, gamma: float = 0.99, lam: float = 0.95,
                 recreate_failed: bool = True):
        self._make = lambda idx: MultiAgentRolloutWorker.options(
            num_cpus=1).remote(
                env_maker, policy_model_configs, policy_mapping_fn,
                worker_index=idx, gamma=gamma, lam=lam, seed=idx)
        self._workers = [self._make(i) for i in range(num_workers)]
        self._recreate = recreate_failed

    @staticmethod
    def _concat(batches):
        return concat_ma_batches(batches)

    @staticmethod
    def _empty():
        return MultiAgentBatch({}, 0)
