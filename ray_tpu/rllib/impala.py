"""IMPALA: asynchronous off-policy actor-learner training.

Reference: ``rllib/algorithms/impala/impala.py:474`` (``training_step``
:616): workers sample asynchronously; batches flow to the learner without
waiting for the fleet; staleness is corrected by V-trace.  Weights flow
back per-worker on batch receipt (the broadcast-interval pattern of :571).

Distributed mode (``num_aggregators > 0`` and the
``distributed_training`` master switch): rollout batches flow
worker -> aggregator over the striped data plane (the sample ObjectRef is
passed as an argument, so only the descriptor crosses the control plane),
aggregators reshape to time-major off the driver, and the learner feeds
from a host->TPU double-buffered queue — the
``multi_gpu_learner_thread.py`` analog: a loader thread issues the h2d
transfer of batch t+1 while the update for batch t computes.  Queue depth
is ``impala_queue_depth``; a blocking get on an empty queue counts a
``learner_queue_stalls``.  With the switch off the legacy path below runs
byte-identically and every new counter stays zero.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit
from ray_tpu.train.pipeline_actors import active_config
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS, SampleBatch,
)
from ray_tpu.rllib.vtrace import vtrace


def impala_loss(params, module, batch, *, gamma: float = 0.99,
                vf_coef: float = 0.5, ent_coef: float = 0.01,
                clip_rho: float = 1.0, clip_c: float = 1.0):
    """batch arrays are (T, B, ...) time-major."""
    t, b = batch[ACTIONS].shape
    obs = batch[OBS].reshape(t * b, -1)
    logits, values = module.apply(params, obs)
    logits = logits.reshape(t, b, -1)
    values = values.reshape(t, b)
    logp_all = jax.nn.log_softmax(logits)
    target_logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][..., None].astype(jnp.int32), -1)[..., 0]
    _, bootstrap = module.apply(params, batch["bootstrap_obs"])
    discounts = gamma * (1.0 - batch[DONES].astype(jnp.float32))
    vt = vtrace(batch[LOGP], target_logp, batch[REWARDS], values,
                bootstrap, discounts, clip_rho, clip_c)
    pi_loss = -jnp.mean(target_logp * vt.pg_advantages)
    vf_loss = jnp.mean((values - vt.vs) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                  "entropy": entropy}


def _to_time_major(flat: SampleBatch, frag: int) -> Dict[str, Any]:
    """Worker batches concatenate per-env fragments of length ``frag``;
    reshape (n*frag, ...) -> (frag, n, ...) time-major."""
    n = len(flat) // frag
    out = {}
    for k in (OBS, ACTIONS, REWARDS, DONES, LOGP):
        v = flat[k][: n * frag]
        out[k] = np.moveaxis(
            v.reshape(n, frag, *v.shape[1:]), 0, 1)
    next_obs = flat[NEXT_OBS][: n * frag].reshape(
        n, frag, -1)
    out["bootstrap_obs"] = next_obs[:, -1, :]
    return out


@ray.remote
class _BatchAggregator:
    """Off-driver batch prep (reference: IMPALA's aggregation workers,
    ``impala.py`` aggregator actors).  The sample ObjectRef arrives as an
    argument, so the payload flows rollout worker -> aggregator over the
    data plane and the driver only ever touches the time-major result."""

    def aggregate(self, frag: int, flat: SampleBatch) -> Dict[str, Any]:
        return _to_time_major(flat, frag)


def _to_device(tm: Dict[str, Any]) -> Dict[str, Any]:
    """Host->device transfer of one time-major batch.  Both learner
    paths (queued and direct) route through this single hop so the only
    variable between ``impala_queue_depth`` settings is overlap, never
    the transfer itself."""
    return {k: jnp.asarray(v) for k, v in tm.items()}


class _HostToDeviceQueue:
    """``multi_gpu_learner_thread.py`` analog: a daemon loader thread
    moves time-major host batches onto the device so the h2d transfer of
    batch t+1 is in flight while the learner update for batch t computes.
    ``depth`` bounds host batches awaiting transfer; a blocking ``get``
    on an empty device queue counts one ``learner_queue_stalls``."""

    def __init__(self, depth: int):
        self._in: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._out: "queue.Queue" = queue.Queue()
        self._gets = 0
        self._stalls = 0
        self._occupancy_sum = 0
        self._thread = threading.Thread(
            target=self._loader, name="impala-h2d", daemon=True)
        self._thread.start()

    def _loader(self):
        while True:
            tm = self._in.get()
            if tm is None:
                return
            # The h2d copy overlaps whatever update is currently
            # running on the caller thread.
            self._out.put(_to_device(tm))

    def put(self, tm: Dict[str, Any]):
        self._in.put(tm)

    def get(self) -> Dict[str, Any]:
        self._gets += 1
        self._occupancy_sum += self._out.qsize()
        if self._out.empty():
            self._stalls += 1
            from ray_tpu.train.pipeline_actors import note
            note("learner_queue_stalls")
        return self._out.get()

    def queue_stats(self) -> Dict[str, float]:
        return {
            "gets": self._gets,
            "stalls": self._stalls,
            "occupancy_avg": (self._occupancy_sum / self._gets
                              if self._gets else 0.0),
        }

    def stop(self):
        self._in.put(None)
        self._thread.join(timeout=5.0)


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        self.grad_clip = 40.0
        self.rollout_fragment_length = 50
        self.max_batches_per_step = 8
        # > 0 engages the distributed path: off-driver time-major prep +
        # the h2d double-buffer (gated by cfg.distributed_training).
        self.num_aggregators = 0

    @property
    def algo_class(self):
        return Impala


class Impala(Algorithm):
    config_class = ImpalaConfig

    def _setup(self, cfg: ImpalaConfig):
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        if hasattr(env, "close"):
            env.close()
        model_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        self._obs_dim = obs_dim
        self.workers = WorkerSet(
            cfg.env_maker, model_config, cfg.num_rollout_workers,
            cfg.num_envs_per_worker, gamma=cfg.gamma)
        module = ActorCriticMLP(**model_config)

        def loss(params, mod, batch):
            return impala_loss(params, mod, batch, gamma=cfg.gamma,
                               vf_coef=cfg.vf_loss_coeff,
                               ent_coef=cfg.entropy_coeff,
                               clip_rho=cfg.clip_rho_threshold,
                               clip_c=cfg.clip_c_threshold)

        def make_learner(mesh=None):
            # Time-major columns (T, B, ...) shard their ENV axis over
            # dp; bootstrap rows (B, ...) shard axis 0.  The V-trace
            # scan stays per-shard (it runs over T), XLA psums grads.
            from jax.sharding import PartitionSpec

            def spec(k, v):
                return (PartitionSpec("dp") if k == "bootstrap_obs"
                        else PartitionSpec(None, "dp"))

            return Learner(
                module, loss, optimizer=optax.chain(
                    optax.clip_by_global_norm(cfg.grad_clip),
                    optax.adam(cfg.lr)), seed=cfg.seed,
                mesh=mesh, batch_spec=spec if mesh is not None else None)

        self.learner_group = LearnerGroup(
            make_learner, num_learners=cfg.num_learners)
        w = self.learner_group.get_weights()
        self.workers.sync_weights(w)
        self._aggregators: List[Any] = []
        self._h2d = None
        sys_cfg = active_config()
        if sys_cfg.distributed_training and \
                getattr(cfg, "num_aggregators", 0) > 0:
            self._aggregators = [
                _BatchAggregator.options(num_cpus=1).remote()
                for _ in range(cfg.num_aggregators)]
            if sys_cfg.impala_queue_depth > 0:
                self._h2d = _HostToDeviceQueue(
                    sys_cfg.impala_queue_depth)
        # Kick off the async pipeline: one outstanding sample per worker —
        # the whole wave goes out in one dispatch pass.
        sample_futs = _bulk_submit([
            (worker.sample, (cfg.rollout_fragment_length,), None)
            for worker in self.workers.workers])
        self._inflight = {self._chain(fut, i): i
                          for i, fut in enumerate(sample_futs)}

    def _chain(self, sample_fut, idx: int):
        """Route a sample future through an aggregator (payload flows
        worker -> aggregator over the data plane); identity when the
        distributed path is off."""
        if not getattr(self, "_aggregators", None):
            return sample_fut
        agg = self._aggregators[idx % len(self._aggregators)]
        return agg.aggregate.remote(
            self.algo_config.rollout_fragment_length, sample_fut)

    def _resubmit(self, worker, idx: int):
        """Weight refresh + resample for one worker in one dispatch pass."""
        _, s_ref = _bulk_submit([
            (worker.set_weights, (self.learner_group.get_weights(),), None),
            (worker.sample, (self.algo_config.rollout_fragment_length,),
             None)])
        self._inflight[self._chain(s_ref, idx)] = idx

    def _to_time_major(self, flat: SampleBatch, frag: int) -> Dict[str, Any]:
        return _to_time_major(flat, frag)

    def training_step(self) -> Dict[str, Any]:
        cfg: ImpalaConfig = self.algo_config
        if getattr(self, "_aggregators", None):
            return self._training_step_distributed(cfg)
        metrics: Dict[str, Any] = {}
        steps = 0
        processed = 0
        while processed < cfg.max_batches_per_step and self._inflight:
            done, _ = ray.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
            if not done:
                break
            fut = done[0]
            idx = self._inflight.pop(fut)
            worker = self.workers.workers[idx]
            try:
                flat = ray.get(fut)
            except Exception:
                # Rebuild the dead worker before resubmitting — resubmitting
                # to a dead handle busy-spins on instantly-errored futures.
                worker = self.workers.recreate(idx)
                self._resubmit(worker, idx)
                continue
            tm = self._to_time_major(flat, cfg.rollout_fragment_length)
            metrics = self.learner_group.update(SampleBatch(tm))
            steps += len(flat)
            processed += 1
            # per-worker weight refresh, then immediately resample (async)
            self._resubmit(worker, idx)
        returns = self.workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
        metrics["num_env_steps_sampled"] = steps
        return metrics

    def _training_step_distributed(self, cfg: ImpalaConfig) -> Dict[str, Any]:
        """Aggregator-fed variant: the driver receives time-major batches
        (prepped off-driver) and keeps one batch in flight through the h2d
        queue so transfer of batch t+1 overlaps the update of batch t."""
        metrics: Dict[str, Any] = {}
        steps = 0
        processed = 0
        buffered = 0
        while processed < cfg.max_batches_per_step and self._inflight:
            done, _ = ray.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
            if not done:
                break
            fut = done[0]
            idx = self._inflight.pop(fut)
            worker = self.workers.workers[idx]
            try:
                tm = ray.get(fut)
            except Exception:
                worker = self.workers.recreate(idx)
                self._resubmit(worker, idx)
                continue
            steps += tm[ACTIONS].shape[0] * tm[ACTIONS].shape[1]
            processed += 1
            if self._h2d is not None:
                self._h2d.put(tm)
                buffered += 1
                if buffered > 1:
                    metrics = self.learner_group.update(
                        SampleBatch(self._h2d.get()))
                    buffered -= 1
            else:
                metrics = self.learner_group.update(
                    SampleBatch(_to_device(tm)))
            self._resubmit(worker, idx)
        while buffered > 0:  # drain the double-buffer
            metrics = self.learner_group.update(SampleBatch(self._h2d.get()))
            buffered -= 1
        returns = self.workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
        metrics["num_env_steps_sampled"] = steps
        return metrics

    def save_checkpoint(self):
        return self.learner_group.state()

    def load_checkpoint(self, state):
        self.learner_group.load_state(state)
        self.workers.sync_weights(self.learner_group.get_weights())

    def cleanup(self):
        if getattr(self, "_h2d", None) is not None:
            self._h2d.stop()
        for agg in getattr(self, "_aggregators", []):
            try:
                ray.kill(agg)
            except Exception:
                pass
        self.workers.stop()
