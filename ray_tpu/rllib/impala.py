"""IMPALA: asynchronous off-policy actor-learner training.

Reference: ``rllib/algorithms/impala/impala.py:474`` (``training_step``
:616): workers sample asynchronously; batches flow to the learner without
waiting for the fleet; staleness is corrected by V-trace.  The reference's
CPU->GPU loader threads (``make_learner_thread`` :433,
``multi_gpu_learner_thread.py``) have no equivalent here — one host->TPU
``device_put`` per update and XLA's async dispatch already overlap transfer
with compute.  Weights flow back per-worker on batch receipt (the
broadcast-interval pattern of :571).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as ray
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS, SampleBatch,
)
from ray_tpu.rllib.vtrace import vtrace


def impala_loss(params, module, batch, *, gamma: float = 0.99,
                vf_coef: float = 0.5, ent_coef: float = 0.01,
                clip_rho: float = 1.0, clip_c: float = 1.0):
    """batch arrays are (T, B, ...) time-major."""
    t, b = batch[ACTIONS].shape
    obs = batch[OBS].reshape(t * b, -1)
    logits, values = module.apply(params, obs)
    logits = logits.reshape(t, b, -1)
    values = values.reshape(t, b)
    logp_all = jax.nn.log_softmax(logits)
    target_logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][..., None].astype(jnp.int32), -1)[..., 0]
    _, bootstrap = module.apply(params, batch["bootstrap_obs"])
    discounts = gamma * (1.0 - batch[DONES].astype(jnp.float32))
    vt = vtrace(batch[LOGP], target_logp, batch[REWARDS], values,
                bootstrap, discounts, clip_rho, clip_c)
    pi_loss = -jnp.mean(target_logp * vt.pg_advantages)
    vf_loss = jnp.mean((values - vt.vs) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                  "entropy": entropy}


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        self.grad_clip = 40.0
        self.rollout_fragment_length = 50
        self.max_batches_per_step = 8

    @property
    def algo_class(self):
        return Impala


class Impala(Algorithm):
    config_class = ImpalaConfig

    def _setup(self, cfg: ImpalaConfig):
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        if hasattr(env, "close"):
            env.close()
        model_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        self._obs_dim = obs_dim
        self.workers = WorkerSet(
            cfg.env_maker, model_config, cfg.num_rollout_workers,
            cfg.num_envs_per_worker, gamma=cfg.gamma)
        module = ActorCriticMLP(**model_config)

        def loss(params, mod, batch):
            return impala_loss(params, mod, batch, gamma=cfg.gamma,
                               vf_coef=cfg.vf_loss_coeff,
                               ent_coef=cfg.entropy_coeff,
                               clip_rho=cfg.clip_rho_threshold,
                               clip_c=cfg.clip_c_threshold)

        def make_learner(mesh=None):
            # Time-major columns (T, B, ...) shard their ENV axis over
            # dp; bootstrap rows (B, ...) shard axis 0.  The V-trace
            # scan stays per-shard (it runs over T), XLA psums grads.
            from jax.sharding import PartitionSpec

            def spec(k, v):
                return (PartitionSpec("dp") if k == "bootstrap_obs"
                        else PartitionSpec(None, "dp"))

            return Learner(
                module, loss, optimizer=optax.chain(
                    optax.clip_by_global_norm(cfg.grad_clip),
                    optax.adam(cfg.lr)), seed=cfg.seed,
                mesh=mesh, batch_spec=spec if mesh is not None else None)

        self.learner_group = LearnerGroup(
            make_learner, num_learners=cfg.num_learners)
        w = self.learner_group.get_weights()
        self.workers.sync_weights(w)
        # Kick off the async pipeline: one outstanding sample per worker.
        self._inflight = {
            worker.sample.remote(cfg.rollout_fragment_length): i
            for i, worker in enumerate(self.workers.workers)}

    def _to_time_major(self, flat: SampleBatch, frag: int) -> Dict[str, Any]:
        """Worker batches concatenate per-env fragments of length ``frag``;
        reshape (n*frag, ...) -> (frag, n, ...) time-major."""
        n = len(flat) // frag
        out = {}
        for k in (OBS, ACTIONS, REWARDS, DONES, LOGP):
            v = flat[k][: n * frag]
            out[k] = np.moveaxis(
                v.reshape(n, frag, *v.shape[1:]), 0, 1)
        next_obs = flat[NEXT_OBS][: n * frag].reshape(
            n, frag, -1)
        out["bootstrap_obs"] = next_obs[:, -1, :]
        return out

    def training_step(self) -> Dict[str, Any]:
        cfg: ImpalaConfig = self.algo_config
        metrics: Dict[str, Any] = {}
        steps = 0
        processed = 0
        while processed < cfg.max_batches_per_step and self._inflight:
            done, _ = ray.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
            if not done:
                break
            fut = done[0]
            idx = self._inflight.pop(fut)
            worker = self.workers.workers[idx]
            try:
                flat = ray.get(fut)
            except Exception:
                # Rebuild the dead worker before resubmitting — resubmitting
                # to a dead handle busy-spins on instantly-errored futures.
                worker = self.workers.recreate(idx)
                worker.set_weights.remote(self.learner_group.get_weights())
                self._inflight[worker.sample.remote(
                    cfg.rollout_fragment_length)] = idx
                continue
            tm = self._to_time_major(flat, cfg.rollout_fragment_length)
            metrics = self.learner_group.update(SampleBatch(tm))
            steps += len(flat)
            processed += 1
            # per-worker weight refresh, then immediately resample (async)
            worker.set_weights.remote(self.learner_group.get_weights())
            self._inflight[worker.sample.remote(
                cfg.rollout_fragment_length)] = idx
        returns = self.workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
        metrics["num_env_steps_sampled"] = steps
        return metrics

    def save_checkpoint(self):
        return self.learner_group.state()

    def load_checkpoint(self, state):
        self.learner_group.load_state(state)
        self.workers.sync_weights(self.learner_group.get_weights())

    def cleanup(self):
        self.workers.stop()
