"""Replay buffers: uniform + prioritized (proportional, sum-tree).

Reference: ``rllib/utils/replay_buffers/replay_buffer.py`` and
``prioritized_replay_buffer.py`` (proportional prioritization per
Schaul et al. 2015, with a segment tree for O(log n) sampling) — same
semantics here with a numpy sum-tree.  ``ReplayActor`` hosts a buffer in
its own process so many rollout workers can push concurrently while the
learner samples (the Ape-X pattern, ``rllib/algorithms/apex_dqn``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.rllib.sample_batch import SampleBatch, concat_batches

BATCH_INDEXES = "batch_indexes"
WEIGHTS = "weights"


class ReplayBuffer:
    """Uniform FIFO ring buffer over SampleBatch rows."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def _ensure(self, batch: SampleBatch):
        if self._cols:
            return
        for k, v in batch.items():
            v = np.asarray(v)
            self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                     v.dtype)

    def add(self, batch: SampleBatch):
        self._ensure(batch)
        n = len(batch)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, col in self._cols.items():
            col[idx] = np.asarray(batch[k])
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        return idx

    def sample(self, num_items: int) -> SampleBatch:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, size=num_items)
        out = SampleBatch({k: col[idx] for k, col in self._cols.items()})
        out[BATCH_INDEXES] = idx.astype(np.int64)
        out[WEIGHTS] = np.ones(num_items, np.float32)
        return out


class _SumTree:
    """Flat-array segment tree: O(log n) prefix-sum sampling + updates.
    Leaf count is rounded up to a power of two so every leaf sits at the
    same depth — the vectorized descent steps all queries in lockstep."""

    def __init__(self, capacity: int):
        self.capacity = 1 << max(1, (capacity - 1)).bit_length()
        self._tree = np.zeros(2 * self.capacity, np.float64)

    def set(self, idx: np.ndarray, values: np.ndarray):
        i = np.asarray(idx) + self.capacity
        self._tree[i] = values
        i //= 2
        # Propagate level by level; duplicate parents collapse via unique.
        while np.any(i >= 1):
            i = np.unique(i[i >= 1])
            self._tree[i] = self._tree[2 * i] + self._tree[2 * i + 1]
            i //= 2

    def total(self) -> float:
        return float(self._tree[1])

    def find_prefix(self, prefix: np.ndarray) -> np.ndarray:
        """Vectorized descent: for each p in prefix, the leaf where the
        running sum crosses p."""
        idx = np.ones(len(prefix), np.int64)
        p = prefix.astype(np.float64).copy()
        while idx[0] < self.capacity:
            left = self._tree[2 * idx]
            go_right = p > left
            p = np.where(go_right, p - left, p)
            idx = 2 * idx + go_right
        return idx - self.capacity

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self._tree[np.asarray(idx) + self.capacity]


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    prioritized_replay_buffer.py): P(i) ∝ p_i^alpha, importance weights
    w_i = (N * P(i))^-beta / max_j w_j; new items enter at max priority."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._tree = _SumTree(capacity)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch):
        idx = super().add(batch)
        self._tree.set(idx, np.full(len(idx),
                                    self._max_priority ** self.alpha))
        return idx

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        total = self._tree.total()
        # Stratified prefixes (one per segment) like the reference.
        seg = total / num_items
        prefix = (np.arange(num_items) + self._rng.random(num_items)) * seg
        idx = np.clip(self._tree.find_prefix(prefix), 0, self._size - 1)
        probs = self._tree.get(idx) / max(total, 1e-12)
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-beta)
        weights = (weights / weights.max()).astype(np.float32)
        out = SampleBatch({k: col[idx] for k, col in self._cols.items()})
        out[BATCH_INDEXES] = idx.astype(np.int64)
        out[WEIGHTS] = weights
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        self._max_priority = max(self._max_priority,
                                 float(priorities.max()))
        self._tree.set(np.asarray(idx), priorities ** self.alpha)


@ray.remote
class ReplayActor:
    """Buffer in its own process: rollout workers push, the learner pulls
    (reference: the replay shards of rllib/algorithms/apex_dqn)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 prioritized: bool = True, seed: int = 0):
        self._buf = (PrioritizedReplayBuffer(capacity, alpha, seed)
                     if prioritized else ReplayBuffer(capacity, seed))

    def add(self, batch) -> int:
        self._buf.add(SampleBatch(batch))
        return len(self._buf)

    def sample(self, num_items: int, beta: float = 0.4):
        if len(self._buf) == 0:
            return None
        if isinstance(self._buf, PrioritizedReplayBuffer):
            return dict(self._buf.sample(num_items, beta))
        return dict(self._buf.sample(num_items))

    def update_priorities(self, idx, priorities):
        if isinstance(self._buf, PrioritizedReplayBuffer):
            self._buf.update_priorities(np.asarray(idx),
                                        np.asarray(priorities))
        return True

    def size(self) -> int:
        return len(self._buf)
