"""DQN: off-policy Q-learning with prioritized replay and a target network.

Reference: ``rllib/algorithms/dqn/dqn.py`` (``training_step``: sample ->
store in replay -> train on prioritized batches -> update priorities ->
periodic target sync) with double-Q (van Hasselt) as the reference's
default.  TPU division of labor matches the rest of the stack: CPU
rollout workers act epsilon-greedily and push transitions straight to a
ReplayActor (the Ape-X arrangement); the learner's update is one jitted
program on the device.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import VectorEnv
from ray_tpu.rllib.replay_buffers import (
    BATCH_INDEXES, WEIGHTS, ReplayActor,
)
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch,
)


class QNetworkMLP:
    """obs -> Q(s, ·) MLP (reference: the default dueling-off q-model)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key):
        sizes = (self.obs_dim,) + self.hidden + (self.num_actions,)
        params = []
        for a, b in zip(sizes[:-1], sizes[1:]):
            key, k = jax.random.split(key)
            params.append({"w": jax.random.normal(k, (a, b))
                           * np.sqrt(2.0 / a),
                           "b": jnp.zeros((b,))})
        return params

    def apply(self, params, obs):
        x = obs
        for i, lyr in enumerate(params):
            x = x @ lyr["w"] + lyr["b"]
            if i < len(params) - 1:
                x = jnp.tanh(x)
        return x  # (B, num_actions)


@ray.remote
class DQNRolloutWorker:
    """Epsilon-greedy vectorized rollouts pushed directly to the replay
    actor (reference: Ape-X workers writing to replay shards)."""

    def __init__(self, env_maker, model_config: Dict[str, Any],
                 replay_actor, num_envs: int = 1, worker_index: int = 0,
                 seed: Optional[int] = None):
        self._venv = VectorEnv(env_maker, num_envs,
                               seed=(seed if seed is not None
                                     else worker_index))
        self._model = QNetworkMLP(**model_config)
        self._params = None
        self._replay = replay_actor
        self._rng = np.random.default_rng(
            seed if seed is not None else worker_index)
        self._obs = self._venv.vector_reset()
        self._apply = jax.jit(self._model.apply)
        self._ep_returns = np.zeros(num_envs)
        self._completed: List[float] = []

    def set_weights(self, weights):
        self._params = weights
        return True

    def sample(self, num_steps: int, epsilon: float) -> int:
        """Step envs for ``num_steps``; push transitions to replay.
        Returns env-steps collected."""
        assert self._params is not None, "set_weights first"
        n = self._venv.num_envs
        cols = {k: [] for k in (OBS, ACTIONS, REWARDS, NEXT_OBS, DONES)}
        for _ in range(num_steps):
            q = np.asarray(self._apply(self._params, self._obs))
            acts = q.argmax(axis=-1)
            explore = self._rng.random(n) < epsilon
            acts = np.where(
                explore,
                self._rng.integers(0, q.shape[-1], size=n), acts)
            next_obs, rews, terms, truncs, finals, _ = \
                self._venv.vector_step(acts)
            cols[OBS].append(self._obs)
            cols[ACTIONS].append(acts)
            cols[REWARDS].append(rews)
            cols[NEXT_OBS].append(finals)  # pre-reset obs for bootstrap
            # DONES carries TERMINATION only: a time-limit truncation must
            # still bootstrap gamma*Q(final_obs) in the TD target.
            cols[DONES].append(terms)
            self._ep_returns += rews
            for i in np.nonzero(terms | truncs)[0]:
                self._completed.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            self._obs = next_obs
        batch = SampleBatch({
            OBS: np.concatenate(cols[OBS]).astype(np.float32),
            ACTIONS: np.concatenate(cols[ACTIONS]).astype(np.int32),
            REWARDS: np.concatenate(cols[REWARDS]).astype(np.float32),
            NEXT_OBS: np.concatenate(cols[NEXT_OBS]).astype(np.float32),
            DONES: np.concatenate(cols[DONES]),
        })
        ray.get(self._replay.add.remote(dict(batch)))
        return len(batch)

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._completed)
        if clear:
            self._completed.clear()
        return out


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 64
        self.replay_buffer_capacity = 100_000
        self.prioritized_replay = True
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.target_network_update_freq = 500   # env steps
        self.num_steps_sampled_before_learning = 1000
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 10_000
        self.double_q = True
        self.num_train_batches_per_step = 16
        self.grad_clip = 10.0

    @property
    def algo_class(self):
        return DQN


def dqn_loss(params, target_params, module, batch, *, gamma: float,
             double_q: bool):
    """Double-DQN TD loss with importance weights; returns per-item TD
    errors for priority updates (reference: dqn_torch_policy.py)."""
    q = module.apply(params, batch[OBS])
    q_sa = jnp.take_along_axis(
        q, batch[ACTIONS][:, None].astype(jnp.int32), axis=-1)[:, 0]
    q_next_target = module.apply(target_params, batch[NEXT_OBS])
    if double_q:
        q_next_online = module.apply(params, batch[NEXT_OBS])
        next_a = q_next_online.argmax(axis=-1)
        q_next = jnp.take_along_axis(
            q_next_target, next_a[:, None], axis=-1)[:, 0]
    else:
        q_next = q_next_target.max(axis=-1)
    not_done = 1.0 - batch[DONES].astype(jnp.float32)
    target = batch[REWARDS] + gamma * not_done * q_next
    td = q_sa - jax.lax.stop_gradient(target)
    loss = jnp.mean(batch[WEIGHTS] * jnp.square(td))
    return loss, {"td_errors": td, "mean_q": jnp.mean(q_sa)}


class DQN(Algorithm):
    config_class = DQNConfig

    def _setup(self, cfg: DQNConfig):
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        if hasattr(env, "close"):
            env.close()
        model_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        self.module = QNetworkMLP(**model_config)
        self.replay = ReplayActor.options(num_cpus=1).remote(
            capacity=cfg.replay_buffer_capacity,
            alpha=cfg.prioritized_replay_alpha,
            prioritized=cfg.prioritized_replay, seed=cfg.seed)
        self.workers = [
            DQNRolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, model_config, self.replay,
                num_envs=cfg.num_envs_per_worker, worker_index=i, seed=i)
            for i in range(cfg.num_rollout_workers)]
        self.params = self.module.init(jax.random.PRNGKey(cfg.seed))
        # Real copies, not identity: params buffers are DONATED on update,
        # and an aliasing target would hold invalidated buffers.
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr))
        self._opt_state = self._optimizer.init(self.params)
        module = self.module

        def _update(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                dqn_loss, has_aux=True)(
                    params, target_params, module, batch,
                    gamma=cfg.gamma, double_q=cfg.double_q)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(_update, donate_argnums=(0, 2))
        self._steps_sampled = 0
        self._steps_since_target_sync = 0
        self._sync_worker_weights()

    def _sync_worker_weights(self):
        w = jax.device_get(self.params)
        ray.get(_bulk_submit([(wk.set_weights, (w,), None)
                              for wk in self.workers]))

    def _epsilon(self) -> float:
        cfg: DQNConfig = self.algo_config
        frac = min(1.0, self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg: DQNConfig = self.algo_config
        # 1. rollouts at the current epsilon -> replay actor
        eps = self._epsilon()
        steps = ray.get([w.sample.remote(cfg.rollout_fragment_length, eps)
                         for w in self.workers])
        self._steps_sampled += sum(steps)
        self._steps_since_target_sync += sum(steps)
        metrics: Dict[str, Any] = {"epsilon": eps,
                                   "num_env_steps_sampled":
                                       self._steps_sampled}
        # 2. learn from prioritized batches once warm
        if self._steps_sampled >= cfg.num_steps_sampled_before_learning:
            losses, qs = [], []
            for _ in range(cfg.num_train_batches_per_step):
                raw = ray.get(self.replay.sample.remote(
                    cfg.train_batch_size, cfg.prioritized_replay_beta))
                if raw is None:
                    break
                batch = {k: jnp.asarray(v) for k, v in raw.items()
                         if k != BATCH_INDEXES}
                self.params, self._opt_state, loss, aux = self._update(
                    self.params, self.target_params, self._opt_state,
                    batch)
                if cfg.prioritized_replay:
                    self.replay.update_priorities.remote(
                        raw[BATCH_INDEXES],
                        np.asarray(aux["td_errors"]))
                losses.append(float(loss))
                qs.append(float(aux["mean_q"]))
            if losses:
                metrics["loss"] = float(np.mean(losses))
                metrics["mean_q"] = float(np.mean(qs))
            # 3. periodic hard target sync
            if self._steps_since_target_sync >= \
                    cfg.target_network_update_freq:
                self.target_params = jax.tree.map(jnp.copy, self.params)
                self._steps_since_target_sync = 0
            self._sync_worker_weights()
        returns = []
        for w in self.workers:
            try:
                returns.extend(ray.get(w.episode_returns.remote()))
            except Exception:
                pass
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
            metrics["episodes_this_iter"] = len(returns)
        return metrics

    def save_checkpoint(self):
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.target_params),
                "opt_state": jax.device_get(self._opt_state),
                "steps": self._steps_sampled}

    def load_checkpoint(self, state):
        self.params = jax.device_put(state["params"])
        self.target_params = jax.device_put(state["target_params"])
        self._opt_state = jax.device_put(state["opt_state"])
        self._steps_sampled = state.get("steps", 0)
        self._sync_worker_weights()

    def cleanup(self):
        for w in self.workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        try:
            ray.kill(self.replay)
        except Exception:
            pass
