"""SampleBatch: dict-of-arrays experience container.

Reference: ``rllib/policy/sample_batch.py`` — same core surface (column
access, len, concat, minibatch iteration, shuffle) minus the torch/tf
interop.  Arrays are numpy on the rollout side; the learner device_puts
once per update (single host->TPU transfer per train step).
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "new_obs"
LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        idx = rng.permutation(len(self))
        return SampleBatch({k: v[idx] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = len(self)
        for start in range(0, n - size + 1, size):
            yield SampleBatch({k: v[start:start + size]
                               for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})


def concat_batches(batches: List[SampleBatch]) -> SampleBatch:
    keys = batches[0].keys()
    return SampleBatch({k: np.concatenate([b[k] for b in batches])
                        for k in keys})
