"""PPO: synchronous on-policy training.

Reference: ``rllib/algorithms/ppo/ppo.py:343`` (``training_step`` :384):
synchronous_parallel_sample from the worker fleet -> learner update ->
weight sync (:447).  The loss is the clipped-surrogate + value + entropy
objective of ``ppo_torch_policy.py``, expressed once in JAX; SGD epochs /
minibatching happen driver-side, each minibatch one jitted update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ADVANTAGES, LOGP, OBS, VALUE_TARGETS, SampleBatch,
)


def ppo_loss(params, module, batch, *, clip: float = 0.2,
             vf_coef: float = 0.5, ent_coef: float = 0.0):
    logits, values = module.apply(params, batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][:, None].astype(jnp.int32), axis=-1)[:, 0]
    ratio = jnp.exp(logp - batch[LOGP])
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    surr = jnp.minimum(ratio * adv,
                       jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    pi_loss = -jnp.mean(surr)
    vf_loss = jnp.mean((values - batch[VALUE_TARGETS]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                  "entropy": entropy,
                  "kl": jnp.mean(batch[LOGP] - logp)}


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_sgd_iter = 6
        self.sgd_minibatch_size = 128
        self.lam = 0.95
        self.grad_clip = 0.5

    @property
    def algo_class(self):
        return PPO


class PPO(Algorithm):
    config_class = PPOConfig

    def _setup(self, cfg: PPOConfig):
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close() if hasattr(env, "close") else None
        model_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        self.workers = WorkerSet(
            cfg.env_maker, model_config, cfg.num_rollout_workers,
            cfg.num_envs_per_worker, gamma=cfg.gamma, lam=cfg.lam)
        module = ActorCriticMLP(**model_config)

        def loss(params, mod, batch):
            return ppo_loss(params, mod, batch, clip=cfg.clip_param,
                            vf_coef=cfg.vf_loss_coeff,
                            ent_coef=cfg.entropy_coeff)

        def make_learner():
            return Learner(module, loss, optimizer=optax.chain(
                optax.clip_by_global_norm(cfg.grad_clip),
                optax.adam(cfg.lr)), seed=cfg.seed)

        self.learner_group = LearnerGroup(
            make_learner, remote=cfg.remote_learner,
            num_tpus=cfg.learner_num_tpus)
        self.workers.sync_weights(self.learner_group.get_weights())
        self._rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg: PPOConfig = self.algo_config
        batch = self.workers.sample_sync(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {}
        if len(batch) == 0:
            # every worker failed this round; fleet was rebuilt — skip update
            self.workers.sync_weights(self.learner_group.get_weights())
            return {"num_env_steps_sampled": 0}
        for _ in range(cfg.num_sgd_iter):
            shuffled = batch.shuffle(self._rng)
            mb_size = min(cfg.sgd_minibatch_size, len(shuffled))
            for mb in shuffled.minibatches(mb_size):
                metrics = self.learner_group.update(mb)
        self.workers.sync_weights(self.learner_group.get_weights())
        returns = self.workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
            metrics["episodes_this_iter"] = len(returns)
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics

    def save_checkpoint(self):
        return self.learner_group.state()

    def load_checkpoint(self, state):
        self.learner_group.load_state(state)
        self.workers.sync_weights(self.learner_group.get_weights())

    def cleanup(self):
        self.workers.stop()
