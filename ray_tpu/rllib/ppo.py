"""PPO: synchronous on-policy training.

Reference: ``rllib/algorithms/ppo/ppo.py:343`` (``training_step`` :384):
synchronous_parallel_sample from the worker fleet -> learner update ->
weight sync (:447).  The loss is the clipped-surrogate + value + entropy
objective of ``ppo_torch_policy.py``, expressed once in JAX; SGD epochs /
minibatching happen driver-side, each minibatch one jitted update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ADVANTAGES, LOGP, OBS, VALUE_TARGETS, SampleBatch,
)


def ppo_loss(params, module, batch, *, clip: float = 0.2,
             vf_coef: float = 0.5, ent_coef: float = 0.0):
    logits, values = module.apply(params, batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][:, None].astype(jnp.int32), axis=-1)[:, 0]
    ratio = jnp.exp(logp - batch[LOGP])
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    surr = jnp.minimum(ratio * adv,
                       jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    pi_loss = -jnp.mean(surr)
    vf_loss = jnp.mean((values - batch[VALUE_TARGETS]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                  "entropy": entropy,
                  "kl": jnp.mean(batch[LOGP] - logp)}


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_sgd_iter = 6
        self.sgd_minibatch_size = 128
        self.lam = 0.95
        self.grad_clip = 0.5

    @property
    def algo_class(self):
        return PPO


class PPO(Algorithm):
    config_class = PPOConfig

    def _setup(self, cfg: PPOConfig):
        if cfg.policies:
            self._setup_multi_agent(cfg)
            return
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close() if hasattr(env, "close") else None
        model_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        self.workers = WorkerSet(
            cfg.env_maker, model_config, cfg.num_rollout_workers,
            cfg.num_envs_per_worker, gamma=cfg.gamma, lam=cfg.lam)
        module = ActorCriticMLP(**model_config)

        def loss(params, mod, batch):
            return ppo_loss(params, mod, batch, clip=cfg.clip_param,
                            vf_coef=cfg.vf_loss_coeff,
                            ent_coef=cfg.entropy_coeff)

        def make_learner():
            return Learner(module, loss, optimizer=optax.chain(
                optax.clip_by_global_norm(cfg.grad_clip),
                optax.adam(cfg.lr)), seed=cfg.seed)

        self.learner_group = LearnerGroup(
            make_learner, remote=cfg.remote_learner,
            num_tpus=cfg.learner_num_tpus,
            num_learners=cfg.num_learners)
        self.workers.sync_weights(self.learner_group.get_weights())
        self._rng = np.random.default_rng(cfg.seed)

    def _setup_multi_agent(self, cfg: PPOConfig):
        """Per-policy Learners + policy-mapped rollouts (reference:
        multi-agent PPO through the Learner stack — one LearnerGroup per
        policy in learner_group.py; here one Learner per policy, each a
        single jitted update)."""
        from ray_tpu.rllib.multi_agent import MultiAgentWorkerSet

        if cfg.remote_learner:
            raise NotImplementedError(
                "remote_learner is not supported in multi-agent mode; "
                "the per-policy learners run in-driver (use "
                "num_learners to shard their updates over a mesh)")
        env = cfg.env_maker()
        default_model = None
        if any(mc is None for mc in cfg.policies.values()):
            obs_dim = int(np.prod(env.observation_space.shape))
            num_actions = int(env.action_space.n)
            default_model = {
                "obs_dim": obs_dim, "num_actions": num_actions,
                "hidden": tuple(cfg.model.get("hidden", (64, 64)))}
        env.close() if hasattr(env, "close") else None
        model_configs = {pid: (dict(mc) if mc is not None
                               else dict(default_model))
                         for pid, mc in cfg.policies.items()}
        mapping = cfg.policy_mapping_fn or (lambda aid: next(
            iter(model_configs)))
        self.ma_workers = MultiAgentWorkerSet(
            cfg.env_maker, model_configs, mapping,
            cfg.num_rollout_workers, gamma=cfg.gamma, lam=cfg.lam)

        def make_loss():
            def loss(params, mod, batch):
                return ppo_loss(params, mod, batch, clip=cfg.clip_param,
                                vf_coef=cfg.vf_loss_coeff,
                                ent_coef=cfg.entropy_coeff)
            return loss

        # num_learners shards every policy's update over one shared dp
        # mesh (policies update sequentially; each update data-parallel).
        mesh = (LearnerGroup.make_dp_mesh(cfg.num_learners)
                if cfg.num_learners and cfg.num_learners > 1 else None)
        self.learners: Dict[str, Learner] = {}
        for i, (pid, mc) in enumerate(model_configs.items()):
            self.learners[pid] = Learner(
                ActorCriticMLP(**mc), make_loss(),
                optimizer=optax.chain(
                    optax.clip_by_global_norm(cfg.grad_clip),
                    optax.adam(cfg.lr)),
                seed=cfg.seed + i, mesh=mesh)
        self.ma_workers.sync_weights(
            {pid: lr.get_weights() for pid, lr in self.learners.items()})
        self._rng = np.random.default_rng(cfg.seed)

    def _training_step_multi_agent(self) -> Dict[str, Any]:
        cfg: PPOConfig = self.algo_config
        ma_batch = self.ma_workers.sample_sync(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {}
        for pid, batch in ma_batch.items():
            if not len(batch):
                continue
            pm: Dict[str, Any] = {}  # num_sgd_iter=0 must not NameError
            for _ in range(cfg.num_sgd_iter):
                shuffled = batch.shuffle(self._rng)
                mb_size = min(cfg.sgd_minibatch_size, len(shuffled))
                for mb in shuffled.minibatches(mb_size):
                    pm = self.learners[pid].update(mb)
            metrics.update({f"{pid}/{k}": v for k, v in pm.items()})
        self.ma_workers.sync_weights(
            {pid: lr.get_weights() for pid, lr in self.learners.items()})
        returns = self.ma_workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
            metrics["episodes_this_iter"] = len(returns)
        metrics["num_env_steps_sampled"] = ma_batch.env_steps()
        metrics["num_agent_steps_sampled"] = ma_batch.agent_steps()
        return metrics

    def training_step(self) -> Dict[str, Any]:
        cfg: PPOConfig = self.algo_config
        if cfg.policies:
            return self._training_step_multi_agent()
        batch = self.workers.sample_sync(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {}
        if len(batch) == 0:
            # every worker failed this round; fleet was rebuilt — skip update
            self.workers.sync_weights(self.learner_group.get_weights())
            return {"num_env_steps_sampled": 0}
        for _ in range(cfg.num_sgd_iter):
            shuffled = batch.shuffle(self._rng)
            mb_size = min(cfg.sgd_minibatch_size, len(shuffled))
            for mb in shuffled.minibatches(mb_size):
                metrics = self.learner_group.update(mb)
        self.workers.sync_weights(self.learner_group.get_weights())
        returns = self.workers.episode_returns()
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
            metrics["episodes_this_iter"] = len(returns)
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics

    def save_checkpoint(self):
        if self.algo_config.policies:
            return {pid: lr.state() for pid, lr in self.learners.items()}
        return self.learner_group.state()

    def load_checkpoint(self, state):
        if self.algo_config.policies:
            for pid, s in state.items():
                self.learners[pid].load_state(s)
            self.ma_workers.sync_weights(
                {pid: lr.get_weights()
                 for pid, lr in self.learners.items()})
            return
        self.learner_group.load_state(state)
        self.workers.sync_weights(self.learner_group.get_weights())

    def cleanup(self):
        if self.algo_config.policies:
            self.ma_workers.stop()
        else:
            self.workers.stop()
