"""ray_tpu.rllib — RL training library (RLlib equivalent, second north-star).

Reference: ``rllib/`` (SURVEY.md §2.4, 175k LoC).  The TPU build implements
the *new Learner stack* the reference was migrating to (``rllib/core/learner``,
SURVEY.md: "the TPU build should implement this stack rather than the legacy
Policy-GPU path"): CPU rollout-worker actors feed a JAX Learner whose update
is one jitted program on the TPU mesh.  Algorithms: PPO (sync on-policy) and
IMPALA (async, V-trace in XLA) — the reference's two flagship algorithms.
"""

from ray_tpu.rllib.sample_batch import SampleBatch, concat_batches
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import RolloutWorker, WorkerSet
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.impala import Impala, ImpalaConfig

__all__ = [
    "SampleBatch", "concat_batches", "ActorCriticMLP", "RolloutWorker",
    "WorkerSet", "Learner", "LearnerGroup", "Algorithm", "AlgorithmConfig",
    "PPO", "PPOConfig", "Impala", "ImpalaConfig",
]
