"""ray_tpu.rllib — RL training library (RLlib equivalent, second north-star).

Reference: ``rllib/`` (SURVEY.md §2.4, 175k LoC).  The TPU build implements
the *new Learner stack* the reference was migrating to (``rllib/core/learner``,
SURVEY.md: "the TPU build should implement this stack rather than the legacy
Policy-GPU path"): CPU rollout-worker actors feed a JAX Learner whose update
is one jitted program on the TPU mesh.  Algorithms: PPO (sync on-policy),
IMPALA (async, V-trace in XLA), and DQN (off-policy, prioritized replay +
double-Q + target network, the Ape-X worker->replay-actor arrangement).
"""

from ray_tpu.rllib.sample_batch import SampleBatch, concat_batches
from ray_tpu.rllib.models import ActorCriticMLP
from ray_tpu.rllib.rollout_worker import RolloutWorker, WorkerSet
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import VectorEnv
from ray_tpu.rllib.replay_buffers import (
    PrioritizedReplayBuffer, ReplayActor, ReplayBuffer,
)
from ray_tpu.rllib.multi_agent import (
    MultiAgentBatch, MultiAgentEnv, MultiAgentRolloutWorker,
    MultiAgentWorkerSet,
)
from ray_tpu.rllib.offline import (
    BC, BCConfig, CQL, CQLConfig, ImportanceSampling, JsonReader,
    JsonWriter, MARWIL, MARWILConfig, WeightedImportanceSampling,
)

__all__ = [
    "SampleBatch", "concat_batches", "ActorCriticMLP", "RolloutWorker",
    "WorkerSet", "Learner", "LearnerGroup", "Algorithm", "AlgorithmConfig",
    "PPO", "PPOConfig", "Impala", "ImpalaConfig", "DQN", "DQNConfig",
    "VectorEnv", "ReplayBuffer", "PrioritizedReplayBuffer", "ReplayActor",
    "MultiAgentEnv", "MultiAgentBatch", "MultiAgentRolloutWorker",
    "MultiAgentWorkerSet", "BC", "BCConfig", "MARWIL", "MARWILConfig",
    "CQL", "CQLConfig", "JsonReader", "JsonWriter", "ImportanceSampling",
    "WeightedImportanceSampling",
]
