"""Vectorized environment wrappers.

Reference: ``rllib/env/vector_env.py`` (VectorEnv / VectorEnvWrapper) — N
sub-environments stepped as one batched env with auto-reset, so policy
forward passes batch across envs (the rollout hot loop's vectorization).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np


class VectorEnv:
    """Synchronous vectorization over gymnasium-style envs with auto-reset.

    ``vector_step`` returns the *pre-reset* terminal observation in
    ``final_obs`` for bootstrapping (the gymnasium autoreset convention),
    while ``obs`` always holds the observation to act on next.
    """

    def __init__(self, env_maker: Callable[[], Any], num_envs: int,
                 seed: Optional[int] = None):
        self.envs: List[Any] = [env_maker() for _ in range(num_envs)]
        self.num_envs = num_envs
        first = self.envs[0]
        self.observation_space = first.observation_space
        self.action_space = first.action_space
        self._seed = seed

    def vector_reset(self) -> np.ndarray:
        obs = []
        for i, e in enumerate(self.envs):
            kw = {}
            if self._seed is not None:
                kw["seed"] = self._seed + i
            obs.append(e.reset(**kw)[0])
        return np.stack(obs).astype(np.float32)

    def vector_step(self, actions) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray, np.ndarray,
                                            np.ndarray, list]:
        """-> (next_obs [auto-reset], rewards, terminated, truncated,
        final_obs, infos).  Terminated and truncated stay separate — value
        bootstrapping must continue through time-limit truncations
        (the classic time-limit bias; the reference carries both flags)."""
        discrete = hasattr(self.action_space, "n")
        obs, rews, terms, truncs, finals, infos = [], [], [], [], [], []
        for e, a in zip(self.envs, actions):
            if discrete and (np.isscalar(a) or getattr(a, "ndim", 1) == 0):
                a = int(a)
            o, r, term, trunc, info = e.step(a)
            finals.append(o)
            if term or trunc:
                o = e.reset()[0]
            obs.append(o)
            rews.append(r)
            terms.append(bool(term))
            truncs.append(bool(trunc))
            infos.append(info)
        return (np.stack(obs).astype(np.float32),
                np.asarray(rews, np.float32),
                np.asarray(terms, bool),
                np.asarray(truncs, bool),
                np.stack(finals).astype(np.float32),
                infos)

    def close(self):
        for e in self.envs:
            if hasattr(e, "close"):
                try:
                    e.close()
                except Exception:
                    pass
