"""Algorithm base: every RL algorithm is a Tune Trainable.

Reference: ``rllib/algorithms/algorithm.py:146`` (``Algorithm(Trainable)``,
``setup`` :478, ``step`` :731) + the 3239-LoC fluent ``AlgorithmConfig`` —
``config.build().train()`` and ``tune.run(PPO, config=...)`` both work, and
``train()`` is inherited from the Tune Trainable
(``python/ray/tune/trainable/trainable.py:343``).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional

from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent builder (reference: rllib/algorithms/algorithm_config.py)."""

    algo_class: Optional[type] = None

    def __init__(self):
        self.env_maker: Optional[Callable] = None
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 200
        self.gamma = 0.99
        self.lr = 3e-4
        self.train_batch_size = 4000
        self.model = {"hidden": (64, 64)}
        self.seed = 0
        self.learner_num_tpus = 0
        self.remote_learner = False
        self.num_learners = 0
        # Multi-agent (reference: config.multi_agent(...)): empty =
        # single-agent.
        self.policies: Dict[str, Any] = {}
        self.policy_mapping_fn: Optional[Callable] = None

    # -- fluent sections (reference: .environment/.rollouts/.training) ----
    def environment(self, env_maker: Callable) -> "AlgorithmConfig":
        self.env_maker = env_maker
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def multi_agent(self, *, policies: Optional[Dict[str, Any]] = None,
                    policy_mapping_fn: Optional[Callable] = None
                    ) -> "AlgorithmConfig":
        """Reference: AlgorithmConfig.multi_agent (algorithm_config.py) —
        ``policies`` maps policy_id -> model-config dict (or None to
        infer from the env's spaces); ``policy_mapping_fn(agent_id) ->
        policy_id`` routes agents."""
        if policies is not None:
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def resources(self, *, learner_num_tpus: Optional[int] = None,
                  remote_learner: Optional[bool] = None,
                  num_learners: Optional[int] = None
                  ) -> "AlgorithmConfig":
        if learner_num_tpus is not None:
            self.learner_num_tpus = learner_num_tpus
        if remote_learner is not None:
            self.remote_learner = remote_learner
        if num_learners is not None:
            # num_learners>1 data-parallelizes the update over an
            # N-device mesh 'dp' axis (learner_group.py:51 scaling
            # config; here scaling = sharding, not actor count).
            self.num_learners = num_learners
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config class has no algo_class")
        return self.algo_class(config={"__algo_config__": self})


class Algorithm(Trainable):
    """config dict may carry {"__algo_config__": AlgorithmConfig} (built
    path) or plain keys overriding the default config (tune path)."""

    config_class: type = AlgorithmConfig

    def setup(self, config: Dict[str, Any]):
        ac = config.get("__algo_config__")
        if ac is None:
            ac = self.config_class()
            for k, v in config.items():
                if hasattr(ac, k):
                    setattr(ac, k, v)
        self.algo_config = ac
        self._setup(ac)

    def _setup(self, cfg: AlgorithmConfig):
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        return self.training_step()

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError
