"""V-trace off-policy correction, as one XLA program.

Reference: ``rllib/algorithms/impala/vtrace_torch.py`` (itself from the
IMPALA paper, Espeholt et al. 2018).  The recurrence

    vs_t = V(x_t) + delta_t + gamma * c_t * (vs_{t+1} - V(x_{t+1}))
    delta_t = rho_t * (r_t + gamma * V(x_{t+1}) - V(x_t))

is a backward ``lax.scan`` — sequential in T but batched over B on the
VPU/MXU; no Python loops, fully differentiable (targets are
stop_gradient'ed as in the reference).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class VTraceReturns(NamedTuple):
    vs: jax.Array           # (T, B) value targets
    pg_advantages: jax.Array  # (T, B) policy-gradient advantages


def vtrace(behavior_logp: jax.Array, target_logp: jax.Array,
           rewards: jax.Array, values: jax.Array,
           bootstrap_value: jax.Array, discounts: jax.Array,
           clip_rho_threshold: float = 1.0,
           clip_c_threshold: float = 1.0) -> VTraceReturns:
    """All inputs (T, B) time-major; bootstrap_value (B,);
    discounts = gamma * (1 - done)."""
    ratio = jnp.exp(target_logp - behavior_logp)
    rho = jnp.minimum(clip_rho_threshold, ratio)
    c = jnp.minimum(clip_c_threshold, ratio)
    values_tp1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = rho * (rewards + discounts * values_tp1 - values)

    def body(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        body, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, c), reverse=True)
    vs = values + vs_minus_v
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho * (rewards + discounts * vs_tp1 - values)
    return VTraceReturns(vs=jax.lax.stop_gradient(vs),
                         pg_advantages=jax.lax.stop_gradient(pg_adv))
