"""RolloutWorker: CPU actor stepping environments with the current policy.

Reference: ``rllib/evaluation/rollout_worker.py:166`` (``sample`` :886) +
``worker_set.py`` (fault-tolerant fleet) + GAE postprocessing
(``rllib/evaluation/postprocessing.py``).  TPU division of labor: rollout
workers never touch the TPU — they run numpy/CPU-jax policy forward passes
and ship SampleBatches; the learner owns the chips.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit
from ray_tpu.rllib.models import ActorCriticMLP, sample_action
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, LOGP, NEXT_OBS, OBS, REWARDS, SampleBatch, VF_PREDS,
    ADVANTAGES, VALUE_TARGETS, concat_batches,
)


def compute_gae(batch: SampleBatch, last_value: float, gamma: float,
                lam: float) -> SampleBatch:
    """Generalized advantage estimation (reference:
    rllib/evaluation/postprocessing.py compute_advantages)."""
    rewards = batch[REWARDS]
    values = batch[VF_PREDS]
    dones = batch[DONES]
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    last = 0.0
    next_value = last_value
    for t in reversed(range(n)):
        nonterminal = 1.0 - float(dones[t])
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last = delta + gamma * lam * nonterminal * last
        adv[t] = last
        next_value = values[t]
    batch[ADVANTAGES] = adv
    batch[VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch


@ray.remote
class RolloutWorker:
    def __init__(self, env_maker, model_config: Dict[str, Any],
                 worker_index: int = 0, num_envs: int = 1,
                 gamma: float = 0.99, lam: float = 0.95,
                 seed: Optional[int] = None):
        import jax
        self._envs = [env_maker() for _ in range(num_envs)]
        self._model = ActorCriticMLP(**model_config)
        self._params = None
        self._rng = np.random.default_rng(
            seed if seed is not None else worker_index)
        self._gamma, self._lam = gamma, lam
        self._obs = [e.reset(seed=int(self._rng.integers(2**31)))[0]
                     for e in self._envs]
        self._ep_returns = [0.0] * num_envs
        self._completed_returns: List[float] = []
        self._apply = jax.jit(self._model.apply)

    def set_weights(self, weights):
        self._params = weights
        return True

    def get_weights(self):
        return self._params

    def sample(self, num_steps: int) -> SampleBatch:
        """Collect ``num_steps`` per env; returns a GAE-postprocessed batch
        (reference: SyncSampler, evaluation/sampler.py:144)."""
        assert self._params is not None, "set_weights first"
        per_env: List[Dict[str, list]] = [
            {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGP, VF_PREDS,
                             NEXT_OBS)}
            for _ in self._envs]
        for _ in range(num_steps):
            obs_arr = np.stack(self._obs).astype(np.float32)
            logits, values = self._apply(self._params, obs_arr)
            logits = np.asarray(logits)
            values = np.asarray(values)
            acts, logp = sample_action(logits, self._rng)
            for i, env in enumerate(self._envs):
                nobs, rew, term, trunc, _ = env.step(int(acts[i]))
                done = term or trunc
                buf = per_env[i]
                buf[OBS].append(self._obs[i])
                buf[ACTIONS].append(acts[i])
                buf[REWARDS].append(rew)
                buf[DONES].append(done)
                buf[LOGP].append(logp[i])
                buf[VF_PREDS].append(values[i])
                buf[NEXT_OBS].append(nobs)  # pre-reset obs for bootstrap
                self._ep_returns[i] += rew
                if done:
                    self._completed_returns.append(self._ep_returns[i])
                    self._ep_returns[i] = 0.0
                    nobs = env.reset()[0]
                self._obs[i] = nobs
        batches = []
        obs_arr = np.stack(self._obs).astype(np.float32)
        _, bootstrap = self._apply(self._params, obs_arr)
        bootstrap = np.asarray(bootstrap)
        for i, buf in enumerate(per_env):
            b = SampleBatch({
                OBS: np.asarray(buf[OBS], np.float32),
                ACTIONS: np.asarray(buf[ACTIONS], np.int32),
                REWARDS: np.asarray(buf[REWARDS], np.float32),
                DONES: np.asarray(buf[DONES], bool),
                LOGP: np.asarray(buf[LOGP], np.float32),
                VF_PREDS: np.asarray(buf[VF_PREDS], np.float32),
                NEXT_OBS: np.asarray(buf[NEXT_OBS], np.float32),
            })
            last_v = 0.0 if buf[DONES] and buf[DONES][-1] else \
                float(bootstrap[i])
            batches.append(compute_gae(b, last_v, self._gamma, self._lam))
        return concat_batches(batches)

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._completed_returns)
        if clear:
            self._completed_returns.clear()
        return out


class WorkerSet:
    """Fault-tolerant rollout fleet (reference:
    rllib/evaluation/worker_set.py — recreate failed workers).

    Subclasses swap the worker factory (``_make``) and batch merge
    (``_concat``/``_empty``) — the multi-agent fleet reuses the whole
    recreate/sample/returns machinery this way."""

    def __init__(self, env_maker, model_config, num_workers: int,
                 num_envs_per_worker: int = 1, gamma: float = 0.99,
                 lam: float = 0.95, recreate_failed: bool = True):
        self._make = lambda idx: RolloutWorker.options(num_cpus=1).remote(
            env_maker, model_config, worker_index=idx,
            num_envs=num_envs_per_worker, gamma=gamma, lam=lam, seed=idx)
        self._workers = [self._make(i) for i in range(num_workers)]
        self._recreate = recreate_failed

    @staticmethod
    def _concat(batches):
        return concat_batches(batches)

    @staticmethod
    def _empty():
        return SampleBatch()

    @property
    def workers(self):
        return list(self._workers)

    def recreate(self, idx: int):
        """Replace a dead worker in place; returns the new handle."""
        try:
            ray.kill(self._workers[idx])
        except Exception:
            pass
        self._workers[idx] = self._make(idx)
        return self._workers[idx]

    def sync_weights(self, weights):
        ray.get(_bulk_submit([(w.set_weights, (weights,), None)
                              for w in self._workers]))

    def sample_sync(self, steps_per_worker: int):
        """synchronous_parallel_sample (reference:
        rllib/execution/rollout_ops.py:21) with worker recreation.  The
        whole collection wave goes out in one dispatch pass."""
        futs = _bulk_submit([(w.sample, (steps_per_worker,), None)
                             for w in self._workers])
        out = []
        for i, fut in enumerate(futs):
            try:
                out.append(ray.get(fut))
            except Exception:
                if not self._recreate:
                    raise
                self.recreate(i)
        return self._concat(out) if out else self._empty()

    def episode_returns(self) -> List[float]:
        rets = []
        futs = _bulk_submit([(w.episode_returns, (), None)
                             for w in self._workers])
        for fut in futs:
            try:
                rets.extend(ray.get(fut))
            except Exception:
                pass
        return rets

    def stop(self):
        for w in self._workers:
            try:
                ray.kill(w)
            except Exception:
                pass
