"""SAC: off-policy maximum-entropy RL for continuous actions.

Reference: ``rllib/algorithms/sac/sac.py`` + ``sac_torch_policy.py`` —
twin Q critics with polyak-averaged targets, a tanh-squashed Gaussian
policy trained by the reparameterization trick, and a learned entropy
temperature alpha against a target entropy of ``-act_dim``.  The TPU
split matches DQN here: CPU rollout workers act stochastically and push
transitions to the ReplayActor; the whole update (both critics, actor,
alpha, polyak) is ONE jitted program on the device.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import VectorEnv
from ray_tpu.rllib.replay_buffers import BATCH_INDEXES, ReplayActor
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, NEXT_OBS, OBS, REWARDS,
)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _mlp_init(key, sizes):
    params = []
    for a, b in zip(sizes[:-1], sizes[1:]):
        key, k = jax.random.split(key)
        params.append({"w": jax.random.normal(k, (a, b))
                       * np.sqrt(2.0 / a),
                       "b": jnp.zeros((b,))})
    return params


def _mlp_apply(params, x, final_tanh=False):
    for i, lyr in enumerate(params):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class SquashedGaussianPolicy:
    """obs -> (mu, log_std); actions tanh-squashed into [low, high]
    (reference: SquashedGaussian distribution in rllib/models)."""

    def __init__(self, obs_dim: int, act_dim: int, low, high,
                 hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hidden = tuple(hidden)
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def init(self, key):
        return _mlp_init(key, (self.obs_dim,) + self.hidden
                         + (2 * self.act_dim,))

    def sample(self, params, obs, key):
        """Reparameterized (action, logp) with the tanh change-of-
        variables correction."""
        out = _mlp_apply(params, obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre = mu + std * eps
        # N(mu, std) log-density of pre
        logp = jnp.sum(
            -0.5 * ((pre - mu) / std) ** 2 - log_std
            - 0.5 * np.log(2 * np.pi), axis=-1)
        # tanh squash correction: log det |d tanh / dx| summed over dims
        logp -= jnp.sum(2.0 * (np.log(2.0) - pre
                               - jax.nn.softplus(-2.0 * pre)), axis=-1)
        squashed = jnp.tanh(pre)
        scale = (self.high - self.low) / 2.0
        mid = (self.high + self.low) / 2.0
        action = squashed * scale + mid
        # affine-rescale log-det (constant; keeps alpha's entropy target
        # in the true action measure)
        logp -= float(np.sum(np.log(scale + 1e-8)))
        return action, logp


class QNetwork:
    def __init__(self, obs_dim: int, act_dim: int, hidden=(64, 64)):
        self.sizes = (obs_dim + act_dim,) + tuple(hidden) + (1,)

    def init(self, key):
        return _mlp_init(key, self.sizes)

    def apply(self, params, obs, act):
        return _mlp_apply(params, jnp.concatenate([obs, act], -1))[..., 0]


@ray.remote
class SACRolloutWorker:
    """Stochastic continuous-action rollouts -> replay (reference:
    SAC's default sample collection; exploration IS the policy)."""

    def __init__(self, env_maker, policy_config: Dict[str, Any],
                 replay_actor, num_envs: int = 1, worker_index: int = 0,
                 warmup_uniform_steps: int = 500):
        self._venv = VectorEnv(env_maker, num_envs, seed=worker_index)
        self._policy = SquashedGaussianPolicy(**policy_config)
        self._params = None
        self._replay = replay_actor
        self._key = jax.random.PRNGKey(worker_index)
        self._obs = self._venv.vector_reset()
        self._sample = jax.jit(self._policy.sample)
        self._ep_returns = np.zeros(num_envs)
        self._completed: List[float] = []
        self._steps = 0
        self._warmup = warmup_uniform_steps
        self._rng = np.random.default_rng(worker_index)

    def set_weights(self, weights):
        self._params = jax.device_put(weights)
        return True

    def sample(self, num_steps: int) -> int:
        n = self._venv.num_envs
        cols = {k: [] for k in (OBS, ACTIONS, REWARDS, NEXT_OBS, DONES)}
        for _ in range(max(1, num_steps // n)):
            if self._steps < self._warmup or self._params is None:
                act = self._rng.uniform(
                    self._policy.low, self._policy.high,
                    size=(n, self._policy.act_dim)).astype(np.float32)
            else:
                self._key, k = jax.random.split(self._key)
                act, _ = self._sample(self._params,
                                      jnp.asarray(self._obs), k)
                act = np.asarray(act)
            next_obs, rews, terms, truncs, finals, _ = \
                self._venv.vector_step(act)
            cols[OBS].append(self._obs.copy())
            cols[ACTIONS].append(act)
            cols[REWARDS].append(rews)
            cols[NEXT_OBS].append(finals)  # pre-reset obs for bootstrap
            # DONES carries TERMINATION only: truncation still bootstraps
            # (time-limit bias; same convention as the DQN worker).
            cols[DONES].append(terms.astype(np.float32))
            self._ep_returns += rews
            for i in np.nonzero(terms | truncs)[0]:
                self._completed.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            self._obs = next_obs
            self._steps += n
        ray.get(self._replay.add.remote({
            OBS: np.concatenate(cols[OBS]).astype(np.float32),
            ACTIONS: np.concatenate(cols[ACTIONS]).astype(np.float32),
            REWARDS: np.concatenate(cols[REWARDS]).astype(np.float32),
            NEXT_OBS: np.concatenate(cols[NEXT_OBS]).astype(np.float32),
            DONES: np.concatenate(cols[DONES]).astype(np.float32)}))
        return max(1, num_steps // n) * n

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._completed)
        if clear:
            self._completed = []
        return out


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.tau = 0.005
        self.target_entropy = None  # default: -act_dim
        self.num_steps_sampled_before_learning = 600
        self.num_train_batches_per_step = 32
        self.warmup_uniform_steps = 600
        self.grad_clip = 40.0

    @property
    def algo_class(self):
        return SAC


class SAC(Algorithm):
    config_class = SACConfig

    def _setup(self, cfg: SACConfig):
        env = cfg.env_maker()
        obs_dim = int(np.prod(env.observation_space.shape))
        act_space = env.action_space
        act_dim = int(np.prod(act_space.shape))
        low, high = act_space.low, act_space.high
        if hasattr(env, "close"):
            env.close()
        hidden = tuple(cfg.model.get("hidden", (64, 64)))
        policy_config = {"obs_dim": obs_dim, "act_dim": act_dim,
                         "low": low, "high": high, "hidden": hidden}
        self.policy = SquashedGaussianPolicy(**policy_config)
        self.q = QNetwork(obs_dim, act_dim, hidden)
        key = jax.random.PRNGKey(cfg.seed)
        kp, k1, k2 = jax.random.split(key, 3)
        self.pi_params = self.policy.init(kp)
        self.q_params = {"q1": self.q.init(k1), "q2": self.q.init(k2)}
        self.q_target = jax.tree.map(jnp.copy, self.q_params)
        self.log_alpha = jnp.zeros(())
        target_entropy = (cfg.target_entropy
                          if cfg.target_entropy is not None
                          else -float(act_dim))

        self._pi_opt = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr))
        self._q_opt = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.critic_lr))
        self._a_opt = optax.adam(cfg.alpha_lr)
        self._pi_state = self._pi_opt.init(self.pi_params)
        self._q_state = self._q_opt.init(self.q_params)
        self._a_state = self._a_opt.init(self.log_alpha)

        policy, q = self.policy, self.q
        tau, gamma = cfg.tau, cfg.gamma

        def critic_loss(q_params, pi_params, q_target, log_alpha, batch,
                        key):
            next_a, next_logp = policy.sample(pi_params, batch[NEXT_OBS],
                                              key)
            tq1 = q.apply(q_target["q1"], batch[NEXT_OBS], next_a)
            tq2 = q.apply(q_target["q2"], batch[NEXT_OBS], next_a)
            alpha = jnp.exp(log_alpha)
            next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch[REWARDS] + gamma * (1 - batch[DONES]) * next_v
            target = jax.lax.stop_gradient(target)
            q1 = q.apply(q_params["q1"], batch[OBS], batch[ACTIONS])
            q2 = q.apply(q_params["q2"], batch[OBS], batch[ACTIONS])
            return (jnp.mean((q1 - target) ** 2)
                    + jnp.mean((q2 - target) ** 2)), jnp.mean(q1)

        def actor_loss(pi_params, q_params, log_alpha, batch, key):
            a, logp = policy.sample(pi_params, batch[OBS], key)
            q1 = q.apply(q_params["q1"], batch[OBS], a)
            q2 = q.apply(q_params["q2"], batch[OBS], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        def alpha_loss(log_alpha, logp):
            return -jnp.mean(log_alpha
                             * jax.lax.stop_gradient(logp
                                                     + target_entropy))

        def update(pi_params, q_params, q_target, log_alpha,
                   pi_state, q_state, a_state, batch, key):
            kc, ka = jax.random.split(key)
            (closs, mean_q), qg = jax.value_and_grad(
                critic_loss, has_aux=True)(q_params, pi_params, q_target,
                                           log_alpha, batch, kc)
            qup, q_state = self._q_opt.update(qg, q_state, q_params)
            q_params = optax.apply_updates(q_params, qup)
            (aloss, logp), pg = jax.value_and_grad(
                actor_loss, has_aux=True)(pi_params, q_params, log_alpha,
                                          batch, ka)
            pup, pi_state = self._pi_opt.update(pg, pi_state, pi_params)
            pi_params = optax.apply_updates(pi_params, pup)
            lloss, lg = jax.value_and_grad(alpha_loss)(log_alpha, logp)
            lup, a_state = self._a_opt.update(lg, a_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, lup)
            q_target = jax.tree.map(
                lambda t, s: (1 - tau) * t + tau * s, q_target, q_params)
            return (pi_params, q_params, q_target, log_alpha, pi_state,
                    q_state, a_state,
                    {"critic_loss": closs, "actor_loss": aloss,
                     "alpha": jnp.exp(log_alpha), "mean_q": mean_q,
                     "entropy": -jnp.mean(logp)})

        self._update = jax.jit(update, donate_argnums=(0, 1, 2, 3, 4,
                                                       5, 6))
        self._key = jax.random.PRNGKey(cfg.seed + 1)

        self.replay = ReplayActor.options(num_cpus=1).remote(
            capacity=cfg.replay_buffer_capacity, prioritized=False,
            seed=cfg.seed)
        self.workers = [
            SACRolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, policy_config, self.replay,
                num_envs=cfg.num_envs_per_worker, worker_index=i,
                warmup_uniform_steps=cfg.warmup_uniform_steps)
            for i in range(cfg.num_rollout_workers)]
        self._steps_sampled = 0
        self._sync_worker_weights()

    def _sync_worker_weights(self):
        w = jax.device_get(self.pi_params)
        ray.get(_bulk_submit([(wk.set_weights, (w,), None)
                              for wk in self.workers]))

    def training_step(self) -> Dict[str, Any]:
        cfg: SACConfig = self.algo_config
        steps = ray.get([w.sample.remote(cfg.rollout_fragment_length)
                         for w in self.workers])
        self._steps_sampled += sum(steps)
        metrics: Dict[str, Any] = {
            "num_env_steps_sampled": self._steps_sampled}
        if self._steps_sampled >= cfg.num_steps_sampled_before_learning:
            aux = None
            for _ in range(cfg.num_train_batches_per_step):
                raw = ray.get(self.replay.sample.remote(
                    cfg.train_batch_size, 0.0))
                if raw is None:
                    break
                batch = {k: jnp.asarray(v) for k, v in raw.items()
                         if k != BATCH_INDEXES and k != "weights"}
                self._key, k = jax.random.split(self._key)
                (self.pi_params, self.q_params, self.q_target,
                 self.log_alpha, self._pi_state, self._q_state,
                 self._a_state, aux) = self._update(
                    self.pi_params, self.q_params, self.q_target,
                    self.log_alpha, self._pi_state, self._q_state,
                    self._a_state, batch, k)
            if aux is not None:
                metrics.update({k: float(v) for k, v in aux.items()})
            self._sync_worker_weights()
        returns = []
        for w in self.workers:
            try:
                returns.extend(ray.get(w.episode_returns.remote()))
            except Exception:
                pass
        if returns:
            metrics["episode_reward_mean"] = float(np.mean(returns))
        return metrics

    def save_checkpoint(self):
        return {"pi": jax.device_get(self.pi_params),
                "q": jax.device_get(self.q_params),
                "qt": jax.device_get(self.q_target),
                "log_alpha": jax.device_get(self.log_alpha),
                "steps": self._steps_sampled}

    def load_checkpoint(self, state):
        self.pi_params = jax.device_put(state["pi"])
        self.q_params = jax.device_put(state["q"])
        self.q_target = jax.device_put(state["qt"])
        self.log_alpha = jax.device_put(state["log_alpha"])
        self._steps_sampled = state.get("steps", 0)
        self._sync_worker_weights()

    def cleanup(self):
        for w in self.workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        try:
            ray.kill(self.replay)
        except Exception:
            pass
