"""Policy networks in functional JAX.

Reference: ``rllib/models/`` (ModelV2/ModelCatalog; the JAX support there is
a 299-LoC stub, ``rllib/models/jax/``).  Here the model zoo is JAX-first:
pure init/apply pairs over param pytrees, shardable with the same logical
axis rules as the LLM family.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class ActorCriticMLP:
    """Shared-nothing actor-critic MLP with categorical policy head."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        def mlp(key, sizes):
            params = []
            for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
                key, k = jax.random.split(key)
                params.append({
                    "w": jax.random.normal(k, (a, b)) * np.sqrt(2.0 / a),
                    "b": jnp.zeros((b,)),
                })
            return params

        k1, k2 = jax.random.split(key)
        pi_sizes = (self.obs_dim,) + self.hidden + (self.num_actions,)
        vf_sizes = (self.obs_dim,) + self.hidden + (1,)
        return {"pi": mlp(k1, pi_sizes), "vf": mlp(k2, vf_sizes)}

    @staticmethod
    def _forward(layers, x):
        for i, lyr in enumerate(layers):
            x = x @ lyr["w"] + lyr["b"]
            if i < len(layers) - 1:
                x = jnp.tanh(x)
        return x

    def apply(self, params, obs) -> Tuple[jax.Array, jax.Array]:
        """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
        logits = self._forward(params["pi"], obs)
        value = self._forward(params["vf"], obs)[..., 0]
        return logits, value


def sample_action(logits: np.ndarray,
                  rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
    """Categorical sample + logp, numpy-side (rollout hot loop)."""
    z = logits - logits.max(axis=-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=-1, keepdims=True)
    cum = np.cumsum(p, axis=-1)
    r = rng.random(size=(len(p), 1))
    acts = np.minimum((r > cum).sum(axis=-1), p.shape[-1] - 1)
    logp = np.log(p[np.arange(len(p)), acts] + 1e-20)
    return acts.astype(np.int32), logp.astype(np.float32)
