"""Learner / LearnerGroup: the gradient-update half of the RL stack.

Reference: ``rllib/core/learner/learner.py:89`` + ``learner_group.py:51`` —
the in-progress "new Learner stack" that decouples updates from rollouts
(SURVEY.md §2.4 says to build this, not the legacy Policy-GPU path).

TPU design: one Learner owns the chips; its ``update(batch)`` is a single
jitted program (loss -> grad -> optax).  Data parallelism over chips comes
from sharding the batch over the mesh 'dp' axis — XLA inserts the gradient
psum, the MultiGPULearnerThread/NCCL machinery of the reference
(``rllib/execution/multi_gpu_learner_thread.py``) has no equivalent because
the compiler owns the overlap.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.sample_batch import SampleBatch


class Learner:
    """Holds params + optimizer; ``update`` jitted once."""

    def __init__(self, module, loss_fn: Callable, *,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 seed: int = 0, mesh=None, batch_spec=None):
        self.module = module
        self._loss_fn = loss_fn
        self._optimizer = optimizer or optax.chain(
            optax.clip_by_global_norm(0.5), optax.adam(3e-4))
        self.params = module.init(jax.random.PRNGKey(seed))
        self._opt_state = self._optimizer.init(self.params)
        self._mesh = mesh
        self._batch_spec = batch_spec

        def _update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(params, module, batch)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics = dict(metrics, total_loss=loss,
                           grad_norm=optax.global_norm(grads))
            return params, opt_state, metrics

        self._update = jax.jit(_update, donate_argnums=(0, 1))

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        dev_batch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self._mesh is not None and self._batch_spec is not None:
            from jax.sharding import NamedSharding
            dev_batch = {
                k: jax.device_put(v, NamedSharding(self._mesh,
                                                   self._batch_spec))
                for k, v in dev_batch.items()}
        self.params, self._opt_state, metrics = self._update(
            self.params, self._opt_state, dev_batch)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.device_put(weights)
        self._opt_state = self._optimizer.init(self.params)

    def state(self):
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self._opt_state)}

    def load_state(self, state):
        self.params = jax.device_put(state["params"])
        self._opt_state = jax.device_put(state["opt_state"])


class LearnerGroup:
    """Reference: rllib/core/learner/learner_group.py:51.  v1 runs the
    learner in-driver (the driver owns the TPU in single-host mode);
    remote=True places it in a dedicated TPU actor."""

    def __init__(self, learner_factory: Callable[[], Learner],
                 remote: bool = False, num_tpus: int = 0):
        self._remote = remote
        if remote:
            import ray_tpu as ray

            @ray.remote
            class _LearnerActor:
                def __init__(self):
                    self.learner = learner_factory()

                def update(self, batch):
                    return self.learner.update(batch)

                def get_weights(self):
                    return self.learner.get_weights()

                def state(self):
                    return self.learner.state()

                def load_state(self, s):
                    return self.learner.load_state(s)

            self._actor = _LearnerActor.options(
                num_tpus=num_tpus, num_cpus=1).remote()
            self._ray = ray
        else:
            self._learner = learner_factory()

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        if self._remote:
            return self._ray.get(self._actor.update.remote(batch))
        return self._learner.update(batch)

    def get_weights(self):
        if self._remote:
            return self._ray.get(self._actor.get_weights.remote())
        return self._learner.get_weights()

    def state(self):
        if self._remote:
            return self._ray.get(self._actor.state.remote())
        return self._learner.state()

    def load_state(self, s):
        if self._remote:
            return self._ray.get(self._actor.load_state.remote(s))
        return self._learner.load_state(s)
