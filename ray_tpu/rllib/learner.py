"""Learner / LearnerGroup: the gradient-update half of the RL stack.

Reference: ``rllib/core/learner/learner.py:89`` + ``learner_group.py:51`` —
the in-progress "new Learner stack" that decouples updates from rollouts
(SURVEY.md §2.4 says to build this, not the legacy Policy-GPU path).

TPU design: one Learner owns the chips; its ``update(batch)`` is a single
jitted program (loss -> grad -> optax).  Data parallelism over chips comes
from sharding the batch over the mesh 'dp' axis — XLA inserts the gradient
psum, the MultiGPULearnerThread/NCCL machinery of the reference
(``rllib/execution/multi_gpu_learner_thread.py``) has no equivalent because
the compiler owns the overlap.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.sample_batch import SampleBatch


class Learner:
    """Holds params + optimizer; ``update`` jitted once.

    With ``mesh``, the update is DATA-PARALLEL over the mesh's ``dp``
    axis: params/opt state live replicated, each batch row-shards over
    dp, and the mean-loss gradient psum is inserted by XLA — this is the
    whole MultiGPULearnerThread/NCCL apparatus of the reference
    (rllib/execution/multi_gpu_learner_thread.py) expressed as sharding
    annotations on one jitted program."""

    def __init__(self, module, loss_fn: Callable, *,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 seed: int = 0, mesh=None, batch_spec=None):
        self.module = module
        self._loss_fn = loss_fn
        self._optimizer = optimizer or optax.chain(
            optax.clip_by_global_norm(0.5), optax.adam(3e-4))
        self.params = module.init(jax.random.PRNGKey(seed))
        self._opt_state = self._optimizer.init(self.params)
        self._mesh = mesh
        if mesh is not None and batch_spec is None:
            from jax.sharding import PartitionSpec
            batch_spec = PartitionSpec("dp")
        self._batch_spec = batch_spec
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            replicated = NamedSharding(mesh, PartitionSpec())
            self.params = jax.device_put(self.params, replicated)
            self._opt_state = jax.device_put(self._opt_state, replicated)

        def _update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(params, module, batch)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics = dict(metrics, total_loss=loss,
                           grad_norm=optax.global_norm(grads))
            return params, opt_state, metrics

        self._update = jax.jit(_update, donate_argnums=(0, 1))

    def _shard_batch(self, dev_batch):
        """device_put each column with its dp sharding.  ``batch_spec``
        may be one PartitionSpec for every column or a callable
        ``(key, value) -> PartitionSpec`` for mixed layouts (IMPALA's
        time-major (T, B) columns + (B,) bootstrap rows).  The sharded
        axis is trimmed to tile over dp."""
        from jax.sharding import NamedSharding, PartitionSpec
        n = self._mesh.shape.get("dp", 1)

        def dp_axis(spec, v):
            return next((i for i, s in enumerate(spec)
                         if s == "dp" and i < v.ndim), None)

        # A batch whose dp axis cannot feed every device runs REPLICATED
        # (correct, just not parallel) — trimming it to zero rows would
        # silently NaN the update.
        replicate = False
        for k, v in dev_batch.items():
            spec = (self._batch_spec(k, v) if callable(self._batch_spec)
                    else self._batch_spec)
            axis = dp_axis(spec, v)
            if axis is not None and v.shape[axis] < n:
                replicate = True
                break
        out = {}
        for k, v in dev_batch.items():
            spec = (self._batch_spec(k, v) if callable(self._batch_spec)
                    else self._batch_spec)
            if replicate:
                spec = PartitionSpec()
            elif n > 1:
                axis = dp_axis(spec, v)
                if axis is not None and v.shape[axis] % n:
                    # Ragged tail cannot tile over dp: drop it (the SGD
                    # minibatcher likewise discards partial minibatches).
                    sl = [slice(None)] * v.ndim
                    sl[axis] = slice(0, v.shape[axis]
                                     - (v.shape[axis] % n))
                    v = v[tuple(sl)]
            out[k] = jax.device_put(v, NamedSharding(self._mesh, spec))
        return out

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        dev_batch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self._mesh is not None:
            dev_batch = self._shard_batch(dev_batch)
        self.params, self._opt_state, metrics = self._update(
            self.params, self._opt_state, dev_batch)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.device_put(weights)
        self._opt_state = self._optimizer.init(self.params)

    def state(self):
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self._opt_state)}

    def load_state(self, state):
        self.params = jax.device_put(state["params"])
        self._opt_state = jax.device_put(state["opt_state"])


class LearnerGroup:
    """Reference: rllib/core/learner/learner_group.py:51 (the scaling
    config's num_learners).  The group's scaling is a MESH, not N actor
    processes: ``num_learners=N`` builds an N-device ``Mesh(('dp',))``
    and the one Learner's update shards over it — batch rows split
    across devices, XLA psums the gradients (SURVEY §2.4's "JAX Learner
    on TPU mesh").  ``remote=True`` additionally places it in a
    dedicated TPU actor."""

    @staticmethod
    def make_dp_mesh(num_learners: int):
        """An N-device ('dp',) mesh over the first N local devices."""
        import numpy as _np
        from jax.sharding import Mesh

        devs = jax.devices()
        if num_learners > len(devs):
            raise ValueError(
                f"num_learners={num_learners} > {len(devs)} devices")
        return Mesh(_np.array(devs[:num_learners]), ("dp",))

    def __init__(self, learner_factory: Callable[[], Learner],
                 remote: bool = False, num_tpus: int = 0,
                 num_learners: int = 0):
        self._remote = remote
        self._num_learners = num_learners

        def build() -> Learner:
            """Factory + optional dp mesh: factories that accept a
            ``mesh`` kwarg get the group's mesh injected (built inside
            the owning process — a remote learner actor builds it over
            ITS visible devices, i.e. its granted TPU chips)."""
            if num_learners and num_learners > 1:
                import inspect

                mesh = LearnerGroup.make_dp_mesh(num_learners)
                try:
                    sig = inspect.signature(learner_factory)
                    if "mesh" in sig.parameters:
                        return learner_factory(mesh=mesh)
                except (TypeError, ValueError):
                    pass
                lr = learner_factory()
                # Factory unaware of meshes: re-home its state onto the
                # group mesh (replicated) and shard batches over dp.
                from jax.sharding import NamedSharding, PartitionSpec
                replicated = NamedSharding(mesh, PartitionSpec())
                lr._mesh = mesh
                lr._batch_spec = PartitionSpec("dp")
                lr.params = jax.device_put(lr.params, replicated)
                lr._opt_state = jax.device_put(lr._opt_state, replicated)
                return lr
            return learner_factory()

        if remote:
            import ray_tpu as ray

            @ray.remote
            class _LearnerActor:
                def __init__(self):
                    self.learner = build()

                def update(self, batch):
                    return self.learner.update(batch)

                def get_weights(self):
                    return self.learner.get_weights()

                def state(self):
                    return self.learner.state()

                def load_state(self, s):
                    return self.learner.load_state(s)

            self._actor = _LearnerActor.options(
                num_tpus=num_tpus, num_cpus=1).remote()
            self._ray = ray
        else:
            self._learner = build()

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        if self._remote:
            return self._ray.get(self._actor.update.remote(batch))
        return self._learner.update(batch)

    def get_weights(self):
        if self._remote:
            return self._ray.get(self._actor.get_weights.remote())
        return self._learner.get_weights()

    def state(self):
        if self._remote:
            return self._ray.get(self._actor.state.remote())
        return self._learner.state()

    def load_state(self, s):
        if self._remote:
            return self._ray.get(self._actor.load_state.remote(s))
        return self._learner.load_state(s)
